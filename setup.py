"""Build glue for the native C++ extension (csrc/).

`pyproject.toml` carries all metadata; this file only declares the extension.
Build in-place with:  python setup.py build_ext --inplace
(dynamo_tpu/native.py auto-attempts this once per checkout).
"""

from setuptools import Extension, setup

setup(
    ext_modules=[
        Extension(
            "dynamo_tpu._native",
            sources=["csrc/native.cpp"],
            include_dirs=["csrc"],
            extra_compile_args=["-O3", "-std=c++17", "-fvisibility=hidden"],
            language="c++",
        )
    ]
)
