// XXH64 — clean-room implementation of the public XXH64 algorithm
// (spec: github.com/Cyan4973/xxHash/blob/dev/doc/xxhash_spec.md).
// Used for chained KV block identity (see dynamo_tpu/tokens). The Python
// fallback (`xxhash.xxh64_intdigest`) is bit-identical by construction.
#pragma once
#include <cstdint>
#include <cstring>

namespace dynamo_native {

static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / arm64)
}

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static inline uint64_t xxh64_round(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  acc *= P1;
  return acc;
}

static inline uint64_t xxh64_merge_round(uint64_t acc, uint64_t val) {
  acc ^= xxh64_round(0, val);
  acc = acc * P1 + P4;
  return acc;
}

inline uint64_t xxh64(const uint8_t* input, size_t len, uint64_t seed) {
  const uint8_t* p = input;
  const uint8_t* end = input + len;
  uint64_t h;

  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed + 0;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = xxh64_round(v1, read64(p)); p += 8;
      v2 = xxh64_round(v2, read64(p)); p += 8;
      v3 = xxh64_round(v3, read64(p)); p += 8;
      v4 = xxh64_round(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = xxh64_merge_round(h, v1);
    h = xxh64_merge_round(h, v2);
    h = xxh64_merge_round(h, v3);
    h = xxh64_merge_round(h, v4);
  } else {
    h = seed + P5;
  }

  h += (uint64_t)len;

  while (p + 8 <= end) {
    h ^= xxh64_round(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (uint64_t)(*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }

  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

}  // namespace dynamo_native
