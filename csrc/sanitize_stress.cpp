// Sanitizer stress harness for the native radix core + hashing.
//
// The reference relies on Rust ownership for memory/thread safety; our C++
// must earn it with sanitizers instead (SURVEY section 5.2). Build+run:
//
//   g++ -std=c++17 -O1 -g -fsanitize=address,undefined \
//       csrc/sanitize_stress.cpp -o /tmp/stress_asan && /tmp/stress_asan
//   g++ -std=c++17 -O1 -g -fsanitize=thread \
//       csrc/sanitize_stress.cpp -o /tmp/stress_tsan && /tmp/stress_tsan
//
// (tests/test_native.py runs both when g++ is available.)
//
// The threaded phase serializes tree mutation with a mutex, mirroring the
// CPython GIL under which the extension actually runs — TSan then verifies
// that the serialized usage really is race-free (and would catch any state
// the extension ever shared outside the GIL).

#include <cassert>
#include <cstdio>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include "radix_core.h"
#include "xxh64.h"

using dynamo_native::Tree;
using dynamo_native::Worker;
using dynamo_native::xxh64;

namespace {

std::vector<uint64_t> chain(uint64_t seed, int start, int n) {
  std::vector<uint64_t> out;
  uint64_t h = seed;
  for (int i = start; i < start + n; i++) {
    uint32_t tok[4] = {(uint32_t)i, (uint32_t)(i * 7), 3u, 4u};
    h = xxh64(reinterpret_cast<const uint8_t*>(tok), sizeof tok, h);
    out.push_back(h);
  }
  return out;
}

void single_thread_stress() {
  Tree tree;
  std::mt19937_64 rng(42);
  std::vector<std::vector<uint64_t>> live;
  for (int iter = 0; iter < 20000; iter++) {
    Worker w{rng() % 8, (int32_t)(rng() % 2)};
    int op = (int)(rng() % 10);
    if (op < 5) {
      auto hashes = chain(rng() % 64, 0, 1 + (int)(rng() % 12));
      bool has_parent = !live.empty() && (rng() & 1);
      uint64_t parent = has_parent ? live[rng() % live.size()].back() : 0;
      tree.apply_stored(w, has_parent, parent, hashes);
      live.push_back(hashes);
      if (live.size() > 256) live.erase(live.begin());
    } else if (op < 8 && !live.empty()) {
      tree.apply_removed(w, live[rng() % live.size()]);
    } else if (op == 8) {
      tree.remove_worker(w);
    } else if (!live.empty()) {
      // find_matches-style walk
      const auto& hashes = live[rng() % live.size()];
      const dynamo_native::Node* cur = &tree.root;
      for (uint64_t h : hashes) {
        auto it = cur->children.find(h);
        if (it == cur->children.end()) break;
        cur = it->second;
        (void)cur->workers.size();
      }
    }
  }
  std::printf("single-thread stress ok (%zu nodes live)\n",
              tree.nodes.size());
}

void gil_serialized_stress() {
  Tree tree;
  std::mutex gil;
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; t++) {
    threads.emplace_back([&tree, &gil, t] {
      std::mt19937_64 rng(1000 + t);
      for (int iter = 0; iter < 5000; iter++) {
        Worker w{(uint64_t)t, 0};
        auto hashes = chain(rng() % 32, (int)(rng() % 8),
                            1 + (int)(rng() % 8));
        std::lock_guard<std::mutex> hold(gil);
        switch (rng() % 4) {
          case 0:
          case 1:
            tree.apply_stored(w, false, 0, hashes);
            break;
          case 2:
            tree.apply_removed(w, hashes);
            break;
          default:
            tree.remove_worker(w);
        }
      }
    });
  }
  for (auto& th : threads) th.join();
  std::printf("gil-serialized thread stress ok (%zu nodes live)\n",
              tree.nodes.size());
}

void concurrent_tree_stress() {
  // The ConcurrentTree does its OWN locking (shared_mutex; the extension
  // drops the GIL around its calls) — hammer it from unsynchronized
  // threads so TSan proves the internal locking, not caller discipline.
  dynamo_native::ConcurrentTree tree(/*ttl_ms=*/50, /*max_tree_size=*/512);
  std::vector<std::thread> threads;
  for (int t = 0; t < 6; t++) {
    threads.emplace_back([&tree, t] {
      std::mt19937_64 rng(2000 + t);
      for (int iter = 0; iter < 4000; iter++) {
        Worker w{(uint64_t)(t % 3), (int32_t)(t & 1)};
        auto hashes = chain(rng() % 32, (int)(rng() % 8),
                            1 + (int)(rng() % 8));
        switch (rng() % 8) {
          case 0:
          case 1:
          case 2:
            tree.apply_stored(w, false, 0, hashes, (uint64_t)iter);
            break;
          case 3:
            tree.apply_removed(w, hashes);
            break;
          case 4:
            tree.remove_worker(w);
            break;
          case 5:
            (void)tree.maintain((uint64_t)iter + 25);
            break;
          default: {
            std::unordered_map<Worker, int64_t,
                               dynamo_native::WorkerHash> scores, sizes;
            tree.find_matches(hashes, false, &scores, &sizes);
          }
        }
      }
    });
  }
  for (auto& th : threads) th.join();
  // final full expiry must empty every worker's index
  (void)tree.maintain(~0ULL);
  std::printf("concurrent tree stress ok (%zu nodes live)\n",
              tree.total_nodes());
}

void prune_manager_checks() {
  dynamo_native::PruneManager pm(/*ttl_ms=*/100, /*max_tree_size=*/10,
                                 /*target_ratio=*/0.5);
  std::vector<dynamo_native::BlockKey> keys;
  for (uint64_t i = 0; i < 20; i++) keys.push_back({i, Worker{1, 0}});
  pm.insert(keys, 0);
  // refresh half at a later tick: they must survive the first expiry sweep
  std::vector<dynamo_native::BlockKey> young(keys.begin() + 10, keys.end());
  pm.insert(young, 60);
  auto expired = pm.pop_expired(110);
  assert(expired.size() == 10);  // the unrefreshed half
  // pop_oldest drains exactly the surviving (refreshed) half
  dynamo_native::BlockKey k;
  size_t popped = 0;
  while (pm.pop_oldest(&k)) {
    assert(k.hash >= 10);  // only refreshed keys survive
    popped++;
  }
  assert(popped == 10);
  assert(pm.pop_expired(1000).size() == 0);  // everything accounted for
  std::printf("prune manager checks ok\n");
}

}  // namespace

int main() {
  // hashing determinism sanity under sanitizers
  uint8_t data[128];
  for (int i = 0; i < 128; i++) data[i] = (uint8_t)(i * 31);
  assert(xxh64(data, sizeof data, 7) == xxh64(data, sizeof data, 7));
  assert(xxh64(data, 0, 7) == xxh64(data, 0, 7));
  single_thread_stress();
  gil_serialized_stress();
  concurrent_tree_stress();
  prune_manager_checks();
  std::printf("sanitize_stress: all ok\n");
  return 0;
}
