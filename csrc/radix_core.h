// dynamo_tpu radix-tree core — pure C++, no CPython dependency.
//
// Shared between the Python extension (native.cpp) and the sanitizer
// stress harness (sanitize_stress.cpp). The reference gets memory/thread
// safety from Rust ownership (SURVEY section 5.2 notes our C++ must add
// sanitizer coverage instead); csrc/sanitize_stress.cpp runs this core
// under ASan/UBSan/TSan.
#pragma once

#include <algorithm>
#include <cstdint>
#include <mutex>
#include <queue>
#include <shared_mutex>
#include <unordered_map>
#include <unordered_set>
#include <utility>
#include <vector>

namespace dynamo_native {

struct Worker {
  uint64_t id;
  int32_t dp;
  bool operator==(const Worker& o) const { return id == o.id && dp == o.dp; }
};

struct WorkerHash {
  size_t operator()(const Worker& w) const {
    uint64_t x = w.id * 0x9E3779B97F4A7C15ULL ^ (uint64_t)(uint32_t)w.dp;
    x ^= x >> 31;
    return (size_t)x;
  }
};

struct Node {
  uint64_t hash;
  Node* parent;
  std::unordered_map<uint64_t, Node*> children;
  std::unordered_set<Worker, WorkerHash> workers;
};

struct Tree {
  Node root;
  std::unordered_map<uint64_t, Node*> nodes;
  std::unordered_map<Worker, int64_t, WorkerHash> worker_blocks;

  Tree() {
    root.hash = 0;
    root.parent = nullptr;
  }
  ~Tree() {
    for (auto& kv : nodes) delete kv.second;
  }

  void apply_stored(Worker w, bool has_parent, uint64_t parent_hash,
                    const std::vector<uint64_t>& hashes) {
    Node* parent = &root;
    if (has_parent) {
      auto it = nodes.find(parent_hash);
      // Unknown parent (joined mid-stream): root the chain; sequence hashes
      // keep lookups correct regardless of attachment point.
      if (it != nodes.end()) parent = it->second;
    }
    for (uint64_t h : hashes) {
      Node* node;
      auto it = nodes.find(h);
      if (it == nodes.end()) {
        node = new Node();
        node->hash = h;
        node->parent = parent;
        nodes.emplace(h, node);
        parent->children.emplace(h, node);
      } else {
        node = it->second;
      }
      if (node->workers.insert(w).second) worker_blocks[w] += 1;
      parent = node;
    }
  }

  void maybe_prune(Node* node) {
    while (node != &root && node->workers.empty() && node->children.empty()) {
      Node* parent = node->parent;
      if (!parent) break;
      parent->children.erase(node->hash);
      nodes.erase(node->hash);
      delete node;
      node = parent;
    }
  }

  void apply_removed(Worker w, const std::vector<uint64_t>& hashes) {
    for (uint64_t h : hashes) {
      auto it = nodes.find(h);
      if (it == nodes.end()) continue;
      Node* node = it->second;
      if (node->workers.erase(w)) {
        auto wb = worker_blocks.find(w);
        if (wb != worker_blocks.end() && wb->second > 0) wb->second -= 1;
      }
      maybe_prune(node);
    }
  }

  void remove_worker(Worker w) {
    // Collect hashes, not pointers: an earlier maybe_prune chain may delete
    // later entries, so re-resolve each through the nodes map.
    std::vector<uint64_t> touched;
    for (auto& kv : nodes) {
      if (kv.second->workers.erase(w)) touched.push_back(kv.first);
    }
    for (uint64_t h : touched) {
      auto it = nodes.find(h);
      if (it != nodes.end()) maybe_prune(it->second);
    }
    worker_blocks.erase(w);
  }

  // Leading-contiguous-match scores: worker -> count of request blocks 0..i
  // it holds without a gap (the router's per-request hot read).
  void match_prefix(const std::vector<uint64_t>& hashes, bool early_exit,
                    std::unordered_map<Worker, int64_t, WorkerHash>* scores)
      const {
    const Node* node = &root;
    int64_t depth = 0;
    for (uint64_t h : hashes) {
      auto it = node->children.find(h);
      if (it == node->children.end()) break;
      node = it->second;
      for (const Worker& w : node->workers) {
        auto s = scores->find(w);
        int64_t cur = (s == scores->end()) ? 0 : s->second;
        if (cur == depth) (*scores)[w] = depth + 1;
      }
      if (early_exit && node->workers.empty()) break;
      depth++;
    }
  }
};

// ---------------------------------------------------------------------------
// TTL + size pruning (ref: lib/kv-router/src/indexer/pruning.rs
// PruneManager — lazy min-heap of expirations over an authoritative timers
// map; stale heap entries are skipped on pop and compacted past a rebuild
// threshold).
// ---------------------------------------------------------------------------

struct BlockKey {
  uint64_t hash;
  Worker worker;
  bool operator==(const BlockKey& o) const {
    return hash == o.hash && worker == o.worker;
  }
};

struct BlockKeyHash {
  size_t operator()(const BlockKey& k) const {
    return (size_t)(k.hash * 0x9E3779B97F4A7C15ULL) ^ WorkerHash{}(k.worker);
  }
};

struct PruneManager {
  // expiry in caller-supplied ms ticks (tests drive a fake clock).
  std::unordered_map<BlockKey, uint64_t, BlockKeyHash> timers;
  struct HeapEntry {
    uint64_t expiry;
    BlockKey key;
    bool operator>(const HeapEntry& o) const { return expiry > o.expiry; }
  };
  std::priority_queue<HeapEntry, std::vector<HeapEntry>,
                      std::greater<HeapEntry>> expirations;
  uint64_t ttl_ms;
  size_t max_tree_size;     // 0 = size pruning disabled
  double prune_target_ratio;
  size_t rebuild_threshold; // heap > timers * threshold -> rebuild

  PruneManager(uint64_t ttl_ms_, size_t max_tree_size_ = 0,
               double target_ratio = 0.8, size_t rebuild = 4)
      : ttl_ms(ttl_ms_), max_tree_size(max_tree_size_),
        prune_target_ratio(target_ratio), rebuild_threshold(rebuild) {}

  void rebuild_heap() {
    std::vector<HeapEntry> entries;
    entries.reserve(timers.size());
    for (auto& kv : timers) entries.push_back({kv.second, kv.first});
    expirations = std::priority_queue<HeapEntry, std::vector<HeapEntry>,
                                      std::greater<HeapEntry>>(
        std::greater<HeapEntry>(), std::move(entries));
  }

  void insert(const std::vector<BlockKey>& keys, uint64_t now_ms) {
    uint64_t expiry = now_ms + ttl_ms;
    for (const BlockKey& k : keys) {
      timers[k] = expiry;  // refresh; old heap entry goes stale
      expirations.push({expiry, k});
    }
    if (expirations.size() > timers.size() * rebuild_threshold &&
        expirations.size() > 1024)
      rebuild_heap();
  }

  void erase(const BlockKey& k) { timers.erase(k); }

  std::vector<BlockKey> pop_expired(uint64_t now_ms) {
    std::vector<BlockKey> out;
    while (!expirations.empty() && expirations.top().expiry <= now_ms) {
      HeapEntry e = expirations.top();
      expirations.pop();
      auto it = timers.find(e.key);
      if (it != timers.end() && it->second == e.expiry) {
        timers.erase(it);
        out.push_back(e.key);
      }
    }
    return out;
  }

  // Pop the single oldest valid (non-stale) entry; false when exhausted.
  bool pop_oldest(BlockKey* out) {
    while (!expirations.empty()) {
      HeapEntry e = expirations.top();
      expirations.pop();
      auto it = timers.find(e.key);
      if (it != timers.end() && it->second == e.expiry) {
        timers.erase(it);
        *out = e.key;
        return true;
      }
    }
    return false;
  }
};

// ---------------------------------------------------------------------------
// Concurrent tree (ref: lib/kv-router/src/indexer/concurrent_radix_tree.rs
// — reader-writer concurrency so per-request find_matches never queues
// behind other readers). One tree-wide shared_mutex: match/size reads take
// shared locks (and the CPython binding drops the GIL around them, so many
// router threads really do read in parallel); event application takes the
// exclusive lock.
// ---------------------------------------------------------------------------

struct ConcurrentTree {
  Tree tree;
  PruneManager pruner;
  mutable std::shared_mutex mu;

  explicit ConcurrentTree(uint64_t ttl_ms = 0, size_t max_tree_size = 0,
                          double target_ratio = 0.8)
      : pruner(ttl_ms, max_tree_size, target_ratio) {}

  // TTL and size budgets are independent: size-only configs still need the
  // timer heap (it provides the oldest-first prune order; with ttl=0 the
  // "expiry" is the insertion tick and pop_expired never runs).
  bool tracking_enabled() const {
    return pruner.ttl_ms > 0 || pruner.max_tree_size > 0;
  }
  bool ttl_enabled() const { return pruner.ttl_ms > 0; }

  void find_matches(const std::vector<uint64_t>& hashes, bool early_exit,
                    std::unordered_map<Worker, int64_t, WorkerHash>* scores,
                    std::unordered_map<Worker, int64_t, WorkerHash>* sizes)
      const {
    std::shared_lock<std::shared_mutex> lk(mu);
    tree.match_prefix(hashes, early_exit, scores);
    if (sizes) *sizes = tree.worker_blocks;
  }

  void apply_stored(Worker w, bool has_parent, uint64_t parent_hash,
                    const std::vector<uint64_t>& hashes, uint64_t now_ms) {
    std::unique_lock<std::shared_mutex> lk(mu);
    tree.apply_stored(w, has_parent, parent_hash, hashes);
    if (tracking_enabled()) {
      std::vector<BlockKey> keys;
      keys.reserve(hashes.size());
      for (uint64_t h : hashes) keys.push_back({h, w});
      pruner.insert(keys, now_ms);
    }
  }

  void apply_removed(Worker w, const std::vector<uint64_t>& hashes) {
    std::unique_lock<std::shared_mutex> lk(mu);
    for (uint64_t h : hashes) pruner.erase({h, w});
    tree.apply_removed(w, hashes);
  }

  void remove_worker(Worker w) {
    std::unique_lock<std::shared_mutex> lk(mu);
    tree.remove_worker(w);
    if (tracking_enabled()) {
      std::vector<BlockKey> dead;
      for (auto& kv : pruner.timers)
        if (kv.first.worker == w) dead.push_back(kv.first);
      for (const BlockKey& k : dead) pruner.timers.erase(k);
    }
  }

  // TTL expiry + size pruning in one sweep; returns what was evicted so the
  // caller can surface metrics/events. Expiry is APPLIED before the size
  // check — pruning against the pre-expiry count would evict live blocks a
  // sweep that just freed enough room. Size pruning evicts per-(worker,
  // hash) entries but tracks the NODE count after each removal: a hash
  // replicated across workers only drops its node when the last holder is
  // evicted, so the loop runs until the tree actually reaches target (or
  // the heap is exhausted).
  std::vector<BlockKey> maintain(uint64_t now_ms) {
    // Config fields are immutable after construction: the disabled check
    // must not grab the writer lock (it would contend the router's hot
    // find_matches read path once a second for nothing).
    if (!tracking_enabled()) return {};
    std::unique_lock<std::shared_mutex> lk(mu);
    std::vector<BlockKey> evicted;
    if (ttl_enabled()) {
      evicted = pruner.pop_expired(now_ms);
      for (const BlockKey& k : evicted)
        tree.apply_removed(k.worker, {k.hash});
    }
    if (pruner.max_tree_size > 0 &&
        tree.nodes.size() > pruner.max_tree_size) {
      size_t target =
          (size_t)(pruner.max_tree_size * pruner.prune_target_ratio);
      BlockKey k;
      while (tree.nodes.size() > target && pruner.pop_oldest(&k)) {
        tree.apply_removed(k.worker, {k.hash});
        evicted.push_back(k);
      }
    }
    return evicted;
  }

  size_t total_nodes() const {
    std::shared_lock<std::shared_mutex> lk(mu);
    return tree.nodes.size();
  }
};

}  // namespace dynamo_native
