// dynamo_tpu radix-tree core — pure C++, no CPython dependency.
//
// Shared between the Python extension (native.cpp) and the sanitizer
// stress harness (sanitize_stress.cpp). The reference gets memory/thread
// safety from Rust ownership (SURVEY section 5.2 notes our C++ must add
// sanitizer coverage instead); csrc/sanitize_stress.cpp runs this core
// under ASan/UBSan/TSan.
#pragma once

#include <cstdint>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace dynamo_native {

struct Worker {
  uint64_t id;
  int32_t dp;
  bool operator==(const Worker& o) const { return id == o.id && dp == o.dp; }
};

struct WorkerHash {
  size_t operator()(const Worker& w) const {
    uint64_t x = w.id * 0x9E3779B97F4A7C15ULL ^ (uint64_t)(uint32_t)w.dp;
    x ^= x >> 31;
    return (size_t)x;
  }
};

struct Node {
  uint64_t hash;
  Node* parent;
  std::unordered_map<uint64_t, Node*> children;
  std::unordered_set<Worker, WorkerHash> workers;
};

struct Tree {
  Node root;
  std::unordered_map<uint64_t, Node*> nodes;
  std::unordered_map<Worker, int64_t, WorkerHash> worker_blocks;

  Tree() {
    root.hash = 0;
    root.parent = nullptr;
  }
  ~Tree() {
    for (auto& kv : nodes) delete kv.second;
  }

  void apply_stored(Worker w, bool has_parent, uint64_t parent_hash,
                    const std::vector<uint64_t>& hashes) {
    Node* parent = &root;
    if (has_parent) {
      auto it = nodes.find(parent_hash);
      // Unknown parent (joined mid-stream): root the chain; sequence hashes
      // keep lookups correct regardless of attachment point.
      if (it != nodes.end()) parent = it->second;
    }
    for (uint64_t h : hashes) {
      Node* node;
      auto it = nodes.find(h);
      if (it == nodes.end()) {
        node = new Node();
        node->hash = h;
        node->parent = parent;
        nodes.emplace(h, node);
        parent->children.emplace(h, node);
      } else {
        node = it->second;
      }
      if (node->workers.insert(w).second) worker_blocks[w] += 1;
      parent = node;
    }
  }

  void maybe_prune(Node* node) {
    while (node != &root && node->workers.empty() && node->children.empty()) {
      Node* parent = node->parent;
      if (!parent) break;
      parent->children.erase(node->hash);
      nodes.erase(node->hash);
      delete node;
      node = parent;
    }
  }

  void apply_removed(Worker w, const std::vector<uint64_t>& hashes) {
    for (uint64_t h : hashes) {
      auto it = nodes.find(h);
      if (it == nodes.end()) continue;
      Node* node = it->second;
      if (node->workers.erase(w)) {
        auto wb = worker_blocks.find(w);
        if (wb != worker_blocks.end() && wb->second > 0) wb->second -= 1;
      }
      maybe_prune(node);
    }
  }

  void remove_worker(Worker w) {
    // Collect hashes, not pointers: an earlier maybe_prune chain may delete
    // later entries, so re-resolve each through the nodes map.
    std::vector<uint64_t> touched;
    for (auto& kv : nodes) {
      if (kv.second->workers.erase(w)) touched.push_back(kv.first);
    }
    for (uint64_t h : touched) {
      auto it = nodes.find(h);
      if (it != nodes.end()) maybe_prune(it->second);
    }
    worker_blocks.erase(w);
  }
};


}  // namespace dynamo_native
