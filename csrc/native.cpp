// dynamo_tpu._native — C++ hot paths for the TPU-native serving runtime.
//
// The reference implements these in Rust (lib/tokens/src/lib.rs chained block
// hashing; lib/kv-router/src/indexer/radix_tree.rs the KV-prefix radix tree).
// Here they are native C++ behind a CPython extension, with bit-identical
// pure-Python fallbacks in dynamo_tpu/ (used when the extension isn't built):
//
//   * compute_block_hashes — chained XXH64 over fixed-size token blocks; the
//     per-request hot path of every routing decision (router side) and every
//     completed decode block (engine side).
//   * RadixTree — prefix index mapping sequence-hash chains -> worker sets,
//     queried per request (find_matches) and mutated per KV event.
//
// Build: `python setup.py build_ext --inplace` (auto-attempted once by
// dynamo_tpu/native.py).
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <chrono>

#include "xxh64.h"
#include "radix_core.h"

namespace {

using dynamo_native::xxh64;
using dynamo_native::BlockKey;
using dynamo_native::ConcurrentTree;
using dynamo_native::Node;
using dynamo_native::Worker;
using dynamo_native::WorkerHash;

static uint64_t steady_now_ms() {
  return (uint64_t)std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Hashing
// ---------------------------------------------------------------------------

// Parse a Python sequence of ints (or a buffer of u32) into u32 tokens.
static bool tokens_from_obj(PyObject* obj, std::vector<uint32_t>* out) {
  Py_buffer view;
  if (PyObject_CheckBuffer(obj) &&
      PyObject_GetBuffer(obj, &view, PyBUF_FORMAT | PyBUF_C_CONTIGUOUS) == 0) {
    // Accept raw bytes (itemsize 1) or 32-bit element buffers. Wider
    // elements (e.g. numpy int64 token arrays) fall through to the sequence
    // path so native and Python hashes never diverge.
    if ((view.itemsize == 1 || view.itemsize == 4) && view.len % 4 == 0) {
      out->resize(view.len / 4);
      std::memcpy(out->data(), view.buf, view.len);
      PyBuffer_Release(&view);
      return true;
    }
    PyBuffer_Release(&view);
  }
  PyErr_Clear();
  PyObject* seq = PySequence_Fast(obj, "tokens must be a sequence or buffer");
  if (!seq) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  out->resize(n);
  for (Py_ssize_t i = 0; i < n; i++) {
    long long v = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(seq, i));
    if (v == -1 && PyErr_Occurred()) {
      Py_DECREF(seq);
      return false;
    }
    (*out)[i] = (uint32_t)v;
  }
  Py_DECREF(seq);
  return true;
}

// compute_block_hashes(tokens, block_size, seed) -> list[int]
// Chained: block i's hash seeds block i+1; partial trailing block unhashed.
static PyObject* py_compute_block_hashes(PyObject*, PyObject* args) {
  PyObject* tokens_obj;
  Py_ssize_t block_size;
  unsigned long long seed;
  if (!PyArg_ParseTuple(args, "OnK", &tokens_obj, &block_size, &seed))
    return nullptr;
  if (block_size <= 0) {
    PyErr_SetString(PyExc_ValueError, "block_size must be positive");
    return nullptr;
  }
  std::vector<uint32_t> tokens;
  if (!tokens_from_obj(tokens_obj, &tokens)) return nullptr;

  size_t n_blocks = tokens.size() / (size_t)block_size;
  PyObject* out = PyList_New((Py_ssize_t)n_blocks);
  if (!out) return nullptr;
  uint64_t h = seed;
  const uint8_t* base = reinterpret_cast<const uint8_t*>(tokens.data());
  for (size_t i = 0; i < n_blocks; i++) {
    h = xxh64(base + i * (size_t)block_size * 4, (size_t)block_size * 4, h);
    PyObject* v = PyLong_FromUnsignedLongLong(h);
    if (!v) { Py_DECREF(out); return nullptr; }
    PyList_SET_ITEM(out, (Py_ssize_t)i, v);
  }
  return out;
}

// hash_bytes(data, seed) -> int  (raw xxh64; parity tests vs python xxhash)
static PyObject* py_hash_bytes(PyObject*, PyObject* args) {
  Py_buffer view;
  unsigned long long seed;
  if (!PyArg_ParseTuple(args, "y*K", &view, &seed)) return nullptr;
  uint64_t h = xxh64((const uint8_t*)view.buf, (size_t)view.len, seed);
  PyBuffer_Release(&view);
  return PyLong_FromUnsignedLongLong(h);
}

// ---------------------------------------------------------------------------
// Radix tree
// ---------------------------------------------------------------------------

typedef struct {
  PyObject_HEAD
  ConcurrentTree* tree;
} RadixTreeObject;

// RadixTree(ttl_secs=0.0, max_tree_size=0, prune_target_ratio=0.8)
// ttl_secs > 0 enables TTL expiry (+ size pruning when max_tree_size > 0),
// serviced by maintain() (ref: indexer/pruning.rs PruneManager).
static PyObject* RadixTree_new(PyTypeObject* type, PyObject* args,
                               PyObject* kwargs) {
  double ttl_secs = 0.0;
  unsigned long long max_tree_size = 0;
  double target_ratio = 0.8;
  static const char* kwlist[] = {"ttl_secs", "max_tree_size",
                                 "prune_target_ratio", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "|dKd",
                                   const_cast<char**>(kwlist), &ttl_secs,
                                   &max_tree_size, &target_ratio))
    return nullptr;
  RadixTreeObject* self = (RadixTreeObject*)type->tp_alloc(type, 0);
  if (self)
    self->tree = new ConcurrentTree((uint64_t)(ttl_secs * 1000.0),
                                    (size_t)max_tree_size, target_ratio);
  return (PyObject*)self;
}

static void RadixTree_dealloc(RadixTreeObject* self) {
  delete self->tree;
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static bool hashes_from_obj(PyObject* obj, std::vector<uint64_t>* out) {
  PyObject* seq = PySequence_Fast(obj, "expected a sequence of hashes");
  if (!seq) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  out->resize(n);
  for (Py_ssize_t i = 0; i < n; i++) {
    uint64_t v =
        PyLong_AsUnsignedLongLongMask(PySequence_Fast_GET_ITEM(seq, i));
    if (PyErr_Occurred()) { Py_DECREF(seq); return false; }
    (*out)[i] = v;
  }
  Py_DECREF(seq);
  return true;
}

// find_matches(hashes, early_exit) -> (scores, tree_sizes)
//   scores:     {(worker_id, dp_rank): contiguous-leading-block count}
//   tree_sizes: {(worker_id, dp_rank): total blocks indexed for the worker}
static PyObject* RadixTree_find_matches(RadixTreeObject* self, PyObject* args) {
  PyObject* hashes_obj;
  int early_exit = 0;
  if (!PyArg_ParseTuple(args, "O|p", &hashes_obj, &early_exit)) return nullptr;
  std::vector<uint64_t> hashes;
  if (!hashes_from_obj(hashes_obj, &hashes)) return nullptr;

  std::unordered_map<Worker, int64_t, WorkerHash> scores;
  std::unordered_map<Worker, int64_t, WorkerHash> sizes;
  // Drop the GIL for the walk: find_matches is the router's per-request hot
  // read and the shared lock lets concurrent readers overlap (the
  // ConcurrentRadixTree role, concurrent_radix_tree.rs).
  Py_BEGIN_ALLOW_THREADS
  self->tree->find_matches(hashes, early_exit != 0, &scores, &sizes);
  Py_END_ALLOW_THREADS

  PyObject* scores_d = PyDict_New();
  PyObject* sizes_d = PyDict_New();
  if (!scores_d || !sizes_d) { Py_XDECREF(scores_d); Py_XDECREF(sizes_d); return nullptr; }
  for (auto& kv : scores) {
    PyObject* key = Py_BuildValue("(Ki)", kv.first.id, (int)kv.first.dp);
    PyObject* val = PyLong_FromLongLong(kv.second);
    if (!key || !val || PyDict_SetItem(scores_d, key, val) < 0) {
      Py_XDECREF(key); Py_XDECREF(val); Py_DECREF(scores_d); Py_DECREF(sizes_d);
      return nullptr;
    }
    Py_DECREF(key); Py_DECREF(val);
  }
  for (auto& kv : sizes) {
    PyObject* key = Py_BuildValue("(Ki)", kv.first.id, (int)kv.first.dp);
    PyObject* val = PyLong_FromLongLong(kv.second);
    if (!key || !val || PyDict_SetItem(sizes_d, key, val) < 0) {
      Py_XDECREF(key); Py_XDECREF(val); Py_DECREF(scores_d); Py_DECREF(sizes_d);
      return nullptr;
    }
    Py_DECREF(key); Py_DECREF(val);
  }
  PyObject* out = PyTuple_Pack(2, scores_d, sizes_d);
  Py_DECREF(scores_d);
  Py_DECREF(sizes_d);
  return out;
}

// apply_stored(worker_id, dp_rank, parent_hash_or_None, hashes)
static PyObject* RadixTree_apply_stored(RadixTreeObject* self, PyObject* args) {
  unsigned long long wid;
  int dp;
  PyObject* parent_obj;
  PyObject* hashes_obj;
  if (!PyArg_ParseTuple(args, "KiOO", &wid, &dp, &parent_obj, &hashes_obj))
    return nullptr;
  bool has_parent = parent_obj != Py_None;
  uint64_t parent_hash = 0;
  if (has_parent) {
    parent_hash = PyLong_AsUnsignedLongLongMask(parent_obj);
    if (PyErr_Occurred()) return nullptr;
  }
  std::vector<uint64_t> hashes;
  if (!hashes_from_obj(hashes_obj, &hashes)) return nullptr;
  Py_BEGIN_ALLOW_THREADS
  self->tree->apply_stored(Worker{wid, dp}, has_parent, parent_hash, hashes,
                           steady_now_ms());
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

static PyObject* RadixTree_apply_removed(RadixTreeObject* self, PyObject* args) {
  unsigned long long wid;
  int dp;
  PyObject* hashes_obj;
  if (!PyArg_ParseTuple(args, "KiO", &wid, &dp, &hashes_obj)) return nullptr;
  std::vector<uint64_t> hashes;
  if (!hashes_from_obj(hashes_obj, &hashes)) return nullptr;
  Py_BEGIN_ALLOW_THREADS
  self->tree->apply_removed(Worker{wid, dp}, hashes);
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

static PyObject* RadixTree_remove_worker(RadixTreeObject* self, PyObject* args) {
  unsigned long long wid;
  int dp;
  if (!PyArg_ParseTuple(args, "Ki", &wid, &dp)) return nullptr;
  Py_BEGIN_ALLOW_THREADS
  self->tree->remove_worker(Worker{wid, dp});
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

static PyObject* RadixTree_remove_worker_id(RadixTreeObject* self,
                                            PyObject* args) {
  unsigned long long wid;
  if (!PyArg_ParseTuple(args, "K", &wid)) return nullptr;
  Py_BEGIN_ALLOW_THREADS
  {
    std::vector<Worker> targets;
    {
      std::shared_lock<std::shared_mutex> lk(self->tree->mu);
      for (auto& kv : self->tree->tree.worker_blocks)
        if (kv.first.id == wid) targets.push_back(kv.first);
    }
    for (Worker w : targets) self->tree->remove_worker(w);
  }
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

// dump_worker(worker_id, dp_rank) -> list[(parent_hash_or_None, hash)]
static PyObject* RadixTree_dump_worker(RadixTreeObject* self, PyObject* args) {
  unsigned long long wid;
  int dp;
  if (!PyArg_ParseTuple(args, "Ki", &wid, &dp)) return nullptr;
  Worker w{wid, dp};
  PyObject* out = PyList_New(0);
  if (!out) return nullptr;
  std::shared_lock<std::shared_mutex> lk(self->tree->mu);
  for (auto& kv : self->tree->tree.nodes) {
    Node* node = kv.second;
    if (node->workers.count(w)) {
      PyObject* item;
      Node* parent = node->parent;
      if (!parent || parent == &self->tree->tree.root)
        item = Py_BuildValue("(OK)", Py_None, node->hash);
      else
        item = Py_BuildValue("(KK)", parent->hash, node->hash);
      if (!item || PyList_Append(out, item) < 0) {
        Py_XDECREF(item); Py_DECREF(out); return nullptr;
      }
      Py_DECREF(item);
    }
  }
  return out;
}

static PyObject* RadixTree_total_nodes(RadixTreeObject* self, PyObject*) {
  return PyLong_FromSize_t(self->tree->total_nodes());
}

// maintain(now_ms=None) -> list[(worker_id, dp_rank, hash)]
// TTL-expire + size-prune; returns evicted (worker, block) pairs.
static PyObject* RadixTree_maintain(RadixTreeObject* self, PyObject* args) {
  PyObject* now_obj = Py_None;
  if (!PyArg_ParseTuple(args, "|O", &now_obj)) return nullptr;
  uint64_t now_ms = (now_obj == Py_None)
                        ? steady_now_ms()
                        : PyLong_AsUnsignedLongLongMask(now_obj);
  if (PyErr_Occurred()) return nullptr;
  std::vector<BlockKey> evicted;
  Py_BEGIN_ALLOW_THREADS
  evicted = self->tree->maintain(now_ms);
  Py_END_ALLOW_THREADS
  PyObject* out = PyList_New((Py_ssize_t)evicted.size());
  if (!out) return nullptr;
  for (size_t i = 0; i < evicted.size(); i++) {
    PyObject* item = Py_BuildValue("(KiK)", evicted[i].worker.id,
                                   (int)evicted[i].worker.dp,
                                   evicted[i].hash);
    if (!item) { Py_DECREF(out); return nullptr; }
    PyList_SET_ITEM(out, (Py_ssize_t)i, item);
  }
  return out;
}

static PyObject* RadixTree_worker_block_counts(RadixTreeObject* self,
                                               PyObject*) {
  PyObject* out = PyDict_New();
  if (!out) return nullptr;
  std::shared_lock<std::shared_mutex> lk(self->tree->mu);
  for (auto& kv : self->tree->tree.worker_blocks) {
    PyObject* key = Py_BuildValue("(Ki)", kv.first.id, (int)kv.first.dp);
    PyObject* val = PyLong_FromLongLong(kv.second);
    if (!key || !val || PyDict_SetItem(out, key, val) < 0) {
      Py_XDECREF(key); Py_XDECREF(val); Py_DECREF(out); return nullptr;
    }
    Py_DECREF(key); Py_DECREF(val);
  }
  return out;
}

static PyMethodDef RadixTree_methods[] = {
    {"find_matches", (PyCFunction)RadixTree_find_matches, METH_VARARGS, nullptr},
    {"apply_stored", (PyCFunction)RadixTree_apply_stored, METH_VARARGS, nullptr},
    {"apply_removed", (PyCFunction)RadixTree_apply_removed, METH_VARARGS, nullptr},
    {"remove_worker", (PyCFunction)RadixTree_remove_worker, METH_VARARGS, nullptr},
    {"remove_worker_id", (PyCFunction)RadixTree_remove_worker_id, METH_VARARGS, nullptr},
    {"dump_worker", (PyCFunction)RadixTree_dump_worker, METH_VARARGS, nullptr},
    {"maintain", (PyCFunction)RadixTree_maintain, METH_VARARGS, nullptr},
    {"total_nodes", (PyCFunction)RadixTree_total_nodes, METH_NOARGS, nullptr},
    {"worker_block_counts", (PyCFunction)RadixTree_worker_block_counts, METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

static PyTypeObject RadixTreeType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "dynamo_tpu._native.RadixTree",          /* tp_name */
    sizeof(RadixTreeObject),                 /* tp_basicsize */
    0,                                       /* tp_itemsize */
    (destructor)RadixTree_dealloc,           /* tp_dealloc */
};

// ---------------------------------------------------------------------------
// Module
// ---------------------------------------------------------------------------

static PyMethodDef module_methods[] = {
    {"compute_block_hashes", py_compute_block_hashes, METH_VARARGS,
     "compute_block_hashes(tokens, block_size, seed) -> list[int]"},
    {"hash_bytes", py_hash_bytes, METH_VARARGS,
     "hash_bytes(data, seed) -> int (xxh64)"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef native_module = {
    PyModuleDef_HEAD_INIT, "_native",
    "Native C++ hot paths: chained block hashing + KV radix index.", -1,
    module_methods};

}  // namespace

PyMODINIT_FUNC PyInit__native(void) {
  RadixTreeType.tp_flags = Py_TPFLAGS_DEFAULT;
  RadixTreeType.tp_new = RadixTree_new;
  RadixTreeType.tp_methods = RadixTree_methods;
  if (PyType_Ready(&RadixTreeType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&native_module);
  if (!m) return nullptr;
  Py_INCREF(&RadixTreeType);
  if (PyModule_AddObject(m, "RadixTree", (PyObject*)&RadixTreeType) < 0) {
    Py_DECREF(&RadixTreeType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
