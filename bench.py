"""Benchmark: steady-state decode + prefill throughput of the FLAGSHIP
model (mistral-7b, the honest single-chip 7-8B config — BASELINE.md) on
the available accelerator, with the 0.6B toy as a secondary datapoint.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N,
   "prefill": {...}, "ttft": {...}, "secondary": [{...}]}

`vs_baseline` is the fraction of this chip's HBM-bandwidth roofline for the
model (decode is memory-bound: every step streams all weights + the active
KV). The reference publishes only relative numbers (BASELINE.md), so roofline
fraction is the honest hardware-normalized comparison: 1.0 == perfect
bandwidth utilization, and the reference's vLLM-on-H100 recipes sit around
0.5-0.7 of their roofline on the same measure.

Model selection: with DYNT_BENCH_MODEL / DYNT_BENCH_MODEL_PATH set, bench
exactly that model (single-model mode, all DYNT_BENCH_* knobs honored).
Otherwise on TPU the headline is mistral-7b (int8 KV — required at 7B:
bf16 KV + 14.5 GB of weights exceed the 16 GB HBM) and qwen3-0.6b runs
as `secondary`; on CPU only the toy runs (a 7B random-init on the CPU
smoke path would add tens of minutes for no signal).
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

import numpy as np

PAGE_SIZE = 16
# HBM bandwidth by chip generation (GB/s) for the roofline denominator.
HBM_GBPS = {"v5 lite": 819.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1640.0,
            "cpu": 50.0}
PEAK_TFLOPS = {"v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0,
               "v6e": 918.0, "cpu": 1.0}


def _param_bytes(config) -> int:
    h, v = config.hidden, config.vocab_size
    per_layer = (
        h * config.n_q_heads * config.head_dim
        + 2 * h * config.n_kv_heads * config.head_dim
        + config.n_q_heads * config.head_dim * h
        + 3 * h * config.mlp_hidden
        + 2 * h
    )
    total = v * h + h + config.n_layers * per_layer
    if not config.tie_embeddings:
        total += h * v
    return total * 2  # bf16


def bench_one(model: str, *, model_path: str | None = None,
              batch: int = 8, kv_dtype: str = "model",
              weight_dtype: str = "model",
              num_pages: int = 1024, prompt_len: int = 256,
              decode_steps: int = 256, prefill_chunk: int = 1024,
              do_prefill: bool = True, do_ttft: bool = True,
              do_spec: bool = True, do_kvbm: bool = True,
              device_kind: str = "cpu") -> dict:
    from dynamo_tpu.engine import ModelRunner, RunnerConfig
    from dynamo_tpu.models import get_config
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    host_params = None
    if model_path:
        from dynamo_tpu.models.checkpoint import (
            config_from_checkpoint,
            load_params,
        )

        config = config_from_checkpoint(model_path)
        host_params = load_params(model_path, config)
        model_label = config.name
    else:
        config = get_config(model)
        model_label = model

    max_pages_per_seq = max(64, prefill_chunk // PAGE_SIZE + 2)
    runner = ModelRunner(
        config,
        RunnerConfig(page_size=PAGE_SIZE, num_pages=num_pages,
                     max_batch=batch, max_pages_per_seq=max_pages_per_seq,
                     prefill_buckets=(256, prefill_chunk)
                     if prefill_chunk > 256 else (256,),
                     kv_dtype=kv_dtype, weight_dtype=weight_dtype),
        make_mesh(MeshConfig()),
        host_params,
        seed=0,
    )
    if kv_dtype != "model":
        model_label += f" kv={kv_dtype}"
    if weight_dtype != "model":
        model_label += f" w={weight_dtype}"

    # Prefill BATCH sequences of PROMPT_LEN so decode runs with real KV.
    # Capacity covers prompt + warmup block + timed blocks — undersizing
    # would scatter KV through zero table entries into the shared scratch
    # page and silently corrupt the measured state.
    block = 64
    # Capacity covers the warmup block + timed blocks, and (do_kvbm) the
    # G2-offload A/B window of another settle + n_blocks fused blocks —
    # undersizing would scatter KV through zero table entries into the
    # shared scratch page and corrupt the measured state (comment below).
    total_tokens = prompt_len + decode_steps + (2 if do_kvbm else 1) * block
    pages_per_seq = total_tokens // PAGE_SIZE + 1
    tables = np.zeros((batch, max_pages_per_seq), np.int32)
    rng = np.random.default_rng(0)
    next_page = 1
    for b in range(batch):
        tables[b, :pages_per_seq] = np.arange(next_page,
                                              next_page + pages_per_seq)
        next_page += pages_per_seq
        prompt = rng.integers(0, config.vocab_size, prompt_len).astype(np.int32)
        budget = runner.max_prefill_chunk
        start_tok = 0
        while start_tok < prompt_len:
            chunk = prompt[start_tok:start_tok + budget]
            runner.prefill_chunk(chunk, start_tok, tables[b],
                                 start_tok + len(chunk), (0.0, 1.0, 0, 0))
            start_tok += len(chunk)

    tokens = np.zeros(batch, np.int32)
    positions = np.full(batch, prompt_len, np.int32)
    kv_lens = np.full(batch, prompt_len + 1, np.int32)
    active = np.ones(batch, bool)
    temp = np.zeros(batch, np.float32)
    top_p = np.ones(batch, np.float32)
    top_k = np.zeros(batch, np.int32)
    seeds = np.zeros(batch, np.uint32)

    # Steady-state serving uses fused decode blocks (DYNT_DECODE_BLOCK;
    # lax.scan of K steps per compiled call) with PIPELINED dispatch
    # (DYNT_DECODE_PIPELINE): block d+1 consumes block d's tokens
    # ON-DEVICE, so the host readback of block d overlaps block d+1's
    # compute — exactly what the serving scheduler does
    # (engine/scheduler.py _dispatch_decode/_drain_decode).
    steps_np = np.zeros(batch, np.int32)

    # Table width bucketed to the live context (as the serving scheduler
    # does): the attention kernel streams the table extent's pages.
    from dynamo_tpu.engine.model_runner import bucket_table_width

    width = bucket_table_width(pages_per_seq, max_pages_per_seq)
    btables = np.ascontiguousarray(tables[:, :width])

    state = {"tokens": tokens, "pending": None}
    # Step decomposition accumulators (perf/steptrace.py definitions):
    # dispatch = host time inside submit calls (tunnel RTT lives here),
    # drain = blocked readback waits. Recorded per timed trial so the
    # BENCH_r06 decode number ships with its host/device attribution.
    trace_acc = {"dispatch_s": 0.0, "drain_s": 0.0}

    def step_block():
        nonlocal positions, kv_lens, steps_np
        t0 = time.perf_counter()
        toks_dev = runner.decode_multi(
            state["tokens"], positions, btables, kv_lens, active, temp,
            top_p, top_k, seeds, steps_np, k=block, return_device=True)
        trace_acc["dispatch_s"] += time.perf_counter() - t0
        if state["pending"] is not None:
            t1 = time.perf_counter()
            np.asarray(state["pending"])  # stream block d while d+1 runs
            trace_acc["drain_s"] += time.perf_counter() - t1
        state["pending"] = toks_dev
        state["tokens"] = toks_dev[-1]  # device-side chain
        positions += block
        kv_lens += block
        steps_np += block

    def drain():
        if state["pending"] is not None:
            t1 = time.perf_counter()
            np.asarray(state["pending"])
            trace_acc["drain_s"] += time.perf_counter() - t1
            state["pending"] = None

    step_block()  # warmup (compile + first block)
    drain()

    # Median of three trials: the chip may be tunnel-attached/shared, and
    # a single window can catch a latency spike that says nothing about
    # the engine.
    n_blocks = decode_steps // block
    trials = []
    trial_traces = []
    for _ in range(3):
        trace_acc["dispatch_s"] = trace_acc["drain_s"] = 0.0
        start = time.perf_counter()
        for _ in range(n_blocks):
            step_block()
        drain()
        trials.append(time.perf_counter() - start)
        trial_traces.append(dict(trace_acc))
        # rewind positions so every trial measures the same context length
        positions -= n_blocks * block
        kv_lens -= n_blocks * block
        steps_np -= n_blocks * block
    median_i = sorted(range(3), key=lambda i: trials[i])[1]
    elapsed = trials[median_i]
    tok_per_sec = batch * n_blocks * block / elapsed
    # Decomposition of the median trial: host dispatch share is the
    # tunnel-RTT signal (a remote-attached chip shows it dominating),
    # device window = wall minus the host submit time.
    med_trace = trial_traces[median_i]
    steptrace_cols = {
        "dispatch_ms_per_block": round(
            med_trace["dispatch_s"] / n_blocks * 1e3, 4),
        "drain_wait_ms_per_block": round(
            med_trace["drain_s"] / n_blocks * 1e3, 4),
        "device_ms_per_block": round(
            max(0.0, elapsed - med_trace["dispatch_s"]) / n_blocks * 1e3,
            4),
        "host_dispatch_frac": round(
            med_trace["dispatch_s"] / elapsed, 4),
    }

    # Roofline: steps/sec ceiling = HBM_bw / (weights + active KV per step)
    hbm = 50.0
    for key, bw in HBM_GBPS.items():
        if key in device_kind:
            hbm = bw
            break
    kv_elem_bytes = 1 if kv_dtype == "int8" else 2
    kv_bytes_per_step = (
        config.n_layers * 2 * (prompt_len + decode_steps // 2) * batch
        * config.n_kv_heads * config.head_dim * kv_elem_bytes
    )
    param_bytes = _param_bytes(config)
    if weight_dtype == "int8":
        # W8A16 streams int8 projections (+ negligible scale rows);
        # embeddings/norms stay bf16 but the projections dominate.
        param_bytes //= 2
    elif weight_dtype == "int4":
        # W4A16: 0.5 B/weight packed + f32 scale+zero rows per group
        # (8 B / group weights) vs 2 B bf16. The group comes from the
        # same registered config the kernel reads (runtime/config.py).
        from dynamo_tpu.runtime.config import env as _cfg_env

        q4_group = int(_cfg_env("DYNT_Q4_GROUP"))
        param_bytes = int(param_bytes * (0.5 + 8.0 / q4_group) / 2.0)
    bytes_per_step = param_bytes + kv_bytes_per_step
    roofline_steps = hbm * 1e9 / bytes_per_step
    roofline_tok = roofline_steps * batch
    vs_baseline = tok_per_sec / roofline_tok

    result = {
        "metric": f"decode throughput {model_label} bs={batch} "
                  f"ctx={prompt_len} ({device_kind})",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
        "steptrace": steptrace_cols,
    }
    if weight_dtype == "int4":
        # Record WHICH pack layout served the number (the v1/v2 kernels
        # are A/B-able — docs/quantization.md): the version rides each
        # leaf's dtype, so read it off the live params.
        from dynamo_tpu.ops.q4_linear import pack_version
        from dynamo_tpu.runtime.config import env as _cfg_env

        versions = sorted({
            pack_version(leaf["q4"])
            for layer in runner.params["layers"]
            for leaf in layer.values() if isinstance(leaf, dict)
        })
        result["q4_layout"] = {
            "variant": ("mixed" if len(versions) > 1
                        else f"v{versions[0]}"),
            "group": int(_cfg_env("DYNT_Q4_GROUP")),
            "policy": _cfg_env("DYNT_Q4_VARIANT"),
        }

    # Speculative decode point (ROADMAP item 1 / ISSUE 7): the same
    # decode workload driven through the draftless speculation plane —
    # n-gram proposals mined from each sequence's own token stream,
    # verified k+1 positions per dispatch (engine/spec.py +
    # ModelRunner.decode_spec, exactly what the serving scheduler runs
    # with DYNT_SPEC_ENABLE=1). Greedy continuation of the SAME
    # random-prompt state as the plain decode number above, so
    # acceptance reflects what the model actually repeats — reported
    # alongside tok/s rather than assumed.
    # Gated on runner.supports_spec: MLA/gpt-oss configs have no
    # multi-token verification forward, and a single-model bench of one
    # must not crash away its decode/prefill numbers.
    if do_spec and os.environ.get("DYNT_BENCH_SPEC", "1") != "0" \
            and getattr(runner, "supports_spec", False):
        from dynamo_tpu.engine.spec import NGramProposer
        from dynamo_tpu.runtime.config import env as _spec_env

        # BENCH_r06 capture prep: the serving path speculates at
        # DYNT_SPEC_MAX_K when DYNT_SPEC_ENABLE is on (main() flips it
        # for the flagship run), so the bench's k defaults to the SAME
        # registered knob the scheduler reads — one `python bench.py`
        # on silicon records the number the fleet would serve, with the
        # knob state alongside the acceptance it produced.
        spec_k = int(os.environ.get("DYNT_BENCH_SPEC_K")
                     or _spec_env("DYNT_SPEC_MAX_K"))
        proposers = []
        sp_tokens = np.array(state["tokens"], np.int32).reshape(-1)
        sp_positions = np.full(batch, prompt_len + block, np.int32)
        sp_kv_lens = sp_positions + 1
        sp_steps = np.full(batch, block, np.int32)
        for b in range(batch):
            # History = this slot's committed stream (the bench has no
            # prompt text worth mining; serving seeds with the prompt).
            proposers.append(NGramProposer([int(sp_tokens[b])]))
        drafts = np.zeros((batch, spec_k), np.int32)
        # Committed tokens + the k-token verification overrun must stay
        # inside the per-sequence page allocation sized above.
        n_iter = max(1, (decode_steps - spec_k) // (spec_k + 1))
        proposed = accepted = emitted = 0

        def spec_iter():
            nonlocal proposed, accepted, emitted
            mined = np.zeros(batch, np.int32)
            for b in range(batch):
                drafts[b] = 0
                prop = proposers[b].propose(spec_k)
                drafts[b, : len(prop)] = prop
                mined[b] = len(prop)
                proposed += len(prop)
            targets, n_acc = runner.decode_spec(
                sp_tokens, drafts, sp_positions, btables, sp_kv_lens,
                active, temp, top_p, top_k, seeds, sp_steps)
            for b in range(batch):
                n = int(n_acc[b])
                toks = [int(t) for t in targets[b, : n + 1]]
                proposers[b].extend(toks)
                sp_tokens[b] = toks[-1]
                sp_positions[b] += len(toks)
                sp_kv_lens[b] += len(toks)
                sp_steps[b] += len(toks)
                emitted += len(toks)
                # Acceptance counts MINED drafts only (the scheduler's
                # cap): an accidental target match on a 0-padded row
                # commits a correct token but is not an acceptance.
                accepted += min(n, int(mined[b]))
            return targets

        spec_iter()  # warmup (compiles the spec variant)
        proposed = accepted = emitted = 0
        t0 = time.perf_counter()
        for _ in range(n_iter):
            spec_iter()
        spec_elapsed = time.perf_counter() - t0
        result["spec"] = {
            "tokens_per_sec_per_chip": round(emitted / spec_elapsed, 1),
            "spec_enable": bool(_spec_env("DYNT_SPEC_ENABLE")),
            "max_k": int(_spec_env("DYNT_SPEC_MAX_K")),
            "k": spec_k,
            "steps": n_iter,
            "proposed": proposed,
            "accepted": accepted,
            "acceptance_rate": round(accepted / proposed, 4)
                               if proposed else 0.0,
            "speedup_vs_decode": round(
                (emitted / spec_elapsed) / tok_per_sec, 3),
        }

    # G2-active vs G2-idle serving (ROADMAP item 2 / ISSUE 8): the same
    # fused-block decode loop while the REAL OffloadManager drains a
    # continuous store burst — gathers ride the bench loop's dispatch
    # gap exactly as the serving scheduler's run_in_gap window, with the
    # DYNT_OFFLOAD_* budget active. `active_vs_idle` is the acceptance
    # number (>= 0.8 target; the unbudgeted round-5 collapse was 42/170
    # = 0.25).
    if do_kvbm and os.environ.get("DYNT_BENCH_KVBM", "1") != "0":
        import queue as thread_queue
        import threading

        from dynamo_tpu.block_manager.offload import OffloadManager

        gap_q: thread_queue.Queue = thread_queue.Queue()

        def run_in_gap(fn):
            out: thread_queue.Queue = thread_queue.Queue(1)

            def wrapped():
                try:
                    out.put((fn(), None))
                except Exception as exc:  # noqa: BLE001
                    out.put((None, exc))

            gap_q.put(wrapped)
            return out

        def step_block_with_gap():
            step_block()
            while True:  # drain gathers into the dispatch gap
                try:
                    fn = gap_q.get_nowait()
                except thread_queue.Empty:
                    break
                fn()

        n_bench_pages = max(1, next_page - 1)
        sunk = {"blocks": 0, "bytes": 0}

        def sink(h, block_arr, parent):
            sunk["blocks"] += 1
            sunk["bytes"] += block_arr.nbytes

        mgr = OffloadManager(
            lookup_pages=lambda hs: [1 + (h % n_bench_pages) for h in hs],
            gather=runner.gather_pages_device,
            run_in_step=run_in_gap,
            sink=sink,
        )
        feeding = threading.Event()
        feeding.set()

        def feeder():
            seq = 0
            while feeding.is_set():
                mgr.notify_stored(list(range(seq, seq + 32)), parent=None)
                seq += 32
                time.sleep(0.02)

        feed_thread = threading.Thread(target=feeder, daemon=True)
        feed_thread.start()
        try:
            step_block_with_gap()  # settle
            t0 = time.perf_counter()
            for _ in range(n_blocks):
                step_block_with_gap()
            drain()
            active_elapsed = time.perf_counter() - t0
        finally:
            feeding.clear()
            feed_thread.join(timeout=5)
            mgr.close()
        positions -= (n_blocks + 1) * block
        kv_lens -= (n_blocks + 1) * block
        steps_np -= (n_blocks + 1) * block
        active_tok = batch * n_blocks * block / active_elapsed
        result["kvbm_offload"] = {
            "idle_tokens_per_sec": round(tok_per_sec, 1),
            "active_tokens_per_sec": round(active_tok, 1),
            "active_vs_idle": round(active_tok / tok_per_sec, 3),
            "offloaded_blocks": sunk["blocks"],
            "offloaded_mb": round(sunk["bytes"] / 2**20, 1),
        }

    # On-chip prefill throughput + MFU headline (VERDICT r3 item 2): time
    # PIPELINED prefill chunks exactly like the decode bench pipelines
    # decode blocks — return_device defers the host sync so the dispatch
    # round trip (tunnel-dominated here) overlaps the next chunk's
    # compute. MFU denominator: model forward FLOPs (2 * active params
    # per token) over the chip's peak bf16 FLOPs.
    if do_prefill:
        chunk_len = runner.max_prefill_chunk
        n_chunks = 8
        # All chunks write the SAME page range: they are independent
        # prefills whose KV content is irrelevant to timing, and reuse
        # keeps the bench inside small NUM_PAGES pools (a 14.5GB model
        # leaves little HBM for benchmark-only pages).
        pf_table = np.zeros(max_pages_per_seq, np.int32)
        pf_pages = chunk_len // PAGE_SIZE + 1
        avail = num_pages - next_page
        assert avail >= pf_pages, (
            f"prefill bench needs {pf_pages} free pages, pool has {avail}")
        pf_table[:pf_pages] = np.arange(next_page, next_page + pf_pages)
        pf_prompt = rng.integers(0, config.vocab_size,
                                 chunk_len).astype(np.int32)

        def prefill_pass():
            pending = []
            for _ in range(n_chunks):
                pending.append(runner.prefill_chunk(
                    pf_prompt, 0, pf_table, chunk_len,
                    (0.0, 1.0, 0, 0), return_device=True))
            for tok in pending:
                np.asarray(tok)

        prefill_pass()  # compile + settle
        pf_trials = []
        for _ in range(3):
            t0 = time.perf_counter()
            prefill_pass()
            pf_trials.append(time.perf_counter() - t0)
        pf_elapsed = sorted(pf_trials)[1]
        pf_tok_per_sec = n_chunks * chunk_len / pf_elapsed
        peak = 1.0
        for key, tf in PEAK_TFLOPS.items():
            if key in device_kind:
                peak = tf
                break
        # Forward FLOPs/token: 2 * ACTIVE matmul params (MoE counts only
        # the routed experts; the embedding gather does no matmul) +
        # attention score/value FLOPs over the mean context.
        h = config.hidden
        per_layer = (h * config.n_q_heads * config.head_dim
                     + 2 * h * config.n_kv_heads * config.head_dim
                     + config.n_q_heads * config.head_dim * h)
        if config.n_experts:
            em = config.expert_mlp_hidden or config.mlp_hidden
            per_layer += config.n_experts_active * 3 * h * em
            per_layer += h * config.n_experts  # router
            per_layer += 3 * h * (getattr(config, "n_shared_experts", 0)
                                  * em)
        else:
            per_layer += 3 * h * config.mlp_hidden
        matmul_params = (config.n_layers * per_layer
                         + config.vocab_size * h)  # the head matmul
        attn_flops = (2 * 2 * config.n_layers * config.n_q_heads
                      * config.head_dim * (chunk_len / 2))
        flops_per_tok = 2 * matmul_params + attn_flops
        mfu = pf_tok_per_sec * flops_per_tok / (peak * 1e12)
        result["prefill"] = {
            "tokens_per_sec_per_chip": round(pf_tok_per_sec, 1),
            "chunk_len": chunk_len,
            "mfu": round(mfu, 4),
        }

    # Prefill/TTFT tail: p50/p99 single-request prefill latency at a few
    # ISLs (the reference's aiperf sweeps report TTFT alongside decode —
    # BASELINE.md measurement method). Tunnel-RTT-dominated on a
    # remote-attached chip (documented in BASELINE.md).
    if do_ttft:
        ttft = {}
        bt = np.zeros(max_pages_per_seq, np.int32)
        for isl in (128, 512, 1024):
            if isl > runner.config.max_context - 8:
                continue
            pages = isl // PAGE_SIZE + 1
            bt[:] = 0
            bt[:pages] = np.arange(1, pages + 1)
            prompt = rng.integers(0, config.vocab_size, isl).astype(np.int32)
            # TTFT = time to run the full prefill (chunked at the largest
            # bucket) + sample the first token, prompt cold in the engine.
            budget = runner.max_prefill_chunk
            samples = []
            for trial in range(12):
                t0 = time.perf_counter()
                start = 0
                tok = None
                dispatch_s = 0.0
                while start < isl:
                    chunk = prompt[start:start + budget]
                    # Deferred readback per chunk, as the serving
                    # scheduler dispatches (dispatch-submit cost is the
                    # host/tunnel share; the final drain closes the
                    # device-stream window).
                    d0 = time.perf_counter()
                    tok = runner.prefill_chunk(chunk, start, bt,
                                               start + len(chunk),
                                               (0.0, 1.0, 0, 0),
                                               return_device=True)
                    dispatch_s += time.perf_counter() - d0
                    start += len(chunk)
                np.asarray(tok)
                total_ms = (time.perf_counter() - t0) * 1e3
                samples.append((total_ms, dispatch_s * 1e3))
            samples = sorted(samples[2:])  # drop compile-warmup trials
            p50 = samples[len(samples) // 2]
            p99 = samples[min(len(samples) - 1, int(len(samples) * 0.99))]
            ttft[str(isl)] = {
                "p50_ms": round(p50[0], 2),
                "p99_ms": round(p99[0], 2),
                # Decomposition of the p50 sample (BENCH_r06: the
                # attributable TTFT that retires the tunnel hypothesis)
                "p50_host_dispatch_ms": round(p50[1], 2),
                "p50_device_ms": round(max(0.0, p50[0] - p50[1]), 2),
            }
        result["ttft"] = ttft
    return result


def bench_disagg_point(requests: int = 16) -> dict:
    """Pipelined vs serial disaggregated prefill on the mocker xPyD
    profile (measured v5e step physics + modeled per-block KV handoff,
    TIMING_PRESETS) — the chip-free overlap point BENCH_r06 records next
    to the silicon numbers. TTFT falls because chunk i's handoff
    overlaps chunk i+1's compute; ITL is untouched by construction
    (docs/disaggregation.md)."""
    import asyncio

    from dynamo_tpu.mocker.engine import MockerConfig
    from dynamo_tpu.mocker.loadgen import OfflineReplay, synthesize_trace

    # Long prompts + moderate speedup keep the modeled handoff delta an
    # order of magnitude above asyncio timer jitter (sub-ms sleeps at
    # high speedup ratios drown the signal), and the arrival rate sits
    # below the 2-engine prefill service rate so queueing noise doesn't
    # swamp the p50.
    records = synthesize_trace(requests, rate_rps=5.0, isl_mean=4096,
                               osl_mean=32, seed=11)
    cfg = MockerConfig.from_timing_preset(
        "tpu-v5e-qwen3-0.6b", speedup_ratio=10.0,
        max_prefill_tokens_per_step=512)

    async def both() -> tuple[dict, dict]:
        pipe = await OfflineReplay(mode="disagg", num_workers=2,
                                   num_prefill_workers=2, config=cfg,
                                   disagg_pipeline=True).run(records)
        serial = await OfflineReplay(mode="disagg", num_workers=2,
                                     num_prefill_workers=2, config=cfg,
                                     disagg_pipeline=False).run(records)
        return pipe.summary(), serial.summary()

    pipe, serial = asyncio.run(both())
    return {
        "profile": "tpu-v5e-qwen3-0.6b xPyD (2P/2D, mocker)",
        "pipelined_ttft_ms": pipe["ttft_ms"],
        "serial_ttft_ms": serial["ttft_ms"],
        "pipelined_itl_ms": pipe["itl_ms"],
        "serial_itl_ms": serial["itl_ms"],
        "ttft_p50_speedup": round(
            serial["ttft_ms"]["p50"] / max(pipe["ttft_ms"]["p50"], 1e-9), 3),
    }


def bench_session_point() -> dict:
    """Session-cache A/B for BENCH_MULTI (ROADMAP item 2 / ISSUE 11):
    two-turn conversations with ~zero natural cross-session overlap
    against a KV-routed 2-worker mocker pair — cold turn-0 vs cached
    turn-1 TTFT with explicit pinning + session affinity ON, and the
    same traffic with the markers OFF (implicit-overlap baseline).
    Target on silicon: cached-turn TTFT <= the kvbm G1 hit number
    (BENCH_MULTI.kvbm_ttft: 2.7ms hit vs 6.2ms cold); here the mocker's
    measured v5e step physics stand in for the chips
    (docs/prompt-caching.md)."""
    import asyncio
    import uuid

    from dynamo_tpu.bench import MultiturnBench
    from dynamo_tpu.frontend import Frontend
    from dynamo_tpu.mocker import MockerConfig, MockerWorker
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    def _cfg(cluster: str) -> RuntimeConfig:
        cfg = RuntimeConfig.from_env()
        cfg.discovery_backend = "mem"
        cfg.discovery_path = cluster
        cfg.request_plane = "tcp"
        cfg.tcp_host = "127.0.0.1"
        cfg.event_plane = "mem"
        cfg.system_enabled = False
        cfg.lease_ttl_secs = 1.0
        return cfg

    async def one_side(session_cache: bool) -> dict:
        cluster = uuid.uuid4().hex
        workers = []
        for _ in range(2):
            rt = await DistributedRuntime(_cfg(cluster)).start()
            worker = MockerWorker(
                rt, model_name="mock-model",
                config=MockerConfig.from_timing_preset(
                    "tpu-v5e-qwen3-0.6b", speedup_ratio=20.0,
                    num_blocks=4096),
                load_publish_interval=0.2)
            await worker.start()
            workers.append((rt, worker))
        frt = await DistributedRuntime(_cfg(cluster)).start()
        frontend = Frontend(frt, host="127.0.0.1", port=0,
                            router_mode="kv")
        await frontend.start()
        try:
            for _ in range(100):
                if frontend.manager.get("mock-model") is not None:
                    break
                await asyncio.sleep(0.05)
            # ~13 mock-tokenizer tokens per synthetic word: 128 words
            # is ~1.7k prompt tokens — two turns stay inside the mock
            # card's 8k context with a prefill big enough to dominate
            # TTFT.
            bench = MultiturnBench(
                f"http://127.0.0.1:{frontend.port}", "mock-model",
                turns=2, isl_mean=128, osl_mean=8,
                followup_isl_mean=8, session_cache=session_cache)
            level = await bench.run_level(concurrency=4,
                                          conversations=16)
            return level.summary()
        finally:
            await frontend.close()
            await frt.shutdown()
            for rt, worker in workers:
                await worker.close()
                await rt.shutdown()

    async def both() -> tuple[dict, dict]:
        return await one_side(True), await one_side(False)

    on, off = asyncio.run(both())

    def turn_ttft(summary: dict, turn: int):
        return summary.get("ttft_ms_by_turn", {}).get(str(turn))

    cold = turn_ttft(on, 0)
    cached = turn_ttft(on, 1)
    return {
        "profile": "2-worker v5e mocker, kv router, 2-turn sessions, "
                   "~zero cross-session overlap",
        "pinned_cold_ttft_ms": cold,
        "pinned_cached_ttft_ms": cached,
        "cached_speedup": (round(cold / cached, 2)
                           if cold and cached else None),
        "unpinned_cold_ttft_ms": turn_ttft(off, 0),
        "unpinned_cached_ttft_ms": turn_ttft(off, 1),
        "errors": on.get("errors", 0) + off.get("errors", 0),
    }


def bench_drain_point() -> dict:
    """Graceful-drain point for BENCH_r06 (ISSUE 15 / docs/
    fault-tolerance.md departure ladder): evict one worker of a mocker
    fleet mid-decode and record what the departure cost — wall time of
    the drain (announce -> handoff -> deregistration-ready), sequences
    per ladder rung, and the re-prefilled-token count on the KV-handoff
    path (the zero-drop headline: 0 on the handoff rung vs a full
    prompt re-prefill per stream on the replay fallback). Runs the same
    in-process scenario the chaos-drain CI job gates on
    (dynamo_tpu/mocker/drain_chaos.py)."""
    import asyncio

    from dynamo_tpu.mocker.drain_chaos import DrainChaosParams, run_scenario

    params = DrainChaosParams(n_workers=2, n_streams=8, max_tokens=40,
                              decode_base_ms=20.0)
    report = asyncio.run(run_scenario(params, fallback_pass=True))

    def rungs(key: str) -> dict:
        rep = report[key]["drain_report"] or {}
        return {"handoff": len(rep.get("handoff") or []),
                "replay": len(rep.get("replay") or []),
                "errored": rep.get("errored", 0),
                "duration_ms": rep.get("duration_ms"),
                "reprefill_tokens": report[key]["reprefill_tokens"]}

    return {
        "profile": (f"{params.n_workers}-worker mocker fleet, "
                    f"{params.n_streams} live streams, evict 1 "
                    "mid-decode"),
        "deadline_secs": params.deadline_secs,
        "passed": report["passed"],
        "handoff_path": rungs("drain_handoff"),
        "replay_fallback": rungs("drain_replay"),
        "bit_identical": all(
            c["ok"] for c in report["assertions"]
            if c["name"] == "bit_identical_to_undrained_run"),
    }


def bench_cold_start_point() -> dict:
    """Cold-start ladder A/B for BENCH_r07 (ISSUE 17 / docs/
    elasticity.md fast-start plane). Two layers:

    * a closed-form matrix from the v5e-calibrated cold-start preset
      (mocker/engine.py coldstart_phases): arrival total with peer
      striping vs single-source G4 fetch, crossed with warm vs cold
      compile cache — the headline speedups the fast-start plane buys;
    * a measured point: the quick chaos-spot scenario (evict+replace
      under a live ramp, dynamo_tpu/mocker/spot_chaos.py) recording the
      replacement's wall-clock first-token and capacity-recovery times
      against its pinned budget — the same contract the chaos-spot CI
      job gates on."""
    import asyncio

    from dynamo_tpu.mocker.engine import MockerConfig, TIMING_PRESETS
    from dynamo_tpu.mocker.engine import coldstart_phases
    from dynamo_tpu.mocker.spot_chaos import SpotChaosParams, run_scenario

    preset = TIMING_PRESETS["tpu-v5e-coldstart"]

    def cell(striped: bool, warm: bool) -> dict:
        cfg = MockerConfig(**{**preset, "fetch_striped": striped,
                              "compile_cache_warm": warm})
        phases = coldstart_phases(cfg)
        return {"phases_s": {k: round(v, 3) for k, v in phases.items()},
                "total_s": round(sum(phases.values()), 3)}

    matrix = {
        "striped_warm": cell(True, True),
        "striped_cold": cell(True, False),
        "single_warm": cell(False, True),
        "single_cold": cell(False, False),
    }
    params = SpotChaosParams(n_workers=2, n_streams=10,
                             evict_cycles=1, streams_before_evict=3)
    report = asyncio.run(run_scenario(params))
    cycles = report["spot"]["cycles"]
    return {
        "profile": (f"v5e preset: {preset['weight_bytes'] / 1e9:.1f}GB "
                    f"weights, {preset['fetch_donors']} donors x "
                    f"{preset['fetch_gbps_per_donor']:.0f}Gbps striped "
                    f"vs {preset['fetch_gbps_single']:.0f}Gbps single"),
        "modeled": matrix,
        "striped_fetch_speedup": round(
            matrix["single_warm"]["phases_s"]["fetch"]
            / matrix["striped_warm"]["phases_s"]["fetch"], 2),
        "warm_cache_speedup": round(
            matrix["striped_cold"]["total_s"]
            / matrix["striped_warm"]["total_s"], 2),
        "measured_spot": {
            "passed": report["passed"],
            "budget_secs": params.coldstart_budget_secs,
            "first_token_secs": [
                c["coldstart"] and round(c["coldstart"]["total_secs"], 3)
                for c in cycles],
            "capacity_recovered_secs": [
                c["recovered_secs"] and round(c["recovered_secs"], 3)
                for c in cycles],
        },
    }


def bench_goodput_point() -> dict:
    """Goodput-vs-load curve with the overload-control loop off vs on
    (ROADMAP item 4 / ISSUE 9) — the chip-free robustness point
    BENCH_MULTI records next to the silicon numbers. An open-loop
    Poisson ramp walks offered load past the mocker cluster's capacity
    knee twice; per offered-rate bucket the curve reports SLO-good
    requests/s and the shed fraction. The headline is dominance past the
    knee: the deadline-aware admission loop sheds early instead of
    FCFS-ing doomed work into late 504s, so goodput flattens instead of
    collapsing (dynamo_tpu/mocker/overload.py, the same scenario the
    chaos-overload CI job gates on)."""
    import asyncio

    from dynamo_tpu.mocker.overload import OverloadParams, run_scenario

    params = OverloadParams(ramp_secs=16.0, ramp_end_rps=28.0)
    report = asyncio.run(run_scenario(params, pd_sweep=False))

    def curve(key: str) -> list[dict]:
        return [{"offered_rps": b["offered_rps"],
                 "goodput_rps": b["goodput_rps"],
                 "shed_frac": b["shed_frac"]}
                for b in report[key]["buckets"]]

    knee = report.get("knee_bucket", 0)
    on = report["ramp_on"]["buckets"]
    off = report["ramp_off"]["buckets"]
    past = range(knee + 1, min(len(on), len(off)))
    return {
        "profile": (f"{params.n_decode}-worker mocker, open-loop ramp "
                    f"{params.ramp_start_rps}->{params.ramp_end_rps} rps"),
        "slo_ttft_ms": params.slo_ttft_ms,
        "deadline_secs": params.deadline_secs,
        "knee_bucket": knee,
        "loop_on": curve("ramp_on"),
        "loop_off": curve("ramp_off"),
        "past_knee_goodput_on": round(
            sum(on[i]["goodput_rps"] for i in past), 2),
        "past_knee_goodput_off": round(
            sum(off[i]["goodput_rps"] for i in past), 2),
        "assertions_passed": report["passed"],
    }


def bench_two_class_point() -> dict:
    """Two-class goodput A/B for BENCH_MULTI (ROADMAP item 5 /
    ISSUE 14): an interactive tenant at a fixed below-knee rate plus a
    batch tenant ramping ~2x past the knee, served twice — untagged
    FCFS vs the full QoS plane (priority classes, fair-share quotas,
    class-strict queues, preempt-to-park). The headline: the
    interactive goodput curve holds flat past the knee at <= 10% total
    goodput cost, with batch absorbing the shed and the preemptions
    (dynamo_tpu/mocker/overload.py, the same scenario the
    chaos-two-tenant CI job gates on; docs/multi-tenancy.md)."""
    import asyncio

    from dynamo_tpu.mocker.overload import (
        TwoTenantParams,
        run_two_tenant_scenario,
    )

    params = TwoTenantParams(ramp_secs=16.0, batch_end_rps=20.0)
    report = asyncio.run(run_two_tenant_scenario(params))

    def tenant_curve(key: str, tenant: str) -> list[dict]:
        return [{"offered_rps": b["offered_rps"],
                 "goodput_rps": b["goodput_rps"],
                 "shed_frac": b["shed_frac"]}
                for b in report[key]["tenant_buckets"].get(tenant, [])]

    qos, base = report["qos_on"], report["qos_off"]
    return {
        "profile": (f"{params.n_decode}-worker mocker; interactive "
                    f"{params.interactive_rps} rps fixed, batch "
                    f"{params.batch_start_rps}->{params.batch_end_rps} "
                    "rps ramp"),
        "slo_ttft_ms": params.slo_ttft_ms,
        "knee_bucket": report.get("knee_bucket", 0),
        "interactive_qos": tenant_curve("qos_on", "interactive"),
        "interactive_fcfs": tenant_curve("qos_off", "interactive"),
        "batch_qos": tenant_curve("qos_on", "batch"),
        "batch_fcfs": tenant_curve("qos_off", "batch"),
        "good_total_qos": qos["good_total"],
        "good_total_fcfs": base["good_total"],
        "total_cost_frac": (round(1 - qos["good_total"]
                                  / base["good_total"], 4)
                            if base["good_total"] else None),
        "preempt": {k: qos["metrics"][f"preempt_{k}"]
                    for k in ("park", "migrate", "resume")},
        "tenant_shed": {
            "batch": qos["metrics"]["tenant_shed_batch"],
            "interactive": qos["metrics"]["tenant_shed_interactive"],
        },
        "assertions_passed": report["passed"],
    }


def main() -> None:
    import jax

    from dynamo_tpu.runtime.config import env as _env

    # Honor DYNT_JAX_PLATFORM BEFORE the first backend touch (CPU smoke
    # runs; the frozen JAX_PLATFORMS env can't override the tunnel
    # platform, the live config update can — see parallel/mesh.py).
    if _env("DYNT_JAX_PLATFORM"):
        jax.config.update("jax_platforms", _env("DYNT_JAX_PLATFORM"))

    device = jax.devices()[0]
    device_kind = getattr(device, "device_kind", "cpu").lower()

    env_model = os.environ.get("DYNT_BENCH_MODEL")
    model_path = os.environ.get("DYNT_BENCH_MODEL_PATH")
    if env_model or model_path:
        # Single-model mode: bench exactly what the caller asked for.
        result = bench_one(
            env_model or "qwen3-0.6b", model_path=model_path,
            batch=int(os.environ.get("DYNT_BENCH_BS", "8")),
            kv_dtype=os.environ.get("DYNT_BENCH_KV_DTYPE", "model"),
            weight_dtype=os.environ.get("DYNT_BENCH_WEIGHT_DTYPE",
                                        "model"),
            num_pages=int(os.environ.get("DYNT_BENCH_PAGES", "1024")),
            prompt_len=int(os.environ.get("DYNT_BENCH_CTX", "256")),
            decode_steps=int(os.environ.get("DYNT_BENCH_STEPS", "256")),
            prefill_chunk=int(os.environ.get("DYNT_BENCH_PREFILL_CHUNK",
                                             "1024")),
            do_prefill=os.environ.get("DYNT_BENCH_PREFILL", "1") != "0",
            do_ttft=os.environ.get("DYNT_BENCH_TTFT", "1") != "0",
            device_kind=device_kind,
        )
        print(json.dumps(result))
        return

    if "cpu" in device_kind:
        # CPU smoke: only the toy — a 7B random-init forward on CPU is
        # tens of minutes of compile+run for zero perf signal.
        result = bench_one("qwen3-0.6b", device_kind=device_kind)
        if os.environ.get("DYNT_BENCH_DISAGG", "1") != "0":
            result["disagg"] = bench_disagg_point()
        if os.environ.get("DYNT_BENCH_GOODPUT", "1") != "0":
            result["goodput_vs_load"] = bench_goodput_point()
        if os.environ.get("DYNT_BENCH_TWO_CLASS", "1") != "0":
            result["two_class_goodput"] = bench_two_class_point()
        if os.environ.get("DYNT_BENCH_SESSION", "1") != "0":
            result["session_cache"] = bench_session_point()
        if os.environ.get("DYNT_BENCH_DRAIN", "1") != "0":
            result["drain"] = bench_drain_point()
        if os.environ.get("DYNT_BENCH_COLD_START", "1") != "0":
            result["cold_start"] = bench_cold_start_point()
        print(json.dumps(result))
        return

    # Flagship-first (VERDICT r4 item 3): the driver-captured headline is
    # the representative 7B config in its FASTEST serving shape — W4A16
    # weights (packed-int4 Pallas matmuls, ops/q4_linear.py: 2.87x decode
    # over bf16 weights / 1.70x over W8A16, measured r5) + int8 KV (the
    # capacity lever; at 7B bf16 weights + bf16 KV exceed HBM).
    # Secondaries: the int8- and bf16-weight 7B configs and the toy.
    # One retry on the flagship: the dev chip is tunnel-attached and a
    # transient relay error (HTTP 500 from the remote-compile helper,
    # observed r5) must not cost the round its headline number.
    # BENCH_r06 capture prep (ROADMAP item 1): speculation ON for the
    # flagship serving block (the spec block records acceptance_rate and
    # the DYNT_SPEC_MAX_K it ran) so spec, kvbm_offload, disagg, and
    # q4_ablation are all captured by ONE `python bench.py` on silicon.
    os.environ.setdefault("DYNT_SPEC_ENABLE", "1")
    try:
        result = bench_one("mistral-7b", kv_dtype="int8",
                           weight_dtype="int4", num_pages=448,
                           device_kind=device_kind)
    except Exception:  # noqa: BLE001 — retry once after a clean slate
        import traceback

        print("flagship bench failed once; retrying after reset:",
              file=sys.stderr)
        traceback.print_exc()
        gc.collect()
        jax.clear_caches()
        time.sleep(5)
        result = bench_one("mistral-7b", kv_dtype="int8",
                           weight_dtype="int4", num_pages=448,
                           device_kind=device_kind)
    secondary = []
    for label, kwargs in (
        ("mistral-7b int8 weights",
         dict(kv_dtype="int8", weight_dtype="int8", num_pages=448,
              do_ttft=False)),
        ("mistral-7b bf16 weights",
         dict(kv_dtype="int8", num_pages=448, do_ttft=False)),
        ("qwen3-0.6b", dict(do_ttft=False)),
    ):
        gc.collect()
        jax.clear_caches()
        try:
            secondary.append(bench_one(
                "mistral-7b" if "mistral" in label else "qwen3-0.6b",
                device_kind=device_kind, **kwargs))
        except Exception as exc:  # noqa: BLE001 — the flagship number
            # must survive a secondary-bench failure
            secondary.append({"metric": label, "error": repr(exc)})
    result["secondary"] = secondary
    if os.environ.get("DYNT_BENCH_Q4_ABLATE", "1") != "0":
        # Kernel-level decomposition of the flagship number: pack-layout
        # variant x block-size sweep over the mistral-7b projection
        # geometries, with per-point effective bandwidth (the same
        # harness CI runs in interpret mode — scripts/q4_ablate.py).
        try:
            gc.collect()
            jax.clear_caches()
            from dynamo_tpu.perf.q4_ablation import run_ablation

            result["q4_ablation"] = run_ablation(
                mode="tpu", gks=(0, 2, 4))
        except Exception as exc:  # noqa: BLE001 — an ablation failure
            # must never cost the round its silicon numbers
            result["q4_ablation"] = {"error": repr(exc)}
    if os.environ.get("DYNT_BENCH_DISAGG", "1") != "0":
        try:
            result["disagg"] = bench_disagg_point()
        except Exception as exc:  # noqa: BLE001 — chip-free point must
            # never cost the round its silicon numbers
            result["disagg"] = {"error": repr(exc)}
    if os.environ.get("DYNT_BENCH_GOODPUT", "1") != "0":
        try:
            result["goodput_vs_load"] = bench_goodput_point()
        except Exception as exc:  # noqa: BLE001 — chip-free point must
            # never cost the round its silicon numbers
            result["goodput_vs_load"] = {"error": repr(exc)}
    if os.environ.get("DYNT_BENCH_TWO_CLASS", "1") != "0":
        try:
            result["two_class_goodput"] = bench_two_class_point()
        except Exception as exc:  # noqa: BLE001 — chip-free point must
            # never cost the round its silicon numbers
            result["two_class_goodput"] = {"error": repr(exc)}
    if os.environ.get("DYNT_BENCH_SESSION", "1") != "0":
        try:
            result["session_cache"] = bench_session_point()
        except Exception as exc:  # noqa: BLE001 — chip-free point must
            # never cost the round its silicon numbers
            result["session_cache"] = {"error": repr(exc)}
    if os.environ.get("DYNT_BENCH_DRAIN", "1") != "0":
        try:
            result["drain"] = bench_drain_point()
        except Exception as exc:  # noqa: BLE001 — chip-free point must
            # never cost the round its silicon numbers
            result["drain"] = {"error": repr(exc)}
    if os.environ.get("DYNT_BENCH_COLD_START", "1") != "0":
        try:
            result["cold_start"] = bench_cold_start_point()
        except Exception as exc:  # noqa: BLE001 — chip-free point must
            # never cost the round its silicon numbers
            result["cold_start"] = {"error": repr(exc)}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
