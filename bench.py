"""Benchmark: steady-state decode throughput of the flagship model on the
available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

`vs_baseline` is the fraction of this chip's HBM-bandwidth roofline for the
model (decode is memory-bound: every step streams all weights + the active
KV). The reference publishes only relative numbers (BASELINE.md), so roofline
fraction is the honest hardware-normalized comparison: 1.0 == perfect
bandwidth utilization, and the reference's vLLM-on-H100 recipes sit around
0.5-0.7 of their roofline on the same measure.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


import os as _os

MODEL = _os.environ.get("DYNT_BENCH_MODEL", "qwen3-0.6b")
BATCH = int(_os.environ.get("DYNT_BENCH_BS", "8"))
PAGE_SIZE = 16
NUM_PAGES = int(_os.environ.get("DYNT_BENCH_PAGES", "1024"))
PROMPT_LEN = int(_os.environ.get("DYNT_BENCH_CTX", "256"))
DECODE_STEPS = int(_os.environ.get("DYNT_BENCH_STEPS", "256"))
# Prefill-headline chunk length: big chunks amortize per-chunk overhead
# onto the MXU (the serving scheduler's chunked-prefill budget plays the
# same role); the table width grows to fit it.
PREFILL_CHUNK = int(_os.environ.get("DYNT_BENCH_PREFILL_CHUNK", "1024"))
MAX_PAGES_PER_SEQ = max(64, PREFILL_CHUNK // PAGE_SIZE + 2)
# HBM bandwidth by chip generation (GB/s) for the roofline denominator.
HBM_GBPS = {"v5 lite": 819.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1640.0,
            "cpu": 50.0}


def _param_bytes(config) -> int:
    h, v = config.hidden, config.vocab_size
    per_layer = (
        h * config.n_q_heads * config.head_dim
        + 2 * h * config.n_kv_heads * config.head_dim
        + config.n_q_heads * config.head_dim * h
        + 3 * h * config.mlp_hidden
        + 2 * h
    )
    total = v * h + h + config.n_layers * per_layer
    if not config.tie_embeddings:
        total += h * v
    return total * 2  # bf16


def main() -> None:
    import jax

    from dynamo_tpu.engine import ModelRunner, RunnerConfig
    from dynamo_tpu.models import get_config
    from dynamo_tpu.parallel import MeshConfig, make_mesh
    from dynamo_tpu.runtime.config import env as _env

    # Honor DYNT_JAX_PLATFORM BEFORE the first backend touch (CPU smoke
    # runs; the frozen JAX_PLATFORMS env can't override the tunnel
    # platform, the live config update can — see parallel/mesh.py).
    if _env("DYNT_JAX_PLATFORM"):
        jax.config.update("jax_platforms", _env("DYNT_JAX_PLATFORM"))

    device = jax.devices()[0]
    device_kind = getattr(device, "device_kind", "cpu").lower()

    # With DYNT_BENCH_MODEL_PATH set, bench a REAL checkpoint (architecture
    # from its config.json, weights from safetensors) instead of the
    # random-init preset.
    import os

    model_path = os.environ.get("DYNT_BENCH_MODEL_PATH")
    host_params = None
    if model_path:
        from dynamo_tpu.models.checkpoint import (
            config_from_checkpoint,
            load_params,
        )

        config = config_from_checkpoint(model_path)
        host_params = load_params(model_path, config)
        model_label = config.name
    else:
        config = get_config(MODEL)
        model_label = MODEL
    kv_dtype = os.environ.get("DYNT_BENCH_KV_DTYPE", "model")
    runner = ModelRunner(
        config,
        RunnerConfig(page_size=PAGE_SIZE, num_pages=NUM_PAGES,
                     max_batch=BATCH, max_pages_per_seq=MAX_PAGES_PER_SEQ,
                     prefill_buckets=(256, PREFILL_CHUNK)
                     if PREFILL_CHUNK > 256 else (256,),
                     kv_dtype=kv_dtype),
        make_mesh(MeshConfig()),
        host_params,
        seed=0,
    )
    if kv_dtype != "model":
        model_label += f" kv={kv_dtype}"

    # Prefill BATCH sequences of PROMPT_LEN so decode runs with real KV.
    # Capacity covers prompt + warmup block + timed blocks — undersizing
    # would scatter KV through zero table entries into the shared scratch
    # page and silently corrupt the measured state.
    block = 64
    total_tokens = PROMPT_LEN + DECODE_STEPS + block
    pages_per_seq = total_tokens // PAGE_SIZE + 1
    tables = np.zeros((BATCH, MAX_PAGES_PER_SEQ), np.int32)
    rng = np.random.default_rng(0)
    next_page = 1
    for b in range(BATCH):
        tables[b, :pages_per_seq] = np.arange(next_page,
                                              next_page + pages_per_seq)
        next_page += pages_per_seq
        prompt = rng.integers(0, config.vocab_size, PROMPT_LEN).astype(np.int32)
        budget = runner.max_prefill_chunk
        start_tok = 0
        while start_tok < PROMPT_LEN:
            chunk = prompt[start_tok:start_tok + budget]
            runner.prefill_chunk(chunk, start_tok, tables[b],
                                 start_tok + len(chunk), (0.0, 1.0, 0, 0))
            start_tok += len(chunk)

    tokens = np.zeros(BATCH, np.int32)
    positions = np.full(BATCH, PROMPT_LEN, np.int32)
    kv_lens = np.full(BATCH, PROMPT_LEN + 1, np.int32)
    active = np.ones(BATCH, bool)
    temp = np.zeros(BATCH, np.float32)
    top_p = np.ones(BATCH, np.float32)
    top_k = np.zeros(BATCH, np.int32)
    seeds = np.zeros(BATCH, np.uint32)

    # Steady-state serving uses fused decode blocks (DYNT_DECODE_BLOCK;
    # lax.scan of K steps per compiled call) with PIPELINED dispatch
    # (DYNT_DECODE_PIPELINE): block d+1 consumes block d's tokens
    # ON-DEVICE, so the host readback of block d overlaps block d+1's
    # compute — exactly what the serving scheduler does
    # (engine/scheduler.py _decode_all).
    steps_np = np.zeros(BATCH, np.int32)

    # Table width bucketed to the live context (as the serving scheduler
    # does): the attention kernel streams the table extent's pages.
    from dynamo_tpu.engine.model_runner import bucket_table_width

    width = bucket_table_width(pages_per_seq, MAX_PAGES_PER_SEQ)
    btables = np.ascontiguousarray(tables[:, :width])

    state = {"tokens": tokens, "pending": None}

    def step_block():
        nonlocal positions, kv_lens, steps_np
        toks_dev = runner.decode_multi(
            state["tokens"], positions, btables, kv_lens, active, temp,
            top_p, top_k, seeds, steps_np, k=block, return_device=True)
        if state["pending"] is not None:
            np.asarray(state["pending"])  # stream block d while d+1 runs
        state["pending"] = toks_dev
        state["tokens"] = toks_dev[-1]  # device-side chain
        positions += block
        kv_lens += block
        steps_np += block

    def drain():
        if state["pending"] is not None:
            np.asarray(state["pending"])
            state["pending"] = None

    step_block()  # warmup (compile + first block)
    drain()

    # Median of three trials: the chip may be tunnel-attached/shared, and
    # a single window can catch a latency spike that says nothing about
    # the engine.
    n_blocks = DECODE_STEPS // block
    trials = []
    for _ in range(3):
        start = time.perf_counter()
        for _ in range(n_blocks):
            step_block()
        drain()
        trials.append(time.perf_counter() - start)
        # rewind positions so every trial measures the same context length
        positions -= n_blocks * block
        kv_lens -= n_blocks * block
        steps_np -= n_blocks * block
    elapsed = sorted(trials)[len(trials) // 2]
    tok_per_sec = BATCH * n_blocks * block / elapsed

    # Roofline: steps/sec ceiling = HBM_bw / (weights + active KV per step)
    hbm = 50.0
    for key, bw in HBM_GBPS.items():
        if key in device_kind:
            hbm = bw
            break
    kv_bytes_per_step = (
        config.n_layers * 2 * (PROMPT_LEN + DECODE_STEPS // 2) * BATCH
        * config.n_kv_heads * config.head_dim * 2
    )
    bytes_per_step = _param_bytes(config) + kv_bytes_per_step
    roofline_steps = hbm * 1e9 / bytes_per_step
    roofline_tok = roofline_steps * BATCH
    vs_baseline = tok_per_sec / roofline_tok

    result = {
        "metric": f"decode throughput {model_label} bs={BATCH} "
                  f"ctx={PROMPT_LEN} ({device_kind})",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
    }

    # On-chip prefill throughput + MFU headline (VERDICT r3 item 2): time
    # PIPELINED prefill chunks exactly like the decode bench pipelines
    # decode blocks — return_device defers the host sync so the dispatch
    # round trip (tunnel-dominated here) overlaps the next chunk's
    # compute. MFU denominator: model forward FLOPs (2 * active params
    # per token) over the chip's peak bf16 FLOPs.
    if os.environ.get("DYNT_BENCH_PREFILL", "1") != "0":
        PEAK_TFLOPS = {"v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0,
                       "v6e": 918.0, "cpu": 1.0}
        chunk_len = runner.max_prefill_chunk
        n_chunks = 8
        # All chunks write the SAME page range: they are independent
        # prefills whose KV content is irrelevant to timing, and reuse
        # keeps the bench inside small NUM_PAGES pools (a 14.5GB model
        # leaves little HBM for benchmark-only pages).
        pf_table = np.zeros(MAX_PAGES_PER_SEQ, np.int32)
        pf_pages = chunk_len // PAGE_SIZE + 1
        avail = NUM_PAGES - next_page
        assert avail >= pf_pages, (
            f"prefill bench needs {pf_pages} free pages, pool has {avail}")
        pf_table[:pf_pages] = np.arange(next_page, next_page + pf_pages)
        pf_prompt = rng.integers(0, config.vocab_size,
                                 chunk_len).astype(np.int32)

        def prefill_pass():
            pending = []
            for _ in range(n_chunks):
                pending.append(runner.prefill_chunk(
                    pf_prompt, 0, pf_table, chunk_len,
                    (0.0, 1.0, 0, 0), return_device=True))
            for tok in pending:
                np.asarray(tok)

        prefill_pass()  # compile + settle
        pf_trials = []
        for _ in range(3):
            t0 = time.perf_counter()
            prefill_pass()
            pf_trials.append(time.perf_counter() - t0)
        pf_elapsed = sorted(pf_trials)[1]
        pf_tok_per_sec = n_chunks * chunk_len / pf_elapsed
        peak = 1.0
        for key, tf in PEAK_TFLOPS.items():
            if key in device_kind:
                peak = tf
                break
        # Forward FLOPs/token: 2 * ACTIVE matmul params (MoE counts only
        # the routed experts; the embedding gather does no matmul) +
        # attention score/value FLOPs over the mean context.
        h = config.hidden
        per_layer = (h * config.n_q_heads * config.head_dim
                     + 2 * h * config.n_kv_heads * config.head_dim
                     + config.n_q_heads * config.head_dim * h)
        if config.n_experts:
            em = config.expert_mlp_hidden or config.mlp_hidden
            per_layer += config.n_experts_active * 3 * h * em
            per_layer += h * config.n_experts  # router
            per_layer += 3 * h * (getattr(config, "n_shared_experts", 0)
                                  * em)
        else:
            per_layer += 3 * h * config.mlp_hidden
        matmul_params = (config.n_layers * per_layer
                         + config.vocab_size * h)  # the head matmul
        attn_flops = (2 * 2 * config.n_layers * config.n_q_heads
                      * config.head_dim * (chunk_len / 2))
        flops_per_tok = 2 * matmul_params + attn_flops
        mfu = pf_tok_per_sec * flops_per_tok / (peak * 1e12)
        result["prefill"] = {
            "tokens_per_sec_per_chip": round(pf_tok_per_sec, 1),
            "chunk_len": chunk_len,
            "mfu": round(mfu, 4),
        }

    # Prefill/TTFT tail: p50/p99 single-request prefill latency at a few
    # ISLs (the reference's aiperf sweeps report TTFT alongside decode —
    # BASELINE.md measurement method). Skipped with DYNT_BENCH_TTFT=0.
    if os.environ.get("DYNT_BENCH_TTFT", "1") != "0":
        ttft = {}
        bt = np.zeros(MAX_PAGES_PER_SEQ, np.int32)
        for isl in (128, 512, 1024):
            if isl > runner.config.max_context - 8:
                continue
            pages = isl // PAGE_SIZE + 1
            bt[:] = 0
            bt[:pages] = np.arange(1, pages + 1)
            prompt = rng.integers(0, config.vocab_size, isl).astype(np.int32)
            # TTFT = time to run the full prefill (chunked at the largest
            # bucket) + sample the first token, prompt cold in the engine.
            budget = runner.max_prefill_chunk
            samples = []
            for trial in range(12):
                t0 = time.perf_counter()
                start = 0
                while start < isl:
                    chunk = prompt[start:start + budget]
                    runner.prefill_chunk(chunk, start, bt,
                                         start + len(chunk),
                                         (0.0, 1.0, 0, 0))
                    start += len(chunk)
                samples.append((time.perf_counter() - t0) * 1e3)
            samples = sorted(samples[2:])  # drop compile-warmup trials
            ttft[str(isl)] = {
                "p50_ms": round(samples[len(samples) // 2], 2),
                "p99_ms": round(samples[min(len(samples) - 1,
                                            int(len(samples) * 0.99))], 2),
            }
        result["ttft"] = ttft

    print(json.dumps(result))


if __name__ == "__main__":
    main()
