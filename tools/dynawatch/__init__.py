"""dynawatch — chip-free perf-regression gate over the bench dry run.

`scripts/bench_dry_run.py` exercises every modeled-performance subsystem
(cold start, drain handoff, q4 parity, spec decode, kvbm offload,
two-class goodput, session cache, disagg) on CPU and emits one JSON
report. dynawatch pins that report to blessed baselines so a refactor
that silently changes a modeled closed-form (cold-start totals, fetch
striping speedups), drops a drain handoff, or breaks q4 parity fails CI
*before* anyone burns chips reproducing it.

Two classes of metric, declared in SPEC below:

  * deterministic anchors — closed-form model outputs, integer event
    counts, pass/fail booleans. Tight or exact envelopes: any drift is
    a semantic change that must be blessed deliberately.
  * measured values — wall-clock latencies from the CPU mocker runs.
    Loose envelopes only (shared CI hosts are noisy); these catch
    catastrophic regressions, not percent-level ones.

Workflow:

    python scripts/bench_dry_run.py --json out.json
    python -m tools.dynawatch --report out.json             # gate
    python -m tools.dynawatch --report out.json --baseline-update
    python -m tools.dynawatch --validate                    # structure only

`--baseline-update` re-blesses `tools/dynawatch/baselines/*.json` from
the report (commit the diff — that IS the review surface for a perf
change). `--validate` checks the baseline files cover the SPEC without
running anything — cheap enough for the dependency-free lint job.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import Any, List, Optional, Sequence, Tuple

BASELINE_DIR = pathlib.Path(__file__).resolve().parent / "baselines"

# Comparison kinds:
#   exact — report value must equal the blessed value (ints, bools,
#           pinned floats like the SLO threshold).
#   rel   — |report - baseline| <= tol * max(|baseline|, 1e-9); for a
#           zero baseline the tolerance is absolute.
#   len   — report value is a list; its LENGTH is compared exactly
#           (parity_failures must stay empty).
_KINDS = ("exact", "rel", "len")

# (block, dotpath, kind, tol). Blocks mirror the dry-run report's eight
# scenario sections; dotpaths index into each block's JSON.
SPEC: List[Tuple[str, str, str, float]] = [
    # -- cold start: closed-form model + measured spot-join smoke ------
    ("cold_start", "modeled.striped_warm.total_s", "rel", 0.02),
    ("cold_start", "modeled.single_warm.total_s", "rel", 0.02),
    ("cold_start", "modeled.striped_cold.total_s", "rel", 0.02),
    ("cold_start", "modeled.single_cold.total_s", "rel", 0.02),
    ("cold_start", "striped_fetch_speedup", "rel", 0.05),
    ("cold_start", "warm_cache_speedup", "rel", 0.05),
    ("cold_start", "measured_spot.passed", "exact", 0.0),
    # -- drain: event counts are exact facts of the scenario -----------
    ("drain", "passed", "exact", 0.0),
    ("drain", "handoff_path.handoff", "exact", 0.0),
    ("drain", "handoff_path.replay", "exact", 0.0),
    ("drain", "handoff_path.errored", "exact", 0.0),
    ("drain", "handoff_path.reprefill_tokens", "exact", 0.0),
    ("drain", "replay_fallback.replay", "exact", 0.0),
    ("drain", "replay_fallback.errored", "exact", 0.0),
    # How far generation got before the kill landed is wall-clock
    # sensitive, so the replayed-token volume gets an envelope.
    ("drain", "replay_fallback.reprefill_tokens", "rel", 0.25),
    ("drain", "bit_identical", "exact", 0.0),
    # -- q4 ablation: parity is the contract -----------------------------
    ("q4_ablation", "schema_version", "exact", 0.0),
    ("q4_ablation", "points", "exact", 0.0),
    ("q4_ablation", "parity_failures", "len", 0.0),
    # -- speculative decode: proposal accounting -------------------------
    ("spec", "max_k", "exact", 0.0),
    ("spec", "k", "exact", 0.0),
    ("spec", "steps", "exact", 0.0),
    ("spec", "proposed", "exact", 0.0),
    # -- kvbm offload: block accounting ----------------------------------
    ("kvbm_offload", "offloaded_blocks", "exact", 0.0),
    ("kvbm_offload", "offloaded_mb", "rel", 0.05),
    # -- two-class goodput: scheduler invariants + loose volume ----------
    # The scenario's all-or-nothing verdict (and the exact interactive
    # shed count inside it) flexes with host load, so the gate pins the
    # structural facts instead: where FCFS knees, that shedding falls
    # on batch, and that interactive sheds stay near zero (a zero
    # baseline makes the rel tolerance absolute: <= 2 requests).
    ("two_class_goodput", "slo_ttft_ms", "exact", 0.0),
    ("two_class_goodput", "knee_bucket", "exact", 0.0),
    ("two_class_goodput", "tenant_shed.batch", "rel", 0.25),
    ("two_class_goodput", "tenant_shed.interactive", "rel", 2.0),
    ("two_class_goodput", "good_total_qos", "rel", 0.25),
    # -- session cache: correctness exact, the latency RATIO loose -------
    # (absolute ttft-ms swings 2-3x with box load; the cached/cold
    # ratio self-normalizes)
    ("session_cache", "errors", "exact", 0.0),
    ("session_cache", "cached_speedup", "rel", 0.75),
    # -- disagg: measured mocker latencies, loose envelopes --------------
    ("disagg", "pipelined_ttft_ms.p50", "rel", 0.75),
    ("disagg", "serial_ttft_ms.p50", "rel", 0.75),
    ("disagg", "pipelined_itl_ms.p50", "rel", 0.75),
    ("disagg", "serial_itl_ms.p50", "rel", 0.75),
]

REQUIRED_BLOCKS = tuple(sorted({block for block, *_ in SPEC}))


def _resolve(obj: Any, dotpath: str) -> Any:
    """Index `a.b.c` into nested dicts; None when any hop is missing."""
    for hop in dotpath.split("."):
        if not isinstance(obj, dict) or hop not in obj:
            return None
        obj = obj[hop]
    return obj


def extract(report: dict, block: str, dotpath: str, kind: str) -> Any:
    value = _resolve(report.get(block) or {}, dotpath)
    if kind == "len":
        return len(value) if isinstance(value, (list, tuple)) else None
    return value


def compare(kind: str, tol: float, baseline: Any, observed: Any
            ) -> Optional[str]:
    """None when within the envelope, else a human-readable reason."""
    if observed is None:
        return "missing from report"
    if kind in ("exact", "len"):
        if observed != baseline:
            return f"observed {observed!r} != blessed {baseline!r}"
        return None
    if kind == "rel":
        try:
            b, o = float(baseline), float(observed)
        except (TypeError, ValueError):
            return f"non-numeric: observed {observed!r} vs {baseline!r}"
        bound = tol * max(abs(b), 1e-9) if b else tol
        if abs(o - b) > bound:
            pct = (o - b) / b * 100.0 if b else float("inf")
            return (f"observed {o:g} vs blessed {b:g} "
                    f"({pct:+.1f}%, envelope ±{tol * 100:.0f}%)")
        return None
    return f"unknown comparison kind {kind!r}"


def baseline_path(block: str, baseline_dir: pathlib.Path) -> pathlib.Path:
    return baseline_dir / f"{block}.json"


def load_baseline(block: str, baseline_dir: pathlib.Path) -> Optional[dict]:
    path = baseline_path(block, baseline_dir)
    if not path.is_file():
        return None
    return json.loads(path.read_text())


def bless(report: dict, baseline_dir: pathlib.Path) -> List[str]:
    """Write blessed envelopes for every SPEC block from `report`.
    Returns the per-block file names written (relative to the dir)."""
    baseline_dir.mkdir(parents=True, exist_ok=True)
    written = []
    for block in REQUIRED_BLOCKS:
        metrics = {}
        for blk, dotpath, kind, tol in SPEC:
            if blk != block:
                continue
            value = extract(report, block, dotpath, kind)
            if value is None:
                raise SystemExit(
                    f"dynawatch: cannot bless — report is missing "
                    f"{block}.{dotpath}")
            metrics[dotpath] = {"value": value, "kind": kind, "tol": tol}
        path = baseline_path(block, baseline_dir)
        path.write_text(json.dumps(
            {"block": block, "metrics": metrics}, indent=2, sort_keys=True)
            + "\n")
        written.append(path.name)
    return written


def gate(report: dict, baseline_dir: pathlib.Path) -> List[str]:
    """Compare `report` to the blessed baselines; returns the failures
    (empty list == gate passes). Every failure line carries the blessed
    value, the observed one, and the envelope — the CI log IS the diff."""
    failures: List[str] = []
    for block in REQUIRED_BLOCKS:
        base = load_baseline(block, baseline_dir)
        if base is None:
            failures.append(
                f"{block}: no baseline (run --baseline-update and commit "
                f"{baseline_path(block, baseline_dir)})")
            continue
        if block not in report:
            failures.append(f"{block}: block missing from report")
            continue
        blessed = base.get("metrics", {})
        for blk, dotpath, kind, tol in SPEC:
            if blk != block:
                continue
            entry = blessed.get(dotpath)
            if entry is None:
                failures.append(
                    f"{block}.{dotpath}: not in baseline — re-bless")
                continue
            # The blessed file pins kind/tol too, so a stale baseline
            # written under an older SPEC fails loudly instead of
            # silently gating with the wrong envelope.
            if entry.get("kind") != kind or entry.get("tol") != tol:
                failures.append(
                    f"{block}.{dotpath}: baseline envelope drift "
                    f"(blessed {entry.get('kind')}/{entry.get('tol')} vs "
                    f"SPEC {kind}/{tol}) — re-bless")
                continue
            observed = extract(report, block, dotpath, kind)
            reason = compare(kind, tol, entry.get("value"), observed)
            if reason:
                failures.append(f"{block}.{dotpath}: {reason}")
    return failures


def validate(baseline_dir: pathlib.Path) -> List[str]:
    """Structural check (no report needed): every SPEC block has a
    parseable baseline covering every SPEC metric with the current
    envelope. Cheap enough for the dependency-free lint job."""
    problems: List[str] = []
    for block in REQUIRED_BLOCKS:
        try:
            base = load_baseline(block, baseline_dir)
        except (OSError, json.JSONDecodeError) as exc:
            problems.append(f"{block}: unreadable baseline ({exc})")
            continue
        if base is None:
            problems.append(f"{block}: baseline file missing")
            continue
        blessed = base.get("metrics", {})
        for blk, dotpath, kind, tol in SPEC:
            if blk != block:
                continue
            entry = blessed.get(dotpath)
            if entry is None:
                problems.append(f"{block}.{dotpath}: not blessed")
            elif entry.get("kind") != kind or entry.get("tol") != tol:
                problems.append(
                    f"{block}.{dotpath}: envelope drift — re-bless")
            elif entry.get("value") is None:
                problems.append(f"{block}.{dotpath}: blessed value is null")
        for dotpath in blessed:
            if not any(b == block and d == dotpath
                       for b, d, _k, _t in SPEC):
                problems.append(
                    f"{block}.{dotpath}: blessed but not in SPEC — "
                    f"re-bless to drop it")
    return problems


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dynawatch",
        description="chip-free perf-regression gate over the bench dry run")
    parser.add_argument("--report", help="bench_dry_run.py JSON report")
    parser.add_argument("--baseline-update", action="store_true",
                        help="bless baselines from --report instead of gating")
    parser.add_argument("--validate", action="store_true",
                        help="structural baseline check only (no report)")
    parser.add_argument("--baseline-dir", default=str(BASELINE_DIR),
                        help="baseline directory (default: bundled)")
    args = parser.parse_args(argv)
    baseline_dir = pathlib.Path(args.baseline_dir)

    if args.validate:
        problems = validate(baseline_dir)
        for line in problems:
            print(f"dynawatch: {line}", file=sys.stderr)
        if problems:
            print(f"dynawatch: validate FAILED ({len(problems)} problems)",
                  file=sys.stderr)
            return 1
        print(f"dynawatch: baselines valid "
              f"({len(SPEC)} metrics across {len(REQUIRED_BLOCKS)} blocks)")
        return 0

    if not args.report:
        parser.error("--report is required unless --validate")
    try:
        report = json.loads(pathlib.Path(args.report).read_text())
    except (OSError, json.JSONDecodeError) as exc:
        print(f"dynawatch: cannot read report: {exc}", file=sys.stderr)
        return 2

    if args.baseline_update:
        written = bless(report, baseline_dir)
        print(f"dynawatch: blessed {len(written)} baselines in "
              f"{baseline_dir}: {', '.join(written)}")
        return 0

    failures = gate(report, baseline_dir)
    for line in failures:
        print(f"dynawatch: FAIL {line}", file=sys.stderr)
    if failures:
        print(f"dynawatch: gate FAILED ({len(failures)}/{len(SPEC)} "
              f"metrics out of envelope)", file=sys.stderr)
        return 1
    print(f"dynawatch: gate passed ({len(SPEC)} metrics across "
          f"{len(REQUIRED_BLOCKS)} blocks within envelope)")
    return 0
