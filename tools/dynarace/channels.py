"""Blessed-channel registry: the tree's mediated cross-domain surface.

Every shared attribute the domain model proves mediated — a lock held
at all access sites, a channel-typed attribute, a sentinel flag — is a
*channel*: a deliberate cross-domain contract the race analysis leans
on. Like dynaflow's wire schemas and dynajit's jit surface, that
contract must change deliberately: the surface snapshots into
``tools/dynarace/channels/channel_registry.json`` and DR102 fails with
a diff whenever the extracted surface drifts. Bless a reviewed change
with ``python -m tools.dynarace --registry-update`` and commit the
regenerated file.
"""

from __future__ import annotations

import json
import pathlib
from typing import Optional

from tools.dynalint.core import SourceFile

from .domains import BLESSED_PATH, CHANNEL_DIR, get_model  # noqa: F401

REGISTRY_PATH = CHANNEL_DIR / "channel_registry.json"


def _anchor(rel: str) -> str:
    """Anchor paths at the package root so the snapshot agrees whether
    the tree was collected relatively or absolutely (the jit-surface
    contract)."""
    idx = rel.find("dynamo_tpu/")
    return rel[idx:] if idx >= 0 else rel


def channel_surface(files: list[SourceFile]) -> dict:
    """The mediated surface: channel-typed attributes plus every
    multi-domain shared attribute with its mediation verdict."""
    model = get_model(files)
    entries = []
    for cls, attrs in model.channels.items():
        for attr, info in attrs.items():
            entries.append({
                "scope": f"{_anchor(info.rel)}::{cls}",
                "attr": attr,
                "kind": (f"{info.flavor}-{info.kind}" if info.flavor
                         else info.kind),
                "mediates": [],
            })
    for scope, attr, accs in model.shared_attrs():
        med = model.mediation(scope, attr, accs)
        if med is None:
            continue  # unmediated: DR101's business, not the registry's
        kind, detail = med
        doms: set[str] = set()
        for a in accs:
            doms |= model.domains_of(a.fn)
        entries.append({
            "scope": f"{_anchor(accs[0].fn.rel)}::{scope}",
            "attr": attr,
            "kind": kind,
            "detail": detail,
            "domains": sorted(doms),
            "mediates": [attr],
        })
    entries.sort(key=lambda e: json.dumps(e, sort_keys=True))
    return {"version": 1, "channels": entries}


def update_registry(files: list[SourceFile],
                    registry_path: pathlib.Path = REGISTRY_PATH) -> bool:
    """Regenerate the checked-in channel registry; True if it changed."""
    registry_path.parent.mkdir(parents=True, exist_ok=True)
    payload = json.dumps(channel_surface(files), indent=2,
                         sort_keys=True) + "\n"
    if registry_path.exists() and registry_path.read_text() == payload:
        return False
    registry_path.write_text(payload)
    return True


def diff_registry(files: list[SourceFile],
                  registry_path: pathlib.Path = REGISTRY_PATH,
                  ) -> Optional[list[str]]:
    """None when the tree matches the snapshot; otherwise human-readable
    drift lines."""
    if not registry_path.exists():
        return ["no channel registry at "
                f"{registry_path}; run `python -m tools.dynarace "
                "--registry-update` and commit the result"]
    want = json.loads(registry_path.read_text())
    got = channel_surface(files)
    if got == want:
        return None

    def keyed(payload: dict) -> dict[str, int]:
        out: dict[str, int] = {}
        for entry in payload.get("channels", []):
            key = json.dumps(entry, sort_keys=True)
            out[key] = out.get(key, 0) + 1
        return out

    want_k, got_k = keyed(want), keyed(got)
    lines = []
    for key in sorted(set(got_k) - set(want_k)):
        entry = json.loads(key)
        lines.append(f"added: {entry['scope']}.{entry['attr']} "
                     f"[{entry['kind']}]")
    for key in sorted(set(want_k) - set(got_k)):
        entry = json.loads(key)
        lines.append(f"removed: {entry['scope']}.{entry['attr']} "
                     f"[{entry['kind']}]")
    for key in sorted(set(want_k) & set(got_k)):
        if want_k[key] != got_k[key]:
            entry = json.loads(key)
            lines.append(f"count changed ({want_k[key]} -> "
                         f"{got_k[key]}): {entry['scope']}."
                         f"{entry['attr']}")
    return lines or ["channel ordering drifted (regenerate)"]
