"""dynarace — concurrency-domain race analysis for dynamo_tpu.

Usage::

    python -m tools.dynarace dynamo_tpu/ [--format json]
    python -m tools.dynarace --registry-update  # bless a channel change
    python -m tools.dynarace --list-rules

The fourth analyzer on the shared dynalint/dynaflow/dynajit driver
(collector, per-line suppressions, JSON output, CI gate): every
function is classified into execution domains (event-loop coroutine,
scheduler step thread, dedicated Thread targets, executor bodies,
signal handlers) by propagating seeds over dynaflow's call graph, and
shared mutable state crossing a domain boundary must be mediated by a
blessed channel — a lock held at every access, a queue, a
call_soon_threadsafe hop, a sentinel flag — recorded in the checked-in
channel registry (tools/dynarace/channels/, DR102 drift gate). Rule
families: cross-domain shared state (DR1xx), loop affinity (DR2xx),
boundary locks (DR3xx), signal handlers (DR4xx), thread lifecycle
(DR5xx). Suppress on the flagged line with
``# dynarace: disable=DR101 -- justification`` citing the blessed
channel or the interleaving test (tests/test_interleave.py) that
earns it. See docs/static-analysis.md for the catalogue and
dynamo_tpu/runtime/interleave.py for the deterministic-interleaving
harness that drives the findings through adversarial schedules.
"""

from __future__ import annotations

from tools.dynalint.core import (  # noqa: F401
    Finding,
    ProjectRule,
    Registry,
    Rule,
    collect_files,
    main_for,
    render_json,
    render_text,
)
from tools.dynalint.core import run as _run

DYNARACE = Registry("dynarace", "DR000")

from . import (  # noqa: E402
    passes_affinity,
    passes_locks,
    passes_shared,
    passes_signals,
    passes_threads,
)
from .channels import (  # noqa: E402,F401
    CHANNEL_DIR,
    REGISTRY_PATH,
    channel_surface,
    diff_registry,
    update_registry,
)
from .domains import DomainModel, get_model  # noqa: E402,F401

for _cls in (
    passes_shared.CrossDomainUnmediatedState,
    passes_shared.ChannelRegistryDrift,
    passes_affinity.ForeignThreadAsyncioTouch,
    passes_locks.SyncLockAwaitedUnder,
    passes_signals.NonIdempotentSignalHandler,
    passes_threads.UnjoinedThread,
):
    DYNARACE.register(_cls)

__all__ = ["DYNARACE", "run", "all_rules", "main", "DomainModel",
           "get_model", "channel_surface", "update_registry",
           "diff_registry", "CHANNEL_DIR", "REGISTRY_PATH"]


def all_rules():
    return DYNARACE.all_rules()


def run(paths, rules=None):
    """Analyze `paths`; returns (findings after suppression, files)."""
    return _run(paths, rules=rules, registry=DYNARACE)


def main(argv=None) -> int:
    def extra_args(parser):
        parser.add_argument(
            "--registry-update", action="store_true",
            help="regenerate tools/dynarace/channels/"
                 "channel_registry.json from the tree (the one-command "
                 "path after a deliberate concurrency-contract change) "
                 "and exit")
        parser.add_argument(
            "--domains", action="store_true",
            help="print the inferred execution-domain classification "
                 "and exit (debugging aid)")

    def handle_extra(args):
        if args.domains:
            files, errors = collect_files(args.paths or ["dynamo_tpu"])
            for err in errors:
                print(f"{err.path}:{err.line}: {err.message}")
            model = get_model(files)
            for qual in sorted(model.domains):
                doms = model.domains[qual]
                if doms:
                    print(f"{qual}: {', '.join(sorted(doms))}")
            return 1 if errors else 0
        if not args.registry_update:
            return None
        files, errors = collect_files(args.paths or ["dynamo_tpu"])
        for err in errors:
            print(f"{err.path}:{err.line}: {err.message}")
        if update_registry(files):
            print(f"updated channel registry: {REGISTRY_PATH}")
        else:
            print("channel registry already current")
        return 1 if errors else 0

    return main_for(
        DYNARACE, ["dynamo_tpu"],
        "concurrency-domain race analysis (execution-domain inference, "
        "cross-domain shared state vs blessed channels, loop affinity, "
        "boundary locks, signal handlers, thread lifecycle) for the "
        "dynamo_tpu codebase", argv, extra_args=extra_args,
        handle_extra=handle_extra)
