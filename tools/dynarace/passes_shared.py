"""DR1xx — cross-domain shared state.

DR101 flags a `self.attr` (or tracked module global) written in one
execution domain and touched in another with no blessed channel
mediating it: no lock held at every access, not a channel-typed
attribute, not a sentinel flag. Exactly the shape of every concurrency
bug this codebase has shipped (the FlightRecorder.get() torn read, the
offload dropped-counter lost update). A deliberate unmediated design
is suppressed on the flagged line citing the blessed channel or the
interleaving test (tests/test_interleave.py) that earns it.

DR102 is the drift gate over the mediated surface (the channel
registry): a new lock-mediated attribute, a new queue, a changed
domain set — any of it must be blessed with ``--registry-update``.
"""

from __future__ import annotations

import pathlib
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, SourceFile

from .channels import REGISTRY_PATH, diff_registry
from .domains import get_model


class CrossDomainUnmediatedState(ProjectRule):
    id = "DR101"
    name = "cross-domain-unmediated-state"
    description = (
        "mutable state (self.attr or module global) is written in one "
        "execution domain and touched from another with no blessed "
        "channel mediating it (no common lock at every access site, "
        "not a queue/Event/deque channel attribute, not a "
        "constant-sentinel flag) — a data race: fix it, or suppress "
        "citing the mediating design and the interleaving test "
        "(tests/test_interleave.py) that exercises it")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        model = get_model(files)
        for scope, attr, accs in model.shared_attrs():
            if model.mediation(scope, attr, accs) is not None:
                continue
            doms: set[str] = set()
            for a in accs:
                doms |= model.domains_of(a.fn)
            # Anchor on the first bare (lock-free) write so the fix or
            # suppression lands on the code that needs the argument;
            # fall back to the first write.
            writes = sorted((a for a in accs if a.kind == "write"),
                            key=lambda a: (a.fn.rel, a.line))
            bare = [a for a in writes if not model.held_at(a)]
            site = (bare or writes)[0]
            others = sorted({f"{a.fn.rel}:{a.line}" for a in accs
                             if a is not site})
            listed = ", ".join(others[:4]) + (", ..."
                                              if len(others) > 4 else "")
            label = f"{scope}.{attr}" if scope != "<module>" else attr
            yield Finding(
                self.id, self.name, site.fn.rel, site.line,
                getattr(site.node, "col_offset", 0),
                f"{label} is accessed from domains "
                f"{{{', '.join(sorted(doms))}}} with no blessed channel "
                f"mediating it (also touched at {listed}) — hold one "
                "lock at every access, route through a queue/"
                "call_soon_threadsafe hop, or hand out immutable "
                "snapshots")


class ChannelRegistryDrift(ProjectRule):
    id = "DR102"
    name = "channel-registry-drift"
    description = (
        "the tree's mediated cross-domain surface (locks, queues, "
        "sentinel flags and the domains they bridge) diverged from the "
        "checked-in registry under tools/dynarace/channels/ — "
        "concurrency-contract changes must be deliberate: run "
        "`python -m tools.dynarace --registry-update` and commit the "
        "diff")

    def __init__(self,
                 registry_path: Optional[pathlib.Path] = REGISTRY_PATH,
                 ) -> None:
        self.registry_path = registry_path

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        if self.registry_path is None or not files:
            return
        if not any("dynamo_tpu/" in src.rel for src in files) \
                and self.registry_path == REGISTRY_PATH:
            return  # fixture trees gate against their own snapshots only
        drift = diff_registry(files, self.registry_path)
        if drift is None:
            return
        src = files[0]
        yield Finding(
            self.id, self.name, src.rel, 1, 0,
            "mediated-channel surface drifted from the checked-in "
            "registry: " + "; ".join(drift[:8])
            + ("; ..." if len(drift) > 8 else "")
            + " — if deliberate, run `python -m tools.dynarace "
            "--registry-update` and commit the diff")
