"""DR4xx — signal-handler discipline.

A POSIX signal can be delivered more than once (double SIGTERM during
a slow drain is the shipped example — PR 15's hand-made fix), and a
`signal.signal` handler interrupts an arbitrary frame. A handler body
must therefore be idempotent and tiny: resolve an Event, log, return.
DR401 flags handler bodies that compound on repeated delivery —
counter increments, queue/list mutation, task or thread spawns —
traced through the registration site (`loop.add_signal_handler`,
`signal.signal`), including lambda handlers.

The drain plane's contract is the model: the handler resolves ONE
shutdown event (runtime/signals.py), and idempotence lives in
DrainCoordinator.drain() where every duplicate delivery joins the one
ladder run (pinned by tests/test_interleave.py::
test_double_drain_converges).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, SourceFile
from tools.dynaflow.graph import call_tail

from .domains import get_model

# Calls that compound when a handler runs twice.
_COMPOUNDING_TAILS = {
    "append", "appendleft", "extend", "insert", "put", "put_nowait",
    "pop", "popleft", "remove",
    "create_task", "ensure_future", "start", "submit", "run",
}
# Idempotent by design: Event resolution, logging, introspection.
_ALLOWED_TAILS = {
    "set", "clear", "is_set", "info", "debug", "warning", "error",
    "exception", "get_logger", "getLogger", "request_shutdown",
}


def _handler_hazards(body: ast.AST) -> Iterable[tuple[ast.AST, str]]:
    stack = list(ast.iter_child_nodes(body))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, ast.AugAssign):
            yield node, "augmented assignment compounds per delivery"
        elif isinstance(node, ast.Call):
            tail = call_tail(node)
            if tail in _COMPOUNDING_TAILS and tail not in _ALLOWED_TAILS:
                yield node, f"'{tail}' call compounds per delivery"
        stack.extend(ast.iter_child_nodes(node))


class NonIdempotentSignalHandler(ProjectRule):
    id = "DR401"
    name = "non-idempotent-signal-handler"
    description = (
        "a signal handler body (registered via loop.add_signal_handler "
        "or signal.signal, lambdas included) mutates compounding state "
        "— counters, queues/lists, task or thread spawns: a repeated "
        "SIGTERM/SIGINT delivery re-runs it; a handler must only "
        "resolve an idempotent event (the runtime/signals.py contract) "
        "and let the converging call (e.g. DrainCoordinator.drain) own "
        "once-semantics")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        model = get_model(files)
        for src in files:
            for fn in [f for f in model.project.functions.values()
                       if f.rel == src.rel]:
                # Shallow walk: nested defs/classes are FunctionInfos of
                # their own, so descending here would visit their calls
                # twice (once from the parent, once from themselves).
                stack = list(ast.iter_child_nodes(fn.node))
                while stack:
                    node = stack.pop()
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                        continue
                    stack.extend(ast.iter_child_nodes(node))
                    if not isinstance(node, ast.Call):
                        continue
                    handler = self._handler_arg(node)
                    if handler is None:
                        continue
                    yield from self._check_handler(model, src, fn, node,
                                                   handler)

    @staticmethod
    def _handler_arg(node: ast.Call) -> Optional[ast.expr]:
        tail = call_tail(node)
        if tail == "add_signal_handler" and len(node.args) >= 2:
            return node.args[1]
        if tail == "signal" and len(node.args) >= 2:
            return node.args[1]
        return None

    def _check_handler(self, model, src: SourceFile, fn, reg: ast.Call,
                       handler: ast.expr) -> Iterable[Finding]:
        if isinstance(handler, ast.Lambda):
            for _node, why in _handler_hazards(handler):
                yield Finding(
                    self.id, self.name, src.rel, reg.lineno,
                    reg.col_offset,
                    f"lambda signal handler is not idempotent: {why} "
                    "— resolve an Event and converge in the callee")
            return
        for target in model._resolve_callback(fn, handler):
            for node, why in _handler_hazards(target.node):
                yield Finding(
                    self.id, self.name, target.rel,
                    getattr(node, "lineno", target.lineno),
                    getattr(node, "col_offset", 0),
                    f"signal handler '{target.name}' (registered at "
                    f"{src.rel}:{reg.lineno}) is not idempotent: {why}")
