"""DR3xx — locks shared across the thread/async boundary.

A `threading.Lock` held while a coroutine awaits is the classic
boundary deadlock: the coroutine parks on the await WITHOUT releasing
the lock, the event loop moves on, and the scheduler/offload thread
that would let the awaited thing complete blocks on the same lock —
with the GIL released, nothing makes progress. dynaflow's DF201 flags
*slow* awaits under any lock for latency; DR301 is the correctness
side: ANY await under a *sync* (threading) lock that threads also
take. The fix is to shrink the locked region to synchronous work, or
use an asyncio.Lock on the loop side and a queue across the boundary.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.dynalint.core import Finding, Rule, SourceFile, call_name
from tools.dynaflow.graph import call_tail

_SYNC_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def _sync_lock_attrs(tree: ast.Module) -> dict[str, set[str]]:
    """class -> attrs assigned a *threading* lock (module-qualified
    `threading.Lock()` etc., the codebase idiom — a bare `Lock()` from
    `asyncio import Lock` must not count)."""
    out: dict[str, set[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Assign)
                    and isinstance(sub.value, ast.Call)):
                continue
            if call_tail(sub.value) not in _SYNC_LOCK_CTORS:
                continue
            if not call_name(sub.value).startswith("threading."):
                continue
            for tgt in sub.targets:
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    out.setdefault(node.name, set()).add(tgt.attr)
    return out


def _contains_await(node: ast.AST) -> ast.AST | None:
    """First Await inside `node`, not descending into nested defs."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(child, ast.Await):
            return child
        stack.extend(ast.iter_child_nodes(child))
    return None


class SyncLockAwaitedUnder(Rule):
    id = "DR301"
    name = "sync-lock-awaited-under"
    description = (
        "a coroutine awaits while holding a threading.Lock/RLock/"
        "Condition (a sync `with` on a thread-shared lock enclosing an "
        "`await`): the coroutine parks without releasing, and any "
        "thread taking the same lock deadlocks against the loop — "
        "shrink the locked region to synchronous work or use an "
        "asyncio.Lock plus a queue across the boundary")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        locks_by_class = _sync_lock_attrs(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            lock_attrs = locks_by_class.get(node.name, set())
            if not lock_attrs:
                continue
            for fn in ast.walk(node):
                if not isinstance(fn, ast.AsyncFunctionDef):
                    continue
                for w in ast.walk(fn):
                    if not isinstance(w, ast.With):
                        continue  # async with = asyncio lock, fine
                    held = [
                        item.context_expr.attr for item in w.items
                        if isinstance(item.context_expr, ast.Attribute)
                        and isinstance(item.context_expr.value, ast.Name)
                        and item.context_expr.value.id == "self"
                        and item.context_expr.attr in lock_attrs]
                    if not held:
                        continue
                    awaited = _contains_await(w)
                    if awaited is not None:
                        yield self.finding(
                            src, awaited,
                            f"await inside `with self.{held[0]}` "
                            f"(threading lock of {node.name}) — the "
                            "coroutine parks holding it and any "
                            "thread on the same lock deadlocks "
                            "against the event loop")
