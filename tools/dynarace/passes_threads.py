"""DR5xx — thread lifecycle.

Every `threading.Thread` in the tree must have a shutdown story:
either it is joined (the owner's close()/stop() path waits for it) or
it is explicitly daemon=True (the declared "may be abandoned at exit"
marker — per-client streamer threads in the weight service). A
non-daemon thread nobody joins keeps the process alive after main
returns; a stored thread without a join is a shutdown leak that
close() silently abandons — both are exactly the departures the drain
plane exists to make graceful.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.dynalint.core import Finding, Rule, SourceFile
from tools.dynaflow.graph import call_tail


def _is_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _thread_calls(tree: ast.AST) -> Iterable[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_tail(node) == "Thread":
            yield node


def _joined_names(scope: ast.AST) -> set[str]:
    """Names (self.X attrs and locals) with a .join(...) call in scope."""
    out: set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and call_tail(node) == "join" \
                and isinstance(node.func, ast.Attribute):
            base = node.func.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                out.add(f"self.{base.attr}")
            elif isinstance(base, ast.Name):
                out.add(base.id)
    return out


def _daemon_set_names(scope: ast.AST) -> set[str]:
    """`t.daemon = True` / `self.X.daemon = True` assignments."""
    out: set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Constant) \
                and node.value.value is True:
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and tgt.attr == "daemon":
                    base = tgt.value
                    if isinstance(base, ast.Name):
                        out.add(base.id)
                    elif isinstance(base, ast.Attribute) \
                            and isinstance(base.value, ast.Name) \
                            and base.value.id == "self":
                        out.add(f"self.{base.attr}")
    return out


class UnjoinedThread(Rule):
    id = "DR501"
    name = "unjoined-thread"
    description = (
        "a threading.Thread is started with no shutdown story: not "
        "joined anywhere in its owning scope and not daemon=True — a "
        "non-daemon unjoined thread pins the process at exit, and a "
        "stored-but-never-joined worker is a leak close() silently "
        "abandons; join it in the owner's close()/stop() or declare "
        "daemon=True deliberately")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        # Class-scoped threads: join may live in any method.
        claimed: set[int] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                joined = _joined_names(node)
                daemons = _daemon_set_names(node)
                for call in _thread_calls(node):
                    claimed.add(id(call))
                    yield from self._check(src, call, node, joined,
                                           daemons)
        # Module/function-scoped threads outside any class.
        joined = _joined_names(src.tree)
        daemons = _daemon_set_names(src.tree)
        for call in _thread_calls(src.tree):
            if id(call) not in claimed:
                yield from self._check(src, call, src.tree, joined,
                                       daemons)

    def _check(self, src: SourceFile, call: ast.Call, scope: ast.AST,
               joined: set[str], daemons: set[str]) -> Iterable[Finding]:
        if _is_daemon(call):
            return
        stored = self._binding(call, scope)
        if stored is not None and (stored in joined or stored in daemons):
            return
        where = (f"stored as {stored} but never joined"
                 if stored is not None else "never stored")
        yield self.finding(
            src, call,
            f"thread is {where} and not daemon=True — no shutdown "
            "story; join it in close()/stop() or mark it daemon "
            "deliberately")

    @staticmethod
    def _binding(call: ast.Call, scope: ast.AST) -> Optional[str]:
        """Name the thread object is bound to ('self.X' or a local)."""
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and node.value is call:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    return f"self.{tgt.attr}"
                if isinstance(tgt, ast.Name):
                    return tgt.id
        return None
