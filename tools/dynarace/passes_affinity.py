"""DR2xx — event-loop affinity.

asyncio primitives are NOT thread-safe: an `asyncio.Queue.put_nowait`,
`asyncio.Event.set`, or `loop.create_task` from a foreign thread can
corrupt the loop's internal state or silently never wake a waiter
(waiters are woken via `call_soon`, which is loop-affine). The one
blessed doorway is `loop.call_soon_threadsafe` — the hop the event
plane uses (MemEventPlane → subscriber `_emit`). DR201 flags
loop-affine mutations reachable in a thread/executor/signal domain
without that hop.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.dynalint.core import Finding, ProjectRule, SourceFile, call_name
from tools.dynaflow.graph import call_tail

from .domains import LOOP, get_model

# Mutating tails on loop-affine objects.
_ASYNC_MUTATORS = {"put_nowait", "set", "clear", "set_result",
                   "set_exception", "cancel"}
_TASK_SPAWNERS = {"create_task", "ensure_future", "call_soon",
                  "call_later", "call_at"}


def _foreign(domains: set[str]) -> set[str]:
    """Domains that are not the event loop (signal handlers run ON the
    loop's thread via add_signal_handler, but the rule still treats a
    handler reached from signal registration as loop-side only when
    the loop seeded it — `signal.signal` handlers interrupt arbitrary
    frames)."""
    return {d for d in domains if d != LOOP}


class ForeignThreadAsyncioTouch(ProjectRule):
    id = "DR201"
    name = "foreign-thread-asyncio-touch"
    description = (
        "an asyncio-affine primitive (asyncio.Queue/Event/Future "
        "mutation, create_task/ensure_future/call_soon) is reached in "
        "a thread, executor, or signal domain without the "
        "call_soon_threadsafe hop — asyncio primitives are not "
        "thread-safe and waiters may never wake; route the mutation "
        "through loop.call_soon_threadsafe (the event-plane idiom)")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        model = get_model(files)
        for fn in model.project.functions.values():
            doms = model.domains_of(fn)
            foreign = _foreign(doms)
            if not foreign:
                continue
            asyncio_attrs = {
                attr for attr, info in
                model.channels.get(fn.cls or "", {}).items()
                if info.flavor == "asyncio"}
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                tail = call_tail(node)
                name = call_name(node)
                if tail in _TASK_SPAWNERS and (
                        name.startswith("asyncio.")
                        or name.startswith("loop.")
                        or name.startswith("self.loop.")
                        or name.startswith("self._loop.")):
                    if tail == "call_soon" and "threadsafe" in name:
                        continue
                    yield Finding(
                        self.id, self.name, fn.rel, node.lineno,
                        node.col_offset,
                        f"'{name}' runs in domain(s) "
                        f"{{{', '.join(sorted(foreign))}}} — loop "
                        "machinery touched off-loop; use "
                        "loop.call_soon_threadsafe to hop in")
                    continue
                if tail in _ASYNC_MUTATORS \
                        and isinstance(node.func, ast.Attribute):
                    base = node.func.value
                    if isinstance(base, ast.Attribute) \
                            and isinstance(base.value, ast.Name) \
                            and base.value.id == "self" \
                            and base.attr in asyncio_attrs:
                        yield Finding(
                            self.id, self.name, fn.rel, node.lineno,
                            node.col_offset,
                            f"self.{base.attr}.{tail}() mutates an "
                            "asyncio primitive in domain(s) "
                            f"{{{', '.join(sorted(foreign))}}} — not "
                            "thread-safe; hop in via "
                            "loop.call_soon_threadsafe")
