"""dynajit — device-plane static analysis for dynamo_tpu.

Usage::

    python -m tools.dynajit dynamo_tpu/ [--format json]
    python -m tools.dynajit --registry-update  # bless a jit-surface change
    python -m tools.dynajit --list-rules

The third analyzer on the shared dynalint/dynaflow driver (collector,
per-line suppressions, JSON output, CI gate): abstract interpretation
over the JAX hot path using dynaflow's call graph. Where dynalint
checks lines and dynaflow checks protocols, dynajit checks what the
DEVICE sees — the jit cache-key space (DJ1xx, with a checked-in
jit-signature registry under tools/dynajit/signatures/), host-sync
reachability from the dispatch loop (DJ2xx), buffer-donation
discipline (DJ3xx), Pallas kernel contracts (DJ4xx), and exactly-once
resource typestate (DJ5xx). Suppress on the flagged line with
``# dynajit: disable=DJ201 -- justification``.
See docs/static-analysis.md for the catalogue.
"""

from __future__ import annotations

from tools.dynalint.core import (  # noqa: F401
    Finding,
    ProjectRule,
    Registry,
    Rule,
    collect_files,
    main_for,
    render_json,
    render_text,
)
from tools.dynalint.core import run as _run

DYNAJIT = Registry("dynajit", "DJ000")

from . import (  # noqa: E402
    passes_donation,
    passes_hostsync,
    passes_pallas,
    passes_retrace,
    passes_typestate,
)
from .jit_surface import (  # noqa: E402,F401
    REGISTRY_PATH,
    SIGNATURE_DIR,
    JitSite,
    diff_registry,
    extract_jit_sites,
    jit_sites,
    surface_json,
    update_registry,
)

for _cls in (
    passes_retrace.JitInLoop,
    passes_retrace.PerCallJit,
    passes_retrace.UnboundedJitCacheKey,
    passes_retrace.JitSignatureDrift,
    passes_hostsync.HostSyncReachable,
    passes_donation.UseAfterDonate,
    passes_donation.DonatedAttrNotRebound,
    passes_donation.KvParamDonationUndeclared,
    passes_pallas.UncheckedGridDivision,
    passes_pallas.Q8VariantDtypeDisagreement,
    passes_pallas.KernelOracleMissing,
    passes_typestate.ReleaseNotExceptionSafe,
    passes_typestate.DoubleRelease,
    passes_typestate.ProbeVerdictLeak,
):
    DYNAJIT.register(_cls)

__all__ = ["DYNAJIT", "run", "all_rules", "main", "extract_jit_sites",
           "jit_sites", "surface_json", "update_registry",
           "diff_registry", "JitSite", "REGISTRY_PATH", "SIGNATURE_DIR"]


def all_rules():
    return DYNAJIT.all_rules()


def run(paths, rules=None):
    """Analyze `paths`; returns (findings after suppression, files)."""
    return _run(paths, rules=rules, registry=DYNAJIT)


def main(argv=None) -> int:
    def extra_args(parser):
        parser.add_argument(
            "--registry-update", action="store_true",
            help="regenerate tools/dynajit/signatures/jit_surface.json "
                 "from the tree (the one-command path after a "
                 "deliberate compile-signature change) and exit")

    def handle_extra(args):
        if not args.registry_update:
            return None
        files, errors = collect_files(args.paths or ["dynamo_tpu"])
        for err in errors:
            print(f"{err.path}:{err.line}: {err.message}")
        if update_registry(files):
            print(f"updated jit-signature registry: {REGISTRY_PATH}")
        else:
            print("jit-signature registry already current")
        return 1 if errors else 0

    return main_for(
        DYNAJIT, ["dynamo_tpu"],
        "device-plane static analysis (jit surface, host syncs, "
        "donation, Pallas contracts, resource typestate) for the "
        "dynamo_tpu codebase", argv, extra_args=extra_args,
        handle_extra=handle_extra)
