"""DJ5xx — exactly-once resource typestate.

Every production incident the last four PRs fixed by hand had the same
shape: a resource acquired on one path and released on most-but-not-all
of the others. KV pages parked with a transfer and released twice; a
trace span opened before an early return and never ended; a breaker's
half-open probe slot leaked by an attempt that died without a verdict;
a claimed transfer whose release lived outside the `finally`. This pass
encodes the contract those reviews enforced: from every acquire, every
path must reach EXACTLY one release — which in Python means the release
lives in a `finally` (or the resource is a context manager), and no
path releases twice.

The checker is per-function with an escape hatch for ownership
transfer: an acquired value that is returned, yielded, stored on an
attribute/container, or passed onward carries its release obligation
with it and is not this function's problem. Resources whose release is
idempotent by design (trace spans — `_SpanHandle.end` is first-wins)
are exempt from the double-release rule but not the leak rule.

  * DJ501 release-not-exception-safe — acquire + release in one
    function, statements that can raise in between, and no release
    under a `finally`/`with`.
  * DJ502 double-release — two unconditional releases of the same
    resource in one straight-line block (non-idempotent resources).
  * DJ503 probe-verdict-leak — a breaker `try_acquire` with no
    release-family call (`release_probe`/`record_success`/
    `record_failure`) under a `finally`: an attempt that dies without a
    verdict leaks the half-open slot and locks the instance out.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from tools.dynalint.core import Finding, Rule, SourceFile


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    name: str
    acquire_tails: tuple[str, ...]
    release_tails: tuple[str, ...]
    idempotent_release: bool = False


RESOURCES = (
    # Trace spans: _SpanHandle.end is first-wins, so double-end is the
    # DESIGNED pattern (success-end in the body, failure-end in the
    # finally); leaking one silently drops the span from export.
    ResourceSpec("span", ("start_span",), ("end",),
                 idempotent_release=True),
    # Pending/streaming KV transfers: claim() removes the table entry
    # atomically and the claimer owns exactly one release() — a leak
    # pins the prefill pool's pages forever, a double release hands
    # live pages to another request.
    ResourceSpec("transfer", ("claim",), ("release",)),
)

PROBE_RELEASES = ("release_probe", "record_success", "record_failure")


def _call_tail(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def _can_raise(stmt: ast.stmt) -> bool:
    """Conservative: any call/await/yield between acquire and release
    can raise (or suspend and be cancelled)."""
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Call, ast.Await, ast.Yield,
                             ast.YieldFrom, ast.Raise)):
            return True
    return False


@dataclasses.dataclass
class _Acquire:
    spec: ResourceSpec
    var: Optional[str]  # bound name, None when consumed inline
    node: ast.AST


class _FunctionScan:
    """One function's acquire/release/escape facts for one spec."""

    def __init__(self, fn, spec: ResourceSpec) -> None:
        self.fn = fn
        self.spec = spec
        self.acquires: list[_Acquire] = []
        self.releases: list[tuple[str, ast.AST]] = []  # (var, node)
        self.finally_released: set[str] = set()
        self.with_managed: set[str] = set()
        self.escaped: set[str] = set()
        self._scan()

    def _scan(self) -> None:
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                if _call_tail(node.value) in self.spec.acquire_tails:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Name):
                        self.acquires.append(
                            _Acquire(self.spec, tgt.id, node))
                    else:
                        self.escaped.add("<unbound>")
            elif isinstance(node, ast.Call) \
                    and _call_tail(node) in self.spec.release_tails \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name):
                self.releases.append((node.func.value.id, node))
        acquired = {a.var for a in self.acquires if a.var}
        if not acquired:
            return
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call) \
                                and _call_tail(sub) in \
                                self.spec.release_tails \
                                and isinstance(sub.func, ast.Attribute) \
                                and isinstance(sub.func.value, ast.Name):
                            self.finally_released.add(sub.func.value.id)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Name) and ctx.id in acquired:
                        self.with_managed.add(ctx.id)
        self._scan_escapes(acquired)

    def _scan_escapes(self, acquired: set[str]) -> None:
        """Ownership transfer = the resource ITSELF leaves the function
        (returned/yielded/stored/passed as a bare name). A derived value
        (`return transfer.page_ids.copy()`) transfers nothing — the
        release obligation stays here."""
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Return) and node.value is not None:
                self._escape_names(node.value, acquired)
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                    and node.value is not None:
                self._escape_names(node.value, acquired)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        self._escape_names(node.value, acquired)
            elif isinstance(node, ast.Call):
                tail = _call_tail(node)
                if tail in self.spec.release_tails \
                        or tail in self.spec.acquire_tails:
                    continue
                for arg in list(node.args) + [k.value
                                              for k in node.keywords]:
                    self._escape_names(arg, acquired)

    def _escape_names(self, expr: ast.expr, acquired: set[str]) -> None:
        nodes: list[ast.expr] = [expr]
        if isinstance(expr, (ast.Tuple, ast.List)):
            nodes = list(expr.elts)
        for sub in nodes:
            if isinstance(sub, ast.Name) and sub.id in acquired:
                self.escaped.add(sub.id)


class _TypestateRule(Rule):
    def _functions(self, src: SourceFile):
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node


class ReleaseNotExceptionSafe(_TypestateRule):
    id = "DJ501"
    name = "release-not-exception-safe"
    description = (
        "a resource (claimed transfer, trace span) is acquired and "
        "released in the same function, statements between them can "
        "raise, and no release sits under a finally (or `with`): the "
        "exception path leaks it — pages pinned forever, a span "
        "silently dropped. Move the release into a finally, or hand "
        "ownership off explicitly")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for fn in self._functions(src):
            for spec in RESOURCES:
                scan = _FunctionScan(fn, spec)
                yield from self._check(src, fn, spec, scan)

    def _check(self, src: SourceFile, fn, spec: ResourceSpec,
               scan: _FunctionScan) -> Iterable[Finding]:
        released_vars = {var for var, _ in scan.releases}
        for acq in scan.acquires:
            if acq.var is None or acq.var in scan.escaped:
                continue
            if acq.var in scan.with_managed:
                continue
            if acq.var not in released_vars:
                # guard-only uses (e.g. `if x.claim(...) is not None`)
                # never bind, so reaching here means a bound resource
                # with no release at all and no escape
                yield self.finding(
                    src, acq.node,
                    f"{spec.name} {acq.var!r} is acquired here but "
                    "never released in this function and never escapes "
                    "— the resource leaks on every path")
                continue
            if acq.var in scan.finally_released:
                continue
            between = _stmts_between(fn, acq.node, acq.var, spec)
            if any(_can_raise(s) for s in between):
                yield self.finding(
                    src, acq.node,
                    f"{spec.name} {acq.var!r} is released outside any "
                    "finally while statements in between can raise: "
                    "the exception path leaks it — move the release "
                    "into a finally")


def _stmts_between(fn, acquire_stmt: ast.AST, var: str,
                   spec: ResourceSpec) -> list[ast.stmt]:
    """Statements after the acquire and before the first release of
    `var` (linear document order — branches over-approximate)."""
    stmts = [s for s in ast.walk(fn) if isinstance(s, ast.stmt)
             and hasattr(s, "lineno")]
    stmts.sort(key=lambda s: (s.lineno, s.col_offset))
    out: list[ast.stmt] = []
    started = False
    for stmt in stmts:
        if stmt is acquire_stmt:
            started = True
            continue
        if not started:
            continue
        has_release = any(
            isinstance(sub, ast.Call)
            and _call_tail(sub) in spec.release_tails
            and isinstance(sub.func, ast.Attribute)
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == var
            for sub in ast.walk(stmt))
        if has_release:
            break
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        out.append(stmt)
    return out


class DoubleRelease(_TypestateRule):
    id = "DJ502"
    name = "double-release"
    description = (
        "the same non-idempotent resource is released twice in one "
        "straight-line block: the second release frees pages another "
        "request may already own. Resources with first-wins release "
        "semantics (trace spans) are exempt")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for fn in self._functions(src):
            for spec in RESOURCES:
                if spec.idempotent_release:
                    continue
                yield from self._check(src, fn, spec)

    def _check(self, src: SourceFile, fn,
               spec: ResourceSpec) -> Iterable[Finding]:
        for block in _blocks(fn):
            seen: dict[str, ast.AST] = {}
            for stmt in block:
                if isinstance(stmt, (ast.If, ast.Try, ast.For, ast.While,
                                     ast.With, ast.AsyncWith)):
                    continue  # releases under conditions judged per-block
                for sub in ast.walk(stmt):
                    if not (isinstance(sub, ast.Call)
                            and _call_tail(sub) in spec.release_tails
                            and isinstance(sub.func, ast.Attribute)
                            and isinstance(sub.func.value, ast.Name)):
                        continue
                    var = sub.func.value.id
                    if var in seen:
                        yield self.finding(
                            src, sub,
                            f"{spec.name} {var!r} is released twice in "
                            "the same block (first release on line "
                            f"{getattr(seen[var], 'lineno', '?')}): the "
                            "second release frees a resource someone "
                            "else may already own")
                    else:
                        seen[var] = sub

    @staticmethod
    def _release_sites(fn, spec):  # pragma: no cover - debugging aid
        return [sub for sub in ast.walk(fn)
                if isinstance(sub, ast.Call)
                and _call_tail(sub) in spec.release_tails]


def _blocks(fn) -> Iterable[list[ast.stmt]]:
    """Every straight-line statement list in the function (bodies of
    the function, ifs, loops, trys, withs — each yielded separately)."""
    stack: list[list[ast.stmt]] = [fn.body]
    while stack:
        body = stack.pop()
        yield body
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if isinstance(sub, list) and sub:
                    stack.append(sub)
            for handler in getattr(stmt, "handlers", []) or []:
                stack.append(handler.body)


class ProbeVerdictLeak(_TypestateRule):
    id = "DJ503"
    name = "probe-verdict-leak"
    description = (
        "a circuit-breaker try_acquire() with no release-family call "
        "(release_probe / record_success / record_failure) under a "
        "finally in the same function: an attempt that dies without a "
        "verdict (cancellation, deadline, client disconnect) leaks the "
        "half-open single-probe slot and locks the instance out "
        "forever")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for fn in self._functions(src):
            acquires = [node for node in ast.walk(fn)
                        if isinstance(node, ast.Call)
                        and _call_tail(node) == "try_acquire"]
            if not acquires:
                continue
            safe = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Try):
                    continue
                for stmt in node.finalbody:
                    if any(isinstance(sub, ast.Call)
                           and _call_tail(sub) in PROBE_RELEASES
                           for sub in ast.walk(stmt)):
                        safe = True
            if safe:
                continue
            yield self.finding(
                src, acquires[0],
                "try_acquire() here has no probe-release family call "
                "(release_probe/record_success/record_failure) under a "
                "finally: a dying attempt leaks the half-open probe "
                "slot — settle the verdict in a finally")
