"""DJ4xx — Pallas kernel contracts.

A Pallas kernel's correctness contract lives outside the code that
expresses it: the grid must tile the array exactly (a truncating `//`
silently drops trailing rows), the int8/q8 variant must actually touch
quantized dtypes (a copy-pasted body that forgot the dequant produces
plausible garbage), and every kernel needs an interpret-mode XLA-oracle
test — the only way kernel math is checkable off silicon. None of these
break a CPU test suite when violated; all of them break the flagship.

  * DJ401 unchecked-grid-division — a `grid=` element `A // B` where
    neither operand is derived through a divisibility-aware computation
    (a `%` guard, a `_divisor`-style helper, pow2 `bit_length`
    bucketing, round-up padding) in the enclosing function.
  * DJ402 q8-variant-dtype-disagreement — a `<fn>_q8` variant whose
    body never references an int8/uint8 dtype (or a base fn that does):
    the quantized and unquantized paths have drifted into each other.
  * DJ403 kernel-oracle-missing — a public ops/ function containing a
    `pl.pallas_call` with no reference anywhere under tests/: the
    kernel has no interpret-mode oracle pinning it to the XLA
    reference (the contract every existing kernel test follows).
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, Rule, SourceFile

# Anchored at the repo root (the dynaflow METRICS_DOC convention) so
# the rule finds the tests tree regardless of the caller's CWD.
DEFAULT_TESTS_DIR = pathlib.Path(__file__).parent.parent.parent / "tests"


def _is_ops(rel: str) -> bool:
    return "/ops/" in rel or rel.startswith("ops/")


def _has_pallas_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(sub.func,
                                                    ast.Attribute) \
                and sub.func.attr == "pallas_call":
            return True
    return False


class UncheckedGridDivision(Rule):
    id = "DJ401"
    name = "unchecked-grid-division"
    description = (
        "a pallas_call grid element divides with // where neither "
        "operand is derived through a divisibility-aware computation "
        "(% guard/assert, a *divisor* helper, pow2 bit_length "
        "bucketing, round-up padding): a non-dividing shape silently "
        "truncates the trailing tile instead of failing")

    def applies(self, rel: str) -> bool:
        return _is_ops(rel)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            guarded = _guarded_names(fn)
            for call in ast.walk(fn):
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr == "pallas_call"):
                    continue
                for kw in call.keywords:
                    if kw.arg not in ("grid", "grid_spec"):
                        continue
                    yield from self._check_grid(src, kw.value, guarded)

    def _check_grid(self, src: SourceFile, grid: ast.expr,
                    guarded: set[str]) -> Iterable[Finding]:
        for node in ast.walk(grid):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.FloorDiv)):
                continue
            names = {sub.id for operand in (node.left, node.right)
                     for sub in ast.walk(operand)
                     if isinstance(sub, ast.Name)}
            if names and not (names & guarded):
                yield self.finding(
                    src, node,
                    f"grid element `{ast.unparse(node)}` divides "
                    "unguarded values: a non-dividing shape silently "
                    "drops the trailing tile — guard with an assert, a "
                    "divisor helper, or round-up padding")


def _guarded_names(fn) -> set[str]:
    """Names the function derives through divisibility-aware
    computation, closed over simple name copies."""
    guarded: set[str] = set()
    copies: list[tuple[str, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assert) or (
                isinstance(node, ast.If)
                and any(isinstance(s, ast.Raise) for s in node.body)):
            test = node.test
            for sub in ast.walk(test):
                if isinstance(sub, ast.BinOp) and isinstance(sub.op,
                                                             ast.Mod):
                    guarded.update(n.id for n in ast.walk(sub)
                                   if isinstance(n, ast.Name))
        elif isinstance(node, ast.Assign):
            derived = any(
                (isinstance(sub, ast.BinOp)
                 and isinstance(sub.op, (ast.Mod, ast.FloorDiv)))
                or (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "bit_length")
                or (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and "divisor" in sub.func.id)
                for sub in ast.walk(node.value))
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if derived:
                    guarded.add(tgt.id)
                elif isinstance(node.value, ast.Name):
                    copies.append((tgt.id, node.value.id))
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name):
            if isinstance(node.op, (ast.FloorDiv, ast.Mod)):
                guarded.add(node.target.id)
        elif isinstance(node, ast.While):
            has_mod = any(isinstance(sub, ast.BinOp)
                          and isinstance(sub.op, ast.Mod)
                          for sub in ast.walk(node.test))
            if has_mod:
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.Assign, ast.AugAssign)):
                        tgt = (sub.targets[0]
                               if isinstance(sub, ast.Assign)
                               else sub.target)
                        if isinstance(tgt, ast.Name):
                            guarded.add(tgt.id)
    # close over x = y copies (one fixpoint pass per edge is enough for
    # the chains this codebase writes)
    changed = True
    while changed:
        changed = False
        for dst, srcname in copies:
            if srcname in guarded and dst not in guarded:
                guarded.add(dst)
                changed = True
    return guarded


_INT8_MARKERS = ("int8", "uint8")


def _mentions_int8(fn) -> bool:
    """The function handles quantized data itself (int8/uint8 dtype
    references) or routes to a *_q8 callee that does (the
    scatter_from_host_q8 -> scatter_kv_blocks_q8 delegation idiom)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr in _INT8_MARKERS:
            return True
        if isinstance(node, ast.Name) and node.id in _INT8_MARKERS:
            return True
        if isinstance(node, ast.Constant) and node.value in _INT8_MARKERS:
            return True
        if isinstance(node, ast.Call):
            f = node.func
            tail = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if tail != fn.name and "q8" in tail:
                return True
    return False


class Q8VariantDtypeDisagreement(Rule):
    id = "DJ402"
    name = "q8-variant-dtype-disagreement"
    description = (
        "a `<fn>_q8` quantized variant never references an int8/uint8 "
        "dtype (or its base fn does): the quantized and unquantized "
        "paths have drifted into each other — the q8 body must handle "
        "packed int8 values and their scale rows explicitly")

    def applies(self, rel: str) -> bool:
        return _is_ops(rel)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        fns = {node.name: node for node in ast.walk(src.tree)
               if isinstance(node, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))}
        for name, fn in fns.items():
            if not name.endswith("_q8"):
                continue
            if not _mentions_int8(fn):
                yield self.finding(
                    src, fn,
                    f"{name!r} is a q8 variant but its body never "
                    "references an int8/uint8 dtype — the quantized "
                    "path has lost its dequant/pack handling")
                continue
            base = fns.get(name[: -len("_q8")])
            if base is not None and _mentions_int8(base) \
                    and not base.name.startswith("_"):
                yield self.finding(
                    src, base,
                    f"{base.name!r} (the unquantized base of {name!r}) "
                    "references int8/uint8 — the two variants have "
                    "drifted into each other")


class KernelOracleMissing(ProjectRule):
    id = "DJ403"
    name = "kernel-oracle-missing"
    description = (
        "a public ops/ function containing a pl.pallas_call has no "
        "reference anywhere under tests/: every Pallas kernel needs an "
        "interpret-mode XLA-oracle test (the only way kernel math is "
        "checkable off silicon) — add one to tests/test_ops_pallas.py "
        "or the kernel's feature test file")

    def __init__(self, tests_dir: Optional[pathlib.Path] = None) -> None:
        self.tests_dir = (DEFAULT_TESTS_DIR if tests_dir is None
                          else tests_dir)

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        kernels: list[tuple[SourceFile, ast.AST, str]] = []
        for src in files:
            if not _is_ops(src.rel):
                continue
            for node in src.tree.body:
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name.startswith("_"):
                    continue
                if _has_pallas_call(node):
                    kernels.append((src, node, node.name))
        if not kernels:
            return
        corpus = self._tests_corpus()
        if corpus is None:
            return  # no tests tree next to the linted files (fixtures)
        for src, node, name in kernels:
            # Word-boundary match: `paged_decode_attention` appearing
            # inside `paged_decode_attention_partial(` must not satisfy
            # the BASE kernel's oracle requirement (prefix kernels are
            # exactly the family this rule guards).
            if not re.search(rf"\b{re.escape(name)}\b", corpus):
                yield Finding(
                    self.id, self.name, src.rel, node.lineno,
                    node.col_offset,
                    f"Pallas kernel {name!r} has no reference anywhere "
                    f"under {self.tests_dir}/ — add an interpret-mode "
                    "XLA-oracle test pinning it")

    def _tests_corpus(self) -> Optional[str]:
        if not self.tests_dir.is_dir():
            return None
        parts = []
        for path in sorted(self.tests_dir.rglob("*.py")):
            if "fixtures" in path.parts:
                continue  # lint-fixture kernels must not self-satisfy
            try:
                parts.append(path.read_text(encoding="utf-8"))
            except OSError:
                continue
        return "\n".join(parts)
