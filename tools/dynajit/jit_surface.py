"""Jit-surface extraction: every `jax.jit` construction site in the tree.

The compile boundary is the TPU serving plane's real API surface: each
`jax.jit` site defines a cache-key space (static argnames/nums, traced
shapes, the Python identity of the jitted callable), and every change to
one — a new static arg, a dropped donation, a callable constructed per
call instead of per process — changes what the device compiles and when.
None of that is visible in a runtime test until silicon stalls.

This module recovers the whole surface statically: decorator sites
(`@jax.jit`, `@partial(jax.jit, ...)`), call sites (`jax.jit(fn, ...)`),
their static/donate declarations, and the *disposition* of each
constructed callable — module-level, cached in a dict, stored on an
attribute, returned from a builder, bound to a local, or invoked
immediately. Dispositions are what the DJ1xx retrace rules reason about
(a per-call construction never hits jit's identity-keyed cache), and the
full surface snapshots into a checked-in registry
(`tools/dynajit/signatures/jit_surface.json`) so any signature change
fails CI with a diff — the drift-gate contract dynaflow's wire schemas
established, applied to the compile plane.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
from typing import Iterable, Optional

from tools.dynalint.core import SourceFile, call_name

SIGNATURE_DIR = pathlib.Path(__file__).parent / "signatures"
REGISTRY_PATH = SIGNATURE_DIR / "jit_surface.json"


@dataclasses.dataclass
class JitSite:
    rel: str
    line: int
    scope: str            # "<module>", "func", or "Class.method"
    form: str             # "decorator" | "call"
    target: str           # jitted callable's name ("<lambda>" when anon)
    static_argnames: tuple[str, ...] = ()
    static_argnums: tuple[int, ...] = ()
    donate_argnums: tuple[int, ...] = ()
    donate_declared: bool = False  # donate_argnums kw present (even `()`)
    # How the constructed callable is held: "decorator" | "module" |
    # "returned" | "attr:<name>" | "cached:<container>" | "immediate" |
    # "local" (never stored — a fresh callable per execution of scope).
    disposition: str = "local"
    cache_key: str = ""   # unparsed key expr for cached dispositions
    in_loop: bool = False
    target_params: tuple[str, ...] = ()  # resolvable jitted-fn params
    node: Optional[ast.AST] = dataclasses.field(
        default=None, repr=False, compare=False)

    def signature(self) -> dict:
        """Registry entry: everything stable across pure line moves.
        The file path is anchored at the package root so the snapshot
        agrees whether the tree was collected via a relative or an
        absolute path (CI runs from the repo root; pytest hands the
        collector absolute paths)."""
        idx = self.rel.find("dynamo_tpu/")
        return {
            "file": self.rel[idx:] if idx >= 0 else self.rel,
            "scope": self.scope,
            "form": self.form,
            "target": self.target,
            "static_argnames": sorted(self.static_argnames),
            "static_argnums": list(self.static_argnums),
            "donate_argnums": list(self.donate_argnums),
            "donate_declared": self.donate_declared,
            "disposition": self.disposition,
            "cache_key": self.cache_key,
            "params": list(self.target_params),
        }


def _jit_callee(node: ast.AST) -> Optional[ast.Call]:
    """The call carrying jit kwargs: `jax.jit(...)` itself, or
    `partial(jax.jit, ...)` / `functools.partial(jax.jit, ...)`."""
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if name in ("jax.jit", "jit"):
        return node
    if name in ("partial", "functools.partial") and node.args:
        inner = node.args[0]
        if isinstance(inner, (ast.Attribute, ast.Name)) and \
                ast.unparse(inner) in ("jax.jit", "jit"):
            return node
    return None


def _const_ints(node: ast.expr) -> tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def _const_strs(node: ast.expr) -> tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _jit_kwargs(call: ast.Call) -> dict:
    out = {"static_argnames": (), "static_argnums": (),
           "donate_argnums": (), "donate_declared": False}
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            out["static_argnames"] = _const_strs(kw.value)
        elif kw.arg == "static_argnums":
            out["static_argnums"] = _const_ints(kw.value)
        elif kw.arg == "donate_argnums":
            out["donate_argnums"] = _const_ints(kw.value)
            out["donate_declared"] = True
    return out


def _params_of(args: ast.arguments) -> tuple[str, ...]:
    return tuple(a.arg for a in args.posonlyargs + args.args)


def _target_info(call: ast.Call, local_defs: dict) -> tuple[str, tuple]:
    """(target name, params) of the callable handed to jax.jit(...)."""
    if call_name(call) in ("partial", "functools.partial"):
        return "<partial-jit>", ()  # configured jit awaiting its target
    if not call.args:
        return "<unknown>", ()
    tgt = call.args[0]
    if isinstance(tgt, ast.Lambda):
        return "<lambda>", _params_of(tgt.args)
    if isinstance(tgt, ast.Name):
        fn = local_defs.get(tgt.id)
        return tgt.id, _params_of(fn.args) if fn is not None else ()
    if isinstance(tgt, ast.Call) and call_name(tgt) in (
            "partial", "functools.partial") and tgt.args:
        inner = tgt.args[0]
        name = (ast.unparse(inner)
                if isinstance(inner, (ast.Name, ast.Attribute)) else "?")
        fn = local_defs.get(name)
        # partial binds keywords in this codebase; positional params of
        # the underlying def still apply when it is locally resolvable.
        return f"partial:{name}", _params_of(fn.args) if fn else ()
    if isinstance(tgt, (ast.Attribute, ast.Name)):
        return ast.unparse(tgt), ()
    return "<expr>", ()


def _is_jit_decorator(dec: ast.expr) -> Optional[ast.Call]:
    """Returns the kwargs-carrying call for decorator forms; bare
    `@jax.jit` returns a synthetic empty marker (None kwargs source)."""
    call = _jit_callee(dec)
    if call is not None:
        return call
    return None


class _Extractor:
    def __init__(self, src: SourceFile) -> None:
        self.src = src
        self.sites: list[JitSite] = []
        # module + nested defs by bare name, for target param resolution
        self.defs: dict[str, ast.FunctionDef] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[node.name] = node

    def run(self) -> list[JitSite]:
        self._visit_body(self.src.tree.body, scope="<module>", cls=None,
                         in_loop=False)
        return self.sites

    # -- traversal ---------------------------------------------------------

    def _visit_body(self, body: list, scope: str, cls: Optional[str],
                    in_loop: bool) -> None:
        for stmt in body:
            self._visit_stmt(stmt, scope, cls, in_loop)

    def _visit_stmt(self, stmt: ast.stmt, scope: str, cls: Optional[str],
                    in_loop: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in stmt.decorator_list:
                self._record_decorator(stmt, dec, scope, cls, in_loop)
            inner = (stmt.name if cls is None else f"{cls}.{stmt.name}")
            self._visit_function(stmt, inner)
            return
        if isinstance(stmt, ast.ClassDef):
            self._visit_body(stmt.body, scope, stmt.name, in_loop)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            self._scan_exprs(stmt, scope, in_loop, stmt_ctx=None,
                             header_only=True)
            self._visit_body(stmt.body + stmt.orelse, scope, cls, True)
            return
        if isinstance(stmt, (ast.If, ast.With, ast.AsyncWith)):
            self._scan_exprs(stmt, scope, in_loop, stmt_ctx=None,
                             header_only=True)
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.stmt):
                    self._visit_stmt(sub, scope, cls, in_loop)
            return
        if isinstance(stmt, ast.Try):
            for sub in (stmt.body + stmt.orelse + stmt.finalbody):
                self._visit_stmt(sub, scope, cls, in_loop)
            for handler in stmt.handlers:
                self._visit_body(handler.body, scope, cls, in_loop)
            return
        self._scan_exprs(stmt, scope, in_loop, stmt_ctx=stmt)

    def _visit_function(self, fn, scope: str) -> None:
        """Call-form sites inside one function, with local disposition
        refinement (a local later stored in a cache/attr is not a
        per-call construction)."""
        before = len(self.sites)
        self._visit_body(fn.body, scope, cls=None, in_loop=False)
        new = [s for s in self.sites[before:]
               if s.scope == scope and s.form == "call"]
        if not new:
            return
        locals_to_sites: dict[str, list[JitSite]] = {}
        for site in new:
            if site.disposition.startswith("local:"):
                locals_to_sites.setdefault(
                    site.disposition.split(":", 1)[1], []).append(site)
        if locals_to_sites:
            self._refine_locals(fn, locals_to_sites)
        for site in new:  # anything still raw-local collapses to "local"
            if site.disposition.startswith("local:"):
                site.disposition = "local"

    def _refine_locals(self, fn, locals_to_sites: dict) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and isinstance(node.value,
                                                           ast.Name):
                for site in locals_to_sites.get(node.value.id, ()):
                    site.disposition = "returned"
            elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                             ast.Name):
                sites = locals_to_sites.get(node.value.id, ())
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        for site in sites:
                            site.disposition = f"attr:{tgt.attr}"
                    elif isinstance(tgt, ast.Subscript):
                        cont = _container_name(tgt.value)
                        for site in sites:
                            site.disposition = f"cached:{cont}"
                            site.cache_key = ast.unparse(tgt.slice)

    # -- site recording ----------------------------------------------------

    def _record_decorator(self, fn, dec: ast.expr, scope: str,
                          cls: Optional[str], in_loop: bool) -> None:
        call = _is_jit_decorator(dec)
        bare = (isinstance(dec, (ast.Attribute, ast.Name))
                and ast.unparse(dec) in ("jax.jit", "jit"))
        if call is None and not bare:
            return
        kwargs = _jit_kwargs(call) if call is not None else {
            "static_argnames": (), "static_argnums": (),
            "donate_argnums": (), "donate_declared": False}
        self.sites.append(JitSite(
            rel=self.src.rel, line=fn.lineno, scope=scope,
            form="decorator", target=fn.name,
            disposition="decorator", in_loop=in_loop,
            target_params=_params_of(fn.args), node=fn, **kwargs))

    def _scan_exprs(self, stmt: ast.stmt, scope: str, in_loop: bool,
                    stmt_ctx: Optional[ast.stmt],
                    header_only: bool = False) -> None:
        """Find jit Call nodes inside a statement (or just its header
        expressions for compound statements)."""
        if header_only:
            roots = [n for n in ast.iter_child_nodes(stmt)
                     if isinstance(n, ast.expr)]
        else:
            roots = [stmt]
        for root in roots:
            for node in ast.walk(root):
                call = _jit_callee(node)
                if call is None or call is not node:
                    continue
                self._record_call(call, stmt if stmt_ctx is None else
                                  stmt_ctx, scope, in_loop, root)

    def _record_call(self, call: ast.Call, stmt: ast.stmt, scope: str,
                     in_loop: bool, root: ast.AST) -> None:
        target, params = _target_info(call, self.defs)
        disposition = "local"
        cache_key = ""
        if scope == "<module>":
            disposition = "module"
        elif isinstance(stmt, ast.Return) and stmt.value is call:
            disposition = "returned"
        elif isinstance(stmt, ast.Assign) and stmt.value is call:
            tgt = stmt.targets[0]
            if isinstance(tgt, ast.Attribute):
                disposition = f"attr:{tgt.attr}"
            elif isinstance(tgt, ast.Subscript):
                disposition = f"cached:{_container_name(tgt.value)}"
                cache_key = ast.unparse(tgt.slice)
            elif isinstance(tgt, ast.Name):
                disposition = f"local:{tgt.id}"  # refined by caller
        else:
            # jax.jit(...)(...) — constructed and invoked in one
            # expression: a fresh callable (and an empty jit cache)
            # every time the statement runs.
            for outer in ast.walk(root):
                if isinstance(outer, ast.Call) and outer.func is call:
                    disposition = "immediate"
                    break
        kwargs = _jit_kwargs(call)
        self.sites.append(JitSite(
            rel=self.src.rel, line=call.lineno, scope=scope, form="call",
            target=target, disposition=disposition, cache_key=cache_key,
            in_loop=in_loop, target_params=params, node=call, **kwargs))


def _container_name(node: ast.expr) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ast.unparse(node)


def extract_jit_sites(files: list[SourceFile]) -> list[JitSite]:
    sites: list[JitSite] = []
    for src in files:
        sites.extend(_Extractor(src).run())
    return sites


# One extraction shared by every DJ1xx rule in a run (run() hands all
# rules the same `files` list; the entry keys the list itself so a freed
# id() can never serve a stale surface — the dynaflow cache contract).
_CACHE: dict[int, tuple[list, list]] = {}


def jit_sites(files: list[SourceFile]) -> list[JitSite]:
    hit = _CACHE.get(id(files))
    if hit is not None and hit[0] is files:
        return hit[1]
    if len(_CACHE) > 8:
        _CACHE.clear()
    sites = extract_jit_sites(files)
    _CACHE[id(files)] = (files, sites)
    return sites


# -- registry snapshot -------------------------------------------------------


def surface_json(files: list[SourceFile]) -> dict:
    entries = sorted((s.signature() for s in jit_sites(files)),
                     key=lambda e: json.dumps(e, sort_keys=True))
    return {"version": 1, "sites": entries}


def update_registry(files: list[SourceFile],
                    registry_path: pathlib.Path = REGISTRY_PATH) -> bool:
    """Regenerate the checked-in jit-signature registry; True if it
    changed."""
    registry_path.parent.mkdir(parents=True, exist_ok=True)
    payload = json.dumps(surface_json(files), indent=2,
                         sort_keys=True) + "\n"
    if registry_path.exists() and registry_path.read_text() == payload:
        return False
    registry_path.write_text(payload)
    return True


def diff_registry(files: list[SourceFile],
                  registry_path: pathlib.Path = REGISTRY_PATH,
                  ) -> Optional[list[str]]:
    """None when the tree matches the snapshot; otherwise a list of
    human-readable drift lines (added/removed signature entries)."""
    if not registry_path.exists():
        return ["no jit-signature registry at "
                f"{registry_path}; run `python -m tools.dynajit "
                "--registry-update` and commit the result"]
    want = json.loads(registry_path.read_text())
    got = surface_json(files)
    if got == want:
        return None

    def keyed(payload: dict) -> dict[str, int]:
        out: dict[str, int] = {}
        for entry in payload.get("sites", []):
            key = json.dumps(entry, sort_keys=True)
            out[key] = out.get(key, 0) + 1
        return out

    want_k, got_k = keyed(want), keyed(got)
    lines = []
    for key in sorted(set(got_k) - set(want_k)):
        entry = json.loads(key)
        lines.append(f"added: {entry['file']}::{entry['scope']} "
                     f"jit({entry['target']}) [{entry['disposition']}]")
    for key in sorted(set(want_k) - set(got_k)):
        entry = json.loads(key)
        lines.append(f"removed: {entry['file']}::{entry['scope']} "
                     f"jit({entry['target']}) [{entry['disposition']}]")
    for key in sorted(set(want_k) & set(got_k)):
        if want_k[key] != got_k[key]:
            entry = json.loads(key)
            lines.append(
                f"count changed ({want_k[key]} -> {got_k[key]}): "
                f"{entry['file']}::{entry['scope']} "
                f"jit({entry['target']})")
    return lines or ["signature ordering drifted (regenerate)"]


def iter_sites_in(files: list[SourceFile],
                  rel_suffixes: tuple[str, ...]) -> Iterable[JitSite]:
    for site in jit_sites(files):
        if site.rel.endswith(rel_suffixes):
            yield site
