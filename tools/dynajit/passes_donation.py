"""DJ3xx — buffer-donation discipline at the jit boundary.

Donation (`donate_argnums`) is how the engine steps a multi-GiB paged KV
pool without doubling HBM: the input buffer is retired as the output
materializes. It is also the sharpest tool in the box — a donated array
read after the call is a use-after-free XLA only sometimes catches
(`.delete()`d buffer errors on TPU, silent garbage in interpret mode),
and a donated self-attribute that is not rebound in the same statement
leaves every OTHER method holding a dead pointer.

Three rules:

  * DJ301 use-after-donate — an argument passed at a donated position is
    read again after the call without being rebound by it.
  * DJ302 donated-attr-not-rebound — a donated `self.X` must be rebound
    by the call statement's own targets (`self.X, ... = fn(...)`); any
    later method reading the stale attribute is undefined behavior.
  * DJ303 kv-param-donation-undeclared — a jit whose wrapped callable
    takes a KV-pool-shaped parameter (`kv`, `kv_cache`, `kv_pool`,
    `cache`) must carry an explicit `donate_argnums` — donating it, or
    `donate_argnums=()` to declare the read-only intent (the
    ops/block_copy.py gather convention). Donation on the largest
    buffers in the program must never be implicit.

Donating callables are resolved through the idioms this codebase uses:
direct `jax.jit(..., donate_argnums=...)` calls (immediate or bound to
a local), and locals assigned from `self._build_*` builder methods whose
returned jit donates — including the `fn(*args)` dispatch form when
`args` is a local list literal.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, Rule, SourceFile

from .jit_surface import _jit_callee, _jit_kwargs, jit_sites

KV_PARAM_NAMES = {"kv", "kv_cache", "kv_pool", "cache"}


def _donated_nums(call: ast.Call) -> tuple[int, ...]:
    return _jit_kwargs(call)["donate_argnums"]


def _file_builders(src: SourceFile) -> dict[str, tuple[int, ...]]:
    """Method/function name -> donated argnums of the jit it returns."""
    out: dict[str, tuple[int, ...]] = {}
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Call)):
                continue
            call = _jit_callee(node.value)
            if call is not None and _donated_nums(call):
                out[fn.name] = _donated_nums(call)
    return out


def _expr_key(node: ast.expr) -> Optional[str]:
    """Stable key for a donated argument expression: a bare name or a
    self-attribute. Anything else (calls, subscripts) is a fresh value
    the caller cannot re-read."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name) \
            and node.value.id == "self":
        return f"self.{node.attr}"
    return None


def _targets_rebinding(stmt: ast.stmt) -> set[str]:
    """Keys rebound by an assignment statement's targets (tuple targets
    flattened)."""
    out: set[str] = set()
    if not isinstance(stmt, ast.Assign):
        return out
    stack: list[ast.expr] = list(stmt.targets)
    while stack:
        tgt = stack.pop()
        if isinstance(tgt, (ast.Tuple, ast.List)):
            stack.extend(tgt.elts)
            continue
        key = _expr_key(tgt)
        if key is not None:
            out.add(key)
    return out


class _DonationAnalysis:
    """Per-function resolution of donating calls and their donated
    argument expressions."""

    def __init__(self, src: SourceFile, fn,
                 builders: dict[str, tuple[int, ...]]) -> None:
        self.src = src
        self.fn = fn
        self.builders = builders
        # local name -> donated argnums (jit assignments + builder calls)
        self.donating_locals: dict[str, tuple[int, ...]] = {}
        # local list literals (for the `fn(*args)` dispatch form)
        self.list_locals: dict[str, ast.List] = {}
        self._collect()

    def _collect(self) -> None:
        for node in ast.walk(self.fn):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            nums: tuple[int, ...] = ()
            jit = _jit_callee(val) if isinstance(val, ast.Call) else None
            if jit is not None:
                nums = _donated_nums(jit)
            elif isinstance(val, ast.Call):
                f = val.func
                tail = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
                nums = self.builders.get(tail, ())
            if isinstance(val, ast.List):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.list_locals[tgt.id] = val
            if nums:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.donating_locals[tgt.id] = nums

    def donating_calls(self) -> list[tuple[ast.Call, tuple[int, ...]]]:
        out = []
        for node in ast.walk(self.fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            jit = _jit_callee(f) if isinstance(f, ast.Call) else None
            if jit is not None and _donated_nums(jit):
                out.append((node, _donated_nums(jit)))
            elif isinstance(f, ast.Name) \
                    and f.id in self.donating_locals:
                out.append((node, self.donating_locals[f.id]))
        return out

    def positional_args(self, call: ast.Call) -> list[ast.expr]:
        """Positional arguments, expanding `*args` when args is a local
        list literal (the ModelRunner dispatch idiom)."""
        out: list[ast.expr] = []
        for arg in call.args:
            if isinstance(arg, ast.Starred):
                if isinstance(arg.value, ast.Name) \
                        and arg.value.id in self.list_locals:
                    out.extend(self.list_locals[arg.value.id].elts)
                else:
                    return out  # opaque splat: stop resolving positions
            else:
                out.append(arg)
        return out


def _statement_of(fn, node: ast.AST) -> Optional[ast.stmt]:
    """Innermost statement containing `node` plus the flat statement
    sequence (pre-order) of the function for after-the-call scanning."""
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.stmt):
            if any(sub is node for sub in ast.walk(stmt)):
                found = stmt
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.stmt) and any(
                            sub is node for sub in ast.walk(child)):
                        return _statement_of_inner(child, node)
                return found
    return None


def _statement_of_inner(stmt: ast.stmt, node: ast.AST) -> ast.stmt:
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, ast.stmt) and any(
                sub is node for sub in ast.walk(child)):
            return _statement_of_inner(child, node)
    return stmt


def _reads_after(fn, call_stmt: ast.stmt, key: str) -> Optional[ast.AST]:
    """First read of `key` in statements AFTER call_stmt (document
    order), stopping at the first rebind."""
    stmts = [s for s in ast.walk(fn) if isinstance(s, ast.stmt)]
    stmts.sort(key=lambda s: (s.lineno, s.col_offset))
    started = False
    for stmt in stmts:
        if stmt is call_stmt:
            started = True
            continue
        if not started or stmt.lineno <= call_stmt.lineno:
            continue
        if key in _targets_rebinding(stmt):
            # rebound before any read: the stale buffer is unreachable
            value_read = _read_in(stmt.value, key) \
                if isinstance(stmt, ast.Assign) else None
            return value_read
        read = _read_in(stmt, key)
        if read is not None:
            return read
    return None


def _read_in(node: Optional[ast.AST], key: str) -> Optional[ast.AST]:
    if node is None:
        return None
    for sub in ast.walk(node):
        if _expr_key(sub) == key and isinstance(
                getattr(sub, "ctx", ast.Load()), ast.Load):
            return sub
    return None


class UseAfterDonate(ProjectRule):
    id = "DJ301"
    name = "use-after-donate"
    description = (
        "an argument passed at a donated position of a jit-compiled "
        "call is read again after the call without being rebound: the "
        "buffer was retired by XLA — on device this is a deleted-buffer "
        "error at best and silent garbage at worst")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for src in files:
            builders = _file_builders(src)
            for fn in ast.walk(src.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                yield from self._check_fn(src, fn, builders)

    def _check_fn(self, src: SourceFile, fn,
                  builders: dict) -> Iterable[Finding]:
        analysis = _DonationAnalysis(src, fn, builders)
        for call, nums in analysis.donating_calls():
            args = analysis.positional_args(call)
            stmt = _statement_of(fn, call)
            if stmt is None:
                continue
            rebound = _targets_rebinding(stmt)
            for num in nums:
                if num >= len(args):
                    continue
                key = _expr_key(args[num])
                if key is None or key in rebound:
                    continue
                read = _reads_after(fn, stmt, key)
                if read is not None:
                    yield Finding(
                        self.id, self.name, src.rel,
                        getattr(read, "lineno", call.lineno),
                        getattr(read, "col_offset", 0),
                        f"{key!r} was donated at position {num} of the "
                        f"jit call on line {call.lineno} and is read "
                        "again here without being rebound — the buffer "
                        "no longer exists")


class DonatedAttrNotRebound(ProjectRule):
    id = "DJ302"
    name = "donated-attr-not-rebound"
    description = (
        "a donated `self.<attr>` must be rebound by the donating call's "
        "own statement (`self.kv_cache, ... = fn(...)`): the attribute "
        "outlives this function, and any other method reading it after "
        "the call holds a retired buffer")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for src in files:
            builders = _file_builders(src)
            for fn in ast.walk(src.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                analysis = _DonationAnalysis(src, fn, builders)
                for call, nums in analysis.donating_calls():
                    args = analysis.positional_args(call)
                    stmt = _statement_of(fn, call)
                    if stmt is None:
                        continue
                    rebound = _targets_rebinding(stmt)
                    for num in nums:
                        if num >= len(args):
                            continue
                        key = _expr_key(args[num])
                        if key is None or not key.startswith("self.") \
                                or key in rebound:
                            continue
                        yield Finding(
                            self.id, self.name, src.rel, call.lineno,
                            call.col_offset,
                            f"{key} is donated here but the statement "
                            "does not rebind it — every later reader "
                            "of the attribute holds a retired buffer; "
                            "rebind it in the same statement")


class KvParamDonationUndeclared(Rule):
    id = "DJ303"
    name = "kv-param-donation-undeclared"
    description = (
        "a jit-compiled callable takes a KV-pool-shaped parameter "
        "(kv/kv_cache/kv_pool/cache) with NO donate_argnums "
        "declaration: donation on the largest buffers in the program "
        "must be explicit — donate it, or declare `donate_argnums=()` "
        "to pin the read-only intent (the ops/block_copy.py gather "
        "convention)")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for site in jit_sites([src]):
            if site.donate_declared:
                continue
            hits = [p for p in site.target_params if p in KV_PARAM_NAMES]
            if not hits:
                continue
            node = site.node
            yield Finding(
                self.id, self.name, src.rel,
                getattr(node, "lineno", site.line),
                getattr(node, "col_offset", 0),
                f"jit({site.target}) takes KV-pool parameter(s) "
                f"{', '.join(hits)} with no donate_argnums declaration; "
                "donate them or declare donate_argnums=() explicitly")
