"""DJ2xx — host-device sync reachability from the dispatch loop.

dynalint's DL201 flags syncs *inside loops*, per file. That misses the
class of regression that actually moved TTFT in round 5: a straight-line
`.item()` / bare `np.asarray` added three calls deep under
`_dispatch_decode` serializes host and device once per engine iteration
and no runtime test notices (CPU tests have no dispatch pipeline to
stall). This pass walks dynaflow's name-resolved call graph from the
serving plane's hot entry points — the scheduler's dispatch/drain/
prefill phases, every ModelRunner decode*/prefill* step, and the
run_in_gap maintenance window (KVBM offload gathers) — and flags every
host-sync operation reachable from them.

Device-readback detection leans on a repo convention the rule also
enforces: host-side array conversions ALWAYS pass an explicit dtype
(`np.asarray(tokens, np.int32)`), while device readbacks are bare
one-argument calls (`np.asarray(toks_dev)`). A flagged line is either a
real regression (fix it) or a designed drain point (suppress it with a
justification — the suppression inventory doubles as the canonical list
of every host sync on the dispatch path).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.dynaflow.graph import FunctionInfo, get_project
from tools.dynalint.core import (
    Finding,
    ProjectRule,
    SourceFile,
    call_name,
    walk_skip_functions,
)

# The serving plane's hot entry points (function bare names).
HOT_ENTRIES = (
    # scheduler loop phases (engine/scheduler.py)
    "_step", "_dispatch_decode", "_drain_decode", "_drain_spec",
    "_prefill_some", "_drain_gap",
    # compiled-step host API (engine/model_runner.py)
    "decode", "decode_multi", "decode_spec",
    "prefill_chunk", "prefill_chunk_batch", "prefill_ring_batch",
    # the maintenance-window device ops (gap callbacks gather through
    # these; the closures themselves are lambdas the graph cannot name)
    "gather_pages_device", "scatter_pages",
)

# Files whose functions participate in the reachability walk. The name-
# resolved graph over-approximates; bounding the walk to the dispatch
# plane keeps every finding a genuine hot-path sync.
SCOPE_MARKERS = ("/engine/", "block_manager/offload.py")

_SYNC_NAMES = {"jax.device_get"}
_SYNC_METHODS = {"item", "block_until_ready"}
_BARE_READBACK = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}


def _in_scope(rel: str) -> bool:
    return any(marker in rel for marker in SCOPE_MARKERS)


def _sync_call(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if name in _SYNC_NAMES:
        return name
    last = name.split(".")[-1]
    if last in _SYNC_METHODS and not node.args and not node.keywords:
        return f".{last}()"
    if name in _BARE_READBACK and len(node.args) == 1 \
            and not node.keywords:
        # Bare one-arg form = device readback by repo convention; host
        # conversions pass an explicit dtype and are exempt.
        return name
    return None


class HostSyncReachable(ProjectRule):
    id = "DJ201"
    name = "host-sync-reachable-from-dispatch"
    description = (
        "a host-device synchronization (.item(), .block_until_ready(), "
        "jax.device_get, or a bare one-argument np.asarray/np.array — "
        "the repo's device-readback form; dtype-carrying conversions "
        "are host-side and exempt) is reachable from a serving-plane "
        "hot entry (scheduler dispatch/drain, ModelRunner "
        "decode*/prefill*, the run_in_gap window) over the call graph: "
        "it serializes host and device once per engine iteration — "
        "remove it, defer it behind the next dispatch, or suppress "
        "with a justification naming why this drain point is designed")

    def __init__(self, entries: tuple[str, ...] = HOT_ENTRIES) -> None:
        self.entries = entries

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        project = get_project(files)
        entry_fns = [fn for name in self.entries
                     for fn in project.by_name.get(name, ())
                     if _in_scope(fn.rel)]
        if not entry_fns:
            return
        reachable = self._reachable_in_scope(project, entry_fns)
        src_by_rel = {src.rel: src for src in files}
        seen: set[tuple[str, int, int]] = set()
        for qualname in sorted(reachable):
            fn = project.functions[qualname]
            if fn.name == "<module>":
                continue
            src = src_by_rel.get(fn.rel)
            if src is None:
                continue
            for finding in self._check_fn(src, fn):
                key = (finding.path, finding.line, finding.col)
                if key not in seen:
                    seen.add(key)
                    yield finding

    @staticmethod
    def _reachable_in_scope(project, entries: list[FunctionInfo]
                            ) -> set[str]:
        # calls-only edges (refs_too=False): bare-name references are
        # how dynaflow catches callback hand-offs, but here they link a
        # loop variable named `start` to `Scheduler.start` and drag the
        # whole offload thread into the "dispatch path". The gap-window
        # device ops the callbacks reach (gather_pages_device /
        # scatter_pages) are entries in their own right, so the
        # precision costs no coverage.
        out: set[str] = set()
        stack = list(entries)
        while stack:
            fn = stack.pop()
            if fn.qualname in out:
                continue
            out.add(fn.qualname)
            stack.extend(c for c in project.callees(fn, refs_too=False)
                         if c.qualname not in out and _in_scope(c.rel))
        return out

    def _check_fn(self, src: SourceFile,
                  fn: FunctionInfo) -> Iterable[Finding]:
        body = getattr(fn.node, "body", None)
        if not isinstance(body, list):
            return
        # Nested defs/lambdas are their own graph nodes (or escape the
        # dispatch plane entirely); only this function's own statements
        # execute on its call path.
        for node in walk_skip_functions(body):
            if not isinstance(node, ast.Call):
                continue
            sync = _sync_call(node)
            if sync is None:
                continue
            yield Finding(
                self.id, self.name, src.rel, node.lineno, node.col_offset,
                f"{sync} in {fn.name!r} is reachable from a dispatch-"
                "loop hot entry: a blocking host-device round trip per "
                "engine iteration — defer the readback behind the next "
                "dispatch or justify the drain point")
