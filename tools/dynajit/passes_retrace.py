"""DJ1xx — retrace hazards over the jit surface.

A `jax.jit` callable caches compiled executables keyed on (its own
Python identity, static argument values, traced shapes/dtypes). Three
construction mistakes defeat that cache silently:

  * a jit constructed inside a loop compiles fresh EVERY iteration
    (each lambda/partial is a new identity with an empty cache);
  * a jit constructed per call — immediately invoked, or bound to a
    local that is never stored — compiles fresh every call of the
    enclosing function;
  * a dict cache of jitted callables keyed on a raw per-request value
    retains one compiled program per distinct value forever: a client
    parameter sweep becomes a compile storm plus unbounded executable
    retention.

The blessed idioms this codebase already uses are recognized and pass
clean: module-level/decorator jits, builder methods that `return
jax.jit(...)` into a cache, `self.<cache>[key] = fn` stores, and cache
keys derived through the pow2 bucketing helpers (`_bucket_for`,
`bucket_table_width`, `.bit_length()`); caches with an eviction path
(`.pop`/`popitem`/`del`) are bounded by construction. Everything else
is a finding — fix it or suppress it with a justification on the line.

DJ104 turns the whole surface into a drift gate: the extracted
signatures must match the checked-in registry
(`tools/dynajit/signatures/jit_surface.json`); bless deliberate changes
with `python -m tools.dynajit --registry-update`.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, SourceFile

from .jit_surface import (
    REGISTRY_PATH,
    JitSite,
    _container_name,
    _jit_callee,
    diff_registry,
    jit_sites,
)

# Key-derivation helpers that bound a cache-key domain to pow2 buckets.
BUCKETING_CALLS = ("_bucket_for", "bucket_table_width", "bit_length")


class _SurfaceRule(ProjectRule):
    def _finding(self, site: JitSite, message: str) -> Finding:
        node = site.node
        return Finding(self.id, self.name, site.rel,
                       getattr(node, "lineno", site.line),
                       getattr(node, "col_offset", 0), message)


class JitInLoop(_SurfaceRule):
    id = "DJ101"
    name = "jit-in-loop"
    description = (
        "jax.jit constructed inside a for/while body: every iteration "
        "creates a fresh callable with an empty compile cache, so the "
        "device recompiles per iteration — hoist the construction out "
        "of the loop (or into a cached builder)")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for site in jit_sites(files):
            if site.in_loop:
                yield self._finding(
                    site,
                    f"jit({site.target}) is constructed inside a loop "
                    "body; each iteration compiles from scratch — hoist "
                    "it out of the loop")


class PerCallJit(_SurfaceRule):
    id = "DJ102"
    name = "per-call-jit-construction"
    description = (
        "jax.jit constructed per call of its enclosing function "
        "(invoked immediately, or bound to a local that is never "
        "stored): the callable's compile cache dies with the call, so "
        "every invocation recompiles — store it (module level, "
        "attribute, bounded cache, or a `return jax.jit(...)` builder). "
        "__init__ is exempt (one-time construction by definition)")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for site in jit_sites(files):
            if site.form != "call" or site.scope == "<module>":
                continue
            if site.in_loop:
                continue  # DJ101 owns loop-constructed sites
            if site.disposition not in ("immediate", "local"):
                continue
            method = site.scope.rsplit(".", 1)[-1]
            if method == "__init__":
                continue
            how = ("invoked in the same expression"
                   if site.disposition == "immediate"
                   else "bound to a local that is never stored")
            yield self._finding(
                site,
                f"jit({site.target}) in {site.scope!r} is {how}: a "
                "fresh callable (and an empty compile cache) per call "
                "— hoist it, or store it in a bounded cache")


class UnboundedJitCacheKey(_SurfaceRule):
    id = "DJ103"
    name = "unbounded-jit-cache-key"
    description = (
        "a dict cache of compiled callables is keyed on a raw function "
        "parameter with no eviction on the container: one executable "
        "retained per distinct value, forever — bucket the key "
        "(pow2 helpers), bound the cache (.pop/popitem eviction), or "
        "justify why the key domain is finite. bool-annotated key "
        "components are exempt (domain of 2)")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for src in files:
            yield from self._check_file(src)

    def _check_file(self, src: SourceFile) -> Iterable[Finding]:
        builders = _builder_names(src)
        evicted = _evicted_containers(src)
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_fn(src, fn, builders, evicted)

    def _check_fn(self, src: SourceFile, fn, builders: set[str],
                  evicted: set[str]) -> Iterable[Finding]:
        params = {a.arg: a for a in (fn.args.posonlyargs + fn.args.args
                                     + fn.args.kwonlyargs)}
        bucketed = _bucketed_names(fn)
        # locals holding compiled callables: jit results or builder calls
        jit_locals: set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if _is_compiled_value(node.value, builders):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        jit_locals.add(tgt.id)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.targets[0], ast.Subscript)):
                continue
            store = node.targets[0]
            is_compiled = (_is_compiled_value(node.value, builders)
                           or (isinstance(node.value, ast.Name)
                               and node.value.id in jit_locals))
            if not is_compiled:
                continue
            container = _container_name(store.value)
            if container in evicted:
                continue
            raw = self._raw_param_keys(store.slice, params, bucketed)
            if raw:
                yield Finding(
                    self.id, self.name, src.rel, node.lineno,
                    node.col_offset,
                    f"compiled-callable cache {container!r} is keyed on "
                    f"raw parameter(s) {', '.join(sorted(raw))} with no "
                    "eviction on the container: unbounded executable "
                    "retention — bucket the key or bound the cache")

    @staticmethod
    def _raw_param_keys(key: ast.expr, params: dict,
                        bucketed: set[str]) -> set[str]:
        raw: set[str] = set()
        for node in ast.walk(key):
            if not isinstance(node, ast.Name) or node.id not in params:
                continue
            if node.id in bucketed:
                continue
            ann = params[node.id].annotation
            if isinstance(ann, ast.Name) and ann.id == "bool":
                continue
            if isinstance(ann, ast.Constant) and ann.value == "bool":
                continue
            raw.add(node.id)
        return raw


def _builder_names(src: SourceFile) -> set[str]:
    """Functions in this file that return a jax.jit-compiled callable
    (the `_build_*` idiom) — calls to them produce compiled values."""
    out: set[str] = set()
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                if any(isinstance(sub, ast.Call)
                       and _jit_callee(sub) is not None
                       for sub in ast.walk(node.value)):
                    out.add(fn.name)
    return out


def _evicted_containers(src: SourceFile) -> set[str]:
    """Container attribute/variable names with an eviction path
    somewhere in the file (`X.pop(...)`, `X.popitem(...)`, `del X[...]`)
    — a bounded cache by construction."""
    out: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if node.func.attr in ("pop", "popitem"):
                out.add(_container_name(node.func.value))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    out.add(_container_name(tgt.value))
    return out


def _bucketed_names(fn) -> set[str]:
    """Local names assigned (anywhere in the function) through a pow2
    bucketing helper — their value domain is finite by construction."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        uses_bucketing = any(
            isinstance(sub, ast.Call) and isinstance(sub.func,
                                                     ast.Attribute)
            and sub.func.attr in BUCKETING_CALLS
            or (isinstance(sub, ast.Call) and isinstance(sub.func,
                                                         ast.Name)
                and sub.func.id in BUCKETING_CALLS)
            for sub in ast.walk(node.value))
        if uses_bucketing:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _is_compiled_value(value: ast.expr, builders: set[str]) -> bool:
    if isinstance(value, ast.Call):
        if _jit_callee(value) is not None:
            return True
        fn = value.func
        tail = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        return tail in builders
    return False


class JitSignatureDrift(ProjectRule):
    id = "DJ104"
    name = "jit-signature-drift"
    description = (
        "the tree's extracted jit surface (sites, static/donate "
        "declarations, cache dispositions) diverged from the checked-in "
        "registry under tools/dynajit/signatures/ — compile-triggering "
        "signature changes must be deliberate: run `python -m "
        "tools.dynajit --registry-update` and commit the diff")

    def __init__(self,
                 registry_path: Optional[pathlib.Path] = REGISTRY_PATH,
                 ) -> None:
        self.registry_path = registry_path

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        if self.registry_path is None or not files:
            return
        if not any(jit_sites(files)):
            return  # no jit surface in this file set; nothing to gate
        drift = diff_registry(files, self.registry_path)
        if drift is None:
            return
        src = files[0]
        yield Finding(
            self.id, self.name, src.rel, 1, 0,
            "jit surface drifted from the checked-in signature "
            "registry: " + "; ".join(drift[:8])
            + ("; ..." if len(drift) > 8 else "")
            + " — if deliberate, run `python -m tools.dynajit "
            "--registry-update` and commit the diff")
