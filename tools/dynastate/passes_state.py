"""dynastate rule families DS1xx–DS5xx.

All rules are ProjectRules driven by the hand-authored protocol specs
(tools/dynastate/protocols/*.json — see specs.py for the shape and
docs/static-analysis.md for the authoring workflow):

* DS100 invalid-protocol-spec — the spec file itself is malformed
  (undeclared states/events in transitions, missing initial, terminal
  states with outgoing edges).
* DS101 unhandled-tag-in-state — a frame the spec says the protocol
  emits has no emission site left in the code (dead spec arm), or a
  dispatching consumer never reads the frame's marker — the
  "cancelled-frame hang" bug class: the producer emits a tag the
  consumer silently drops, and the machine wedges in a non-terminal
  state.
* DS201 post-terminal-emission — an api method that drives the machine
  does not read the terminal-state flags before emitting (so a call
  after fail()/finish() mutates a settled lifecycle), or a producer
  emits another frame lexically after a terminal frame in the same
  block.
* DS301 no-failure-path-to-terminal — a non-terminal, non-idle state
  has no failure/cancellation transition whose path reaches a terminal
  state: an error there strands the instance forever.
* DS401 cancellation-unhandled-in-state — a cancellation event is not
  accepted in some non-terminal state (and the state is not explicitly
  listed in the event's `ignores`).
* DS501 terminal-frame-not-exactly-once — a terminal frame is emitted
  inside a loop without an immediate exit (the stream could terminate
  twice), or an api terminal event has no emitting method.

Suppress on the flagged line with
``# dynastate: disable=DS201 -- justification`` citing the spec file
and the invariant that makes the site safe.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.dynalint.core import Finding, ProjectRule, SourceFile
from tools.dynaflow.graph import get_project

from . import extraction, specs
from .extraction import EmitSite, fn_label


def _spec_finding(rule, spec, message: str) -> Finding:
    return Finding(rule.id, rule.name, spec.path, 1, 0, message)


def _fn_finding(rule, fn, message: str) -> Finding:
    return Finding(rule.id, rule.name, fn.rel, fn.lineno, 0, message)


def _site_finding(rule, site: EmitSite, message: str) -> Finding:
    return Finding(rule.id, rule.name, site.fn.rel,
                   getattr(site.node, "lineno", site.fn.lineno),
                   getattr(site.node, "col_offset", 0), message)


class SpecValidity(ProjectRule):
    id = "DS100"
    name = "invalid-protocol-spec"
    description = (
        "A protocol spec under tools/dynastate/protocols/ is malformed: "
        "unparseable JSON, transitions naming undeclared states or "
        "events, a missing initial state, or a terminal state with "
        "outgoing edges. The spec files drive both the static rules and "
        "the runtime ProtocolMonitor, so a broken spec silently disables "
        "conformance checking.")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for spec in specs.load_specs():
            for err in spec.errors:
                yield _spec_finding(self, spec,
                                    f"protocol {spec.name!r}: {err}")


class UnhandledTag(ProjectRule):
    id = "DS101"
    name = "unhandled-tag-in-state"
    description = (
        "A spec'd wire frame is emitted by no producer left in the tree "
        "(the spec models an emission the code no longer performs), or "
        "a dispatching consumer never reads the frame's marker key — "
        "the consumer silently drops a tag the producer emits and the "
        "protocol wedges in a non-terminal state (the cancelled-frame-"
        "hang bug class).")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        project = get_project(files)
        for spec in specs.load_specs():
            if spec.errors:
                continue
            model = extraction.wire_model(spec, project)
            if model is None:
                continue
            for token, fns in model.producers.items():
                if not fns:
                    yield _spec_finding(
                        self, spec,
                        f"protocol {spec.name!r}: producer {token!r} "
                        "matches no function in the tree")
            for token, fns in model.consumers.items():
                if not fns:
                    yield _spec_finding(
                        self, spec,
                        f"protocol {spec.name!r}: consumer {token!r} "
                        "matches no function in the tree")
            for frame, body in (spec.wire.get("frames") or {}).items():
                body = body or {}
                sites = model.sites.get(frame, [])
                if not sites and any(model.frame_producers(frame)
                                     .values()):
                    yield _spec_finding(
                        self, spec,
                        f"protocol {spec.name!r}: frame {frame!r} has no "
                        "emission site in its producers — dead spec arm "
                        "or the emission moved; update the spec or the "
                        "code")
                    continue
                if not sites:
                    continue
                reads = body.get("read", []) or []
                if not reads:
                    continue
                for token, fns in model.frame_consumers(frame).items():
                    for fn in fns:
                        if not any(extraction._match_read(fn, m)
                                   for m in reads):
                            want = ", ".join(
                                str(m.get("key") or m.get("attr"))
                                for m in reads)
                            yield _fn_finding(
                                self, fn,
                                f"consumer {fn_label(fn)} never reads "
                                f"{want!r}: the {frame!r} frame of "
                                f"protocol {spec.name!r} is emitted "
                                "but silently dropped here")


class PostTerminalEmission(ProjectRule):
    id = "DS201"
    name = "post-terminal-emission"
    description = (
        "An api method that drives a protocol machine does not read the "
        "terminal-state flags before emitting, so a call racing or "
        "following fail()/finish() mutates a settled lifecycle "
        "(resurrecting released resources, republishing closed totals); "
        "or a producer emits another frame lexically after a terminal "
        "frame in the same block. Guard the method on every "
        "terminal_attr the spec declares (or the spec's per-method "
        "`guards` subset).")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        project = get_project(files)
        for spec in specs.load_specs():
            if spec.errors:
                continue
            for am in extraction.api_model(spec, project):
                if not am.guards:
                    continue
                for fn in am.fns:
                    missing = am.missing_guards(fn)
                    if missing:
                        yield _fn_finding(
                            self, fn,
                            f"{fn_label(fn)} emits "
                            f"{am.event or 'machine events'} for protocol "
                            f"{spec.name!r} without checking terminal "
                            f"flag(s) {', '.join(missing)} — a call "
                            "after the machine settled would emit past "
                            "a terminal state")
            model = extraction.wire_model(spec, project)
            if model is None:
                continue
            terminal_frames = {
                f for f, body in (spec.wire.get("frames") or {}).items()
                if (body or {}).get("terminal")}
            all_sites = [s for sites in model.sites.values()
                         for s in sites]
            for site in all_sites:
                if site.frame not in terminal_frames or site.exits_after:
                    continue
                for other in all_sites:
                    if (other.block is site.block
                            and other.index > site.index
                            and not self._exits_between(site, other)):
                        yield _site_finding(
                            self, other,
                            f"frame {other.frame!r} emitted after the "
                            f"terminal {site.frame!r} frame in the same "
                            f"block (protocol {spec.name!r}): the "
                            "stream already ended")

    @staticmethod
    def _exits_between(first: EmitSite, second: EmitSite) -> bool:
        return any(isinstance(stmt, (ast.Return, ast.Raise, ast.Break))
                   for stmt in first.block[first.index + 1:second.index])


class NoFailurePathToTerminal(ProjectRule):
    id = "DS301"
    name = "no-failure-path-to-terminal"
    description = (
        "A non-terminal, non-idle spec state has no failure or "
        "cancellation transition whose path reaches a terminal state: "
        "an error or cancel arriving there strands the instance (and "
        "whatever it holds — pages, slots, probe tokens) forever. Add "
        "the failure arm to the machine and the code, or mark the state "
        "`idle` when nothing is in flight.")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for spec in specs.load_specs():
            if spec.errors or not spec.terminal_states:
                continue
            failure = spec.failure_events
            if not failure:
                continue  # machine declares no failure class (cyclic)
            reach = spec.reaches_terminal()
            for state in spec.states:
                if spec.is_terminal(state) or spec.is_idle(state):
                    continue
                ok = any(event in failure and dst in reach
                         for event, dst in spec.transitions(state).items())
                if not ok:
                    yield _spec_finding(
                        self, spec,
                        f"protocol {spec.name!r}: state {state!r} cannot "
                        "reach a terminal state on any failure/"
                        "cancellation event")


class CancellationUnhandled(ProjectRule):
    id = "DS401"
    name = "cancellation-unhandled-in-state"
    description = (
        "A cancellation event is not accepted in some non-terminal, "
        "non-idle state of the machine: a cancel arriving in that state "
        "has no transition, which is exactly where cancelled work leaks "
        "(the stranded-shutdown bug class). Accept the event in the "
        "state or list the state in the event's `ignores` with a "
        "reviewed reason.")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for spec in specs.load_specs():
            if spec.errors:
                continue
            for event in sorted(spec.cancellation_events):
                ignores = set((spec.events.get(event) or {})
                              .get("ignores", []) or [])
                for state in spec.states:
                    if (spec.is_terminal(state) or spec.is_idle(state)
                            or state in ignores):
                        continue
                    if event not in spec.transitions(state):
                        yield _spec_finding(
                            self, spec,
                            f"protocol {spec.name!r}: cancellation event "
                            f"{event!r} is unhandled in state {state!r}")


class TerminalFrameNotOnce(ProjectRule):
    id = "DS501"
    name = "terminal-frame-not-exactly-once"
    description = (
        "A terminal frame is emitted inside a loop without an immediate "
        "exit (return/raise/break as the next statement), so one "
        "instance's stream can terminate more than once; or a terminal "
        "machine event has no emitting api method left in the tree. "
        "Terminal frames settle the peer's state machine — exactly-once "
        "is the contract every consumer leans on.")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        project = get_project(files)
        for spec in specs.load_specs():
            if spec.errors:
                continue
            model = extraction.wire_model(spec, project)
            if model is not None:
                terminal_frames = {
                    f for f, body in (spec.wire.get("frames") or {}).items()
                    if (body or {}).get("terminal")}
                for frame in sorted(terminal_frames):
                    for site in model.sites.get(frame, []):
                        if site.in_loop and not site.exits_after:
                            yield _site_finding(
                                self, site,
                                f"terminal frame {frame!r} of protocol "
                                f"{spec.name!r} emitted inside a loop "
                                "without an immediate exit — the stream "
                                "could terminate twice")
            # api side: every terminal event bound to a method must
            # still have a matching method in the tree.
            bound = {}
            for am in extraction.api_model(spec, project):
                if am.event is not None:
                    bound.setdefault(am.event, []).extend(am.fns)
            for event in sorted(spec.terminal_events & set(bound)):
                if not bound[event]:
                    yield _spec_finding(
                        self, spec,
                        f"protocol {spec.name!r}: terminal event "
                        f"{event!r} is bound to an api method that no "
                        "longer exists in the tree")
