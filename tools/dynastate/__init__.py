"""dynastate — protocol state-machine analysis for dynamo_tpu.

Usage::

    python -m tools.dynastate dynamo_tpu/ [--format json]
    python -m tools.dynastate --registry-update  # bless a protocol change
    python -m tools.dynastate --list-rules
    python -m tools.dynastate --spec-dir tests/fixtures/... fixture.py

The fifth analyzer on the shared dynalint/dynaflow/dynajit/dynarace
driver (collector, per-line suppressions, JSON output, CI gate): the
repo's multi-hop frame protocols and lifecycles — streaming KV
transfer, drain departure ladder, migration/replay, preemption,
coldstart ladder, striped weight pull, journal frames, flight-recorder
phase order, breaker — are hand-authored as machine-readable state
machines (tools/dynastate/protocols/*.json), and every emission and
dispatch site is extracted over dynaflow's call graph and checked
against them. Rule families: spec validity (DS100), unhandled tags
(DS101), registry drift (DS102), post-terminal emission (DS2xx),
failure reachability (DS3xx), cancellation coverage (DS4xx),
terminal exactly-once (DS5xx). The SAME spec files drive the runtime
ProtocolMonitor (dynamo_tpu/runtime/conformance.py, DYNT_CONFORMANCE)
that the chaos scenarios assert zero violations against. Suppress on
the flagged line with ``# dynastate: disable=DS201 -- justification``
citing the spec file and the invariant that makes the site safe. See
docs/static-analysis.md for the catalogue and the spec authoring
workflow.
"""

from __future__ import annotations

from tools.dynalint.core import (  # noqa: F401
    Finding,
    ProjectRule,
    Registry,
    Rule,
    collect_files,
    main_for,
    render_json,
    render_text,
)
from tools.dynalint.core import run as _run

DYNASTATE = Registry("dynastate", "DS000")

from . import passes_state, registry  # noqa: E402
from .extraction import protocol_surface  # noqa: E402,F401
from .registry import (  # noqa: E402,F401
    diff_registry,
    registry_path,
    update_registry,
)
from .specs import (  # noqa: E402,F401
    SPEC_DIR,
    ProtocolSpec,
    active_spec_dir,
    load_specs,
    set_spec_dir,
)

for _cls in (
    passes_state.SpecValidity,
    passes_state.UnhandledTag,
    registry.ProtocolRegistryDrift,
    passes_state.PostTerminalEmission,
    passes_state.NoFailurePathToTerminal,
    passes_state.CancellationUnhandled,
    passes_state.TerminalFrameNotOnce,
):
    DYNASTATE.register(_cls)

__all__ = ["DYNASTATE", "run", "all_rules", "main", "ProtocolSpec",
           "load_specs", "set_spec_dir", "active_spec_dir", "SPEC_DIR",
           "protocol_surface", "update_registry", "diff_registry",
           "registry_path"]


def all_rules():
    return DYNASTATE.all_rules()


def run(paths, rules=None):
    """Analyze `paths`; returns (findings after suppression, files)."""
    return _run(paths, rules=rules, registry=DYNASTATE)


def main(argv=None) -> int:
    def extra_args(parser):
        parser.add_argument(
            "--spec-dir", default=None,
            help="load protocol specs from this directory instead of "
                 "tools/dynastate/protocols/ (fixture trees ship their "
                 "own spec dirs; the registry snapshot is looked up "
                 "beside the specs)")
        parser.add_argument(
            "--registry-update", action="store_true",
            help="regenerate the protocol registry snapshot beside the "
                 "active spec dir from the tree (the one-command path "
                 "after a deliberate protocol change) and exit")
        parser.add_argument(
            "--protocols", action="store_true",
            help="print the loaded protocol machines and exit "
                 "(debugging aid)")

    def handle_extra(args):
        set_spec_dir(args.spec_dir)
        if args.protocols:
            for spec in load_specs():
                status = "INVALID" if spec.errors else "ok"
                terminals = ",".join(sorted(spec.terminal_states)) or "-"
                print(f"{spec.name} [{status}] states="
                      f"{len(spec.states)} events={len(spec.events)} "
                      f"terminal={terminals}")
                for err in spec.errors:
                    print(f"  error: {err}")
            return 0
        if not args.registry_update:
            return None
        files, errors = collect_files(args.paths or ["dynamo_tpu"])
        for err in errors:
            print(f"{err.path}:{err.line}: {err.message}")
        if update_registry(files):
            print(f"updated protocol registry: {registry_path()}")
        else:
            print("protocol registry already current")
        return 1 if errors else 0

    return main_for(
        DYNASTATE, ["dynamo_tpu"],
        "protocol state-machine analysis (hand-authored lifecycle specs, "
        "emission/dispatch extraction, terminal-state and cancellation "
        "obligations, registry drift gate) for the dynamo_tpu codebase",
        argv, extra_args=extra_args, handle_extra=handle_extra)
