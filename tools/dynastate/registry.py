"""Protocol-registry snapshot: the tree's spec'd lifecycle surface.

Every machine dynastate checks — states, events, emission sites,
consumer dispatch verdicts, api guard verdicts — snapshots into
``tools/dynastate/protocols/protocol_registry.json``. Like dynaflow's
wire schemas, dynajit's jit surface, and dynarace's channel registry,
the protocol surface must change *deliberately*: DS102 fails with a
diff whenever the extracted surface drifts from the snapshot. Bless a
reviewed change with ``python -m tools.dynastate --registry-update``
and commit the regenerated file.
"""

from __future__ import annotations

import json
import pathlib
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, SourceFile

from . import specs as specs_mod
from .extraction import protocol_surface

REGISTRY_NAME = specs_mod.REGISTRY_NAME


def registry_path() -> pathlib.Path:
    """The snapshot lives beside the specs it summarizes, so fixture
    spec dirs carry their own registries."""
    return specs_mod.active_spec_dir() / REGISTRY_NAME


def _surface(files: list[SourceFile]) -> dict:
    return protocol_surface(specs_mod.load_specs(), files)


def update_registry(files: list[SourceFile],
                    path: Optional[pathlib.Path] = None) -> bool:
    """Regenerate the checked-in protocol registry; True if changed."""
    path = registry_path() if path is None else path
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = json.dumps(_surface(files), indent=2, sort_keys=True) + "\n"
    if path.exists() and path.read_text() == payload:
        return False
    path.write_text(payload)
    return True


def diff_registry(files: list[SourceFile],
                  path: Optional[pathlib.Path] = None,
                  ) -> Optional[list[str]]:
    """None when the tree matches the snapshot; otherwise human-readable
    drift lines."""
    path = registry_path() if path is None else path
    if not path.exists():
        return [f"no protocol registry at {path}; run `python -m "
                "tools.dynastate --registry-update` and commit the result"]
    try:
        want = json.loads(path.read_text())
    except ValueError as exc:
        return [f"protocol registry at {path} is unreadable ({exc}); "
                "run `python -m tools.dynastate --registry-update`"]
    got = _surface(files)
    if got == want:
        return None

    def by_protocol(payload: dict) -> dict[str, str]:
        return {e.get("protocol", "?"): json.dumps(e, sort_keys=True)
                for e in payload.get("protocols", [])}

    want_p, got_p = by_protocol(want), by_protocol(got)
    lines = []
    for name in sorted(set(got_p) - set(want_p)):
        lines.append(f"added protocol: {name}")
    for name in sorted(set(want_p) - set(got_p)):
        lines.append(f"removed protocol: {name}")
    for name in sorted(set(want_p) & set(got_p)):
        if want_p[name] == got_p[name]:
            continue
        w, g = json.loads(want_p[name]), json.loads(got_p[name])
        for section in ("machine", "emits", "handles", "api"):
            if w.get(section) != g.get(section):
                lines.append(f"changed: {name}.{section}")
    return lines or ["protocol registry drifted (regenerate)"]


class ProtocolRegistryDrift(ProjectRule):
    id = "DS102"
    name = "protocol-registry-drift"
    description = (
        "The extracted protocol surface — state machines, emission "
        "sites, consumer dispatch verdicts, api guard verdicts — no "
        "longer matches the checked-in snapshot "
        "(tools/dynastate/protocols/protocol_registry.json). Protocol "
        "changes must be deliberate: review the diff, then bless it "
        "with `python -m tools.dynastate --registry-update` and commit "
        "the regenerated registry.")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        lines = diff_registry(files)
        if lines is None:
            return
        path = registry_path().as_posix()
        for line in lines:
            yield Finding(self.id, self.name, path, 1, 0,
                          f"protocol surface drifted from snapshot: "
                          f"{line} (bless with `python -m tools.dynastate "
                          "--registry-update`)")
