"""Emission/dispatch-site extraction for dynastate.

Binds protocol-spec events to concrete code sites over dynaflow's
parsed project view (tools/dynaflow/graph.py):

* **wire frames** — a spec's ``wire`` section names producer and
  consumer functions plus, per frame kind, *emit matchers* (a dict
  literal carrying the frame's marker keys, or a constructor call with
  pinned keywords) and *read matchers* (a key or attribute the
  dispatching consumer must load). Emit sites keep their statement
  context (enclosing block, loop depth, whether the next statement
  exits) so the exactly-once rules can reason about ordering.

* **api methods** — a spec's ``api`` section names a class whose
  methods drive the machine, with the attributes that flag terminal
  states (``terminal_attrs``). A method must *read* every terminal
  flag it is guarded by (default: all of them) before emitting — the
  static form of "no transitions out of a terminal state".

Spec extraction grammar::

    "wire": {
      "producers": [{"module": "<rel-suffix>", "fn": "name|Class.name"}],
      "consumers": [{"module": ..., "fn": ...}],
      "frames": {
        "<frame>": {
          "event": "<machine event>",        # optional binding
          "terminal": true,                  # stream ends at this frame
          "emit": [{"keys": ["error"]} |
                   {"call": "EngineOutput",
                    "kw_equals": {"finish_reason": "migrate"}}],
          "read": [{"key": "error"} | {"attr": "finish_reason"} |
                   {"ref": "JOURNAL_RESYNC_TOPIC"}],
          "producers": ["name", ...],        # optional subset (by fn)
          "consumers": ["name", ...]         # optional subset (by fn)
        }
      }
    }
    "api": [{
      "module": ..., "class": "StreamingTransfer",
      "terminal_attrs": ["done", "failed"],
      "methods": {"finish": {"event": "finish",
                              "guards": ["failed"]}}   # optional override
    }]
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from tools.dynaflow.graph import (
    FunctionInfo,
    Project,
    call_tail,
    const_key,
    get_project,
)

from .specs import ProtocolSpec


def _anchor(rel: str) -> str:
    """Anchor paths at the package root so the registry agrees whether
    the tree was collected relatively or absolutely (the channel-
    registry contract)."""
    idx = rel.find("dynamo_tpu/")
    return rel[idx:] if idx >= 0 else rel


def fn_label(fn: FunctionInfo) -> str:
    name = f"{fn.cls}.{fn.name}" if fn.cls else fn.name
    return f"{_anchor(fn.rel)}::{name}"


def resolve_fns(project: Project, module: str, fn: str
                ) -> list[FunctionInfo]:
    """Functions matching a spec target: `module` is a path suffix,
    `fn` a bare name or Class.name."""
    cls, _, name = fn.rpartition(".")
    out = []
    for cand in project.by_name.get(name or fn, ()):
        if not cand.rel.endswith(module):
            continue
        if cls and cand.cls != cls:
            continue
        if not cls and fn != cand.name:
            continue
        out.append(cand)
    return out


# -- emit-site scanning ------------------------------------------------------


@dataclasses.dataclass
class EmitSite:
    frame: str
    fn: FunctionInfo
    node: ast.AST      # the matched expression
    stmt: ast.stmt     # enclosing statement in its block
    block: list        # the statement list containing stmt
    index: int         # stmt's index in block
    in_loop: bool

    @property
    def exits_after(self) -> bool:
        """The frame cannot be emitted again on this path: the site is
        a return value, or the next statement in its block exits."""
        if isinstance(self.stmt, (ast.Return, ast.Raise)):
            return True
        if self.index + 1 < len(self.block):
            return isinstance(self.block[self.index + 1],
                              (ast.Return, ast.Raise, ast.Break))
        return False


def _sub_blocks(stmt: ast.stmt) -> Iterable[tuple[list, bool]]:
    """(statement-list, enters_loop) pairs nested directly under `stmt`
    — nested function/class scopes excluded (their bodies are their own
    FunctionInfos)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    loop = isinstance(stmt, (ast.For, ast.AsyncFor, ast.While))
    for field in ("body", "orelse", "finalbody"):
        block = getattr(stmt, field, None)
        if block:
            yield block, loop and field == "body"
    for handler in getattr(stmt, "handlers", ()) or ():
        yield handler.body, False
    for case in getattr(stmt, "cases", ()) or ():
        yield case.body, False


def _stmt_exprs(stmt: ast.stmt) -> Iterable[ast.AST]:
    """Every expression node attached to `stmt` itself (not to nested
    statement blocks or nested scopes)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers", "cases"):
            continue
        values = value if isinstance(value, list) else [value]
        stack = [v for v in values if isinstance(v, ast.expr)]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue  # a deferred scope, not this statement's
            yield node
            stack.extend(ast.iter_child_nodes(node))


def _match_emit(node: ast.AST, matcher: dict) -> bool:
    keys = matcher.get("keys")
    if keys is not None:
        if not isinstance(node, ast.Dict):
            return False
        have = {const_key(k) for k in node.keys if k is not None}
        return all(k in have for k in keys)
    call = matcher.get("call")
    if call is not None:
        if not isinstance(node, ast.Call) or call_tail(node) != call:
            return False
        wanted = matcher.get("kw_equals") or {}
        if wanted:
            got = {kw.arg: kw.value.value for kw in node.keywords
                   if kw.arg is not None
                   and isinstance(kw.value, ast.Constant)}
            return all(got.get(k) == v for k, v in wanted.items())
        return True
    return False


def emit_sites(fn: FunctionInfo,
               frame_matchers: dict[str, list[dict]]) -> list[EmitSite]:
    """All frame-emission sites inside `fn` (nested defs excluded),
    with block/loop context."""
    sites: list[EmitSite] = []
    body = getattr(fn.node, "body", None) or []

    def scan(block: list, in_loop: bool) -> None:
        for i, stmt in enumerate(block):
            for node in _stmt_exprs(stmt):
                for frame, matchers in frame_matchers.items():
                    if any(_match_emit(node, m) for m in matchers):
                        sites.append(EmitSite(frame, fn, node, stmt,
                                              block, i, in_loop))
            for sub, enters_loop in _sub_blocks(stmt):
                scan(sub, in_loop or enters_loop)

    scan(body, False)
    return sites


def _match_read(fn: FunctionInfo, matcher: dict) -> bool:
    key = matcher.get("key")
    if key is not None:
        return key in fn.key_reads
    attr = matcher.get("attr")
    if attr is not None:
        return attr in fn.attr_reads
    ref = matcher.get("ref")
    if ref is not None:
        # Dispatch by named constant (e.g. topic.startswith(RESYNC_TOPIC))
        return ref in fn.refs
    return False


# -- per-spec models ---------------------------------------------------------


@dataclasses.dataclass
class WireModel:
    spec: ProtocolSpec
    producers: dict[str, list[FunctionInfo]]  # fn token -> matches
    consumers: dict[str, list[FunctionInfo]]
    sites: dict[str, list[EmitSite]]          # frame -> emit sites

    def frame_producers(self, frame: str) -> dict[str, list[FunctionInfo]]:
        subset = (self.spec.wire["frames"].get(frame) or {}).get("producers")
        if subset is None:
            return self.producers
        return {k: v for k, v in self.producers.items() if k in subset}

    def frame_consumers(self, frame: str) -> dict[str, list[FunctionInfo]]:
        subset = (self.spec.wire["frames"].get(frame) or {}).get("consumers")
        if subset is None:
            return self.consumers
        return {k: v for k, v in self.consumers.items() if k in subset}


def wire_model(spec: ProtocolSpec, project: Project) -> Optional[WireModel]:
    wire = spec.wire
    if not wire:
        return None
    producers: dict[str, list[FunctionInfo]] = {}
    for entry in wire.get("producers", []) or []:
        producers[entry["fn"]] = resolve_fns(project, entry.get("module", ""),
                                             entry["fn"])
    consumers: dict[str, list[FunctionInfo]] = {}
    for entry in wire.get("consumers", []) or []:
        consumers[entry["fn"]] = resolve_fns(project, entry.get("module", ""),
                                             entry["fn"])
    frames = wire.get("frames", {}) or {}
    sites: dict[str, list[EmitSite]] = {f: [] for f in frames}
    for token, fns in producers.items():
        for fn in fns:
            matchers = {
                f: (body or {}).get("emit", []) or []
                for f, body in frames.items()
                if (body or {}).get("producers") is None
                or token in (body or {}).get("producers")
            }
            for site in emit_sites(fn, matchers):
                sites[site.frame].append(site)
    return WireModel(spec, producers, consumers, sites)


@dataclasses.dataclass
class ApiMethod:
    entry: dict
    method: str
    event: Optional[str]
    guards: list[str]
    fns: list[FunctionInfo]

    @property
    def terminal(self) -> bool:
        return bool((self.entry.get("methods") or {})
                    .get(self.method, {}).get("terminal"))

    def missing_guards(self, fn: FunctionInfo) -> list[str]:
        return [g for g in self.guards if g not in fn.attr_reads]


def api_model(spec: ProtocolSpec, project: Project) -> list[ApiMethod]:
    out: list[ApiMethod] = []
    for entry in spec.api:
        module = entry.get("module", "")
        cls = entry.get("class", "")
        terminal_attrs = entry.get("terminal_attrs", []) or []
        for method, body in (entry.get("methods") or {}).items():
            body = body or {}
            fns = resolve_fns(project, module,
                              f"{cls}.{method}" if cls else method)
            out.append(ApiMethod(
                entry, method, body.get("event"),
                list(body.get("guards", terminal_attrs)), fns))
    return out


# -- registry surface (DS102) ------------------------------------------------


def protocol_surface(specs: list[ProtocolSpec], files: list) -> dict:
    """The extracted protocol surface: each spec's machine plus every
    emission site (aggregated per function, no line numbers — moving
    code must not churn the snapshot), consumer dispatch verdicts, and
    api guard verdicts. Snapshot target of the DS102 drift gate."""
    project = get_project(files)
    entries = []
    for spec in sorted(specs, key=lambda s: s.name):
        machine = {
            "initial": spec.initial,
            "states": {
                s: {"terminal": spec.is_terminal(s),
                    "idle": spec.is_idle(s),
                    "on": dict(sorted(spec.transitions(s).items()))}
                for s in sorted(spec.states)
            },
            "events": {
                e: {k: v for k, v in sorted((spec.events[e] or {}).items())}
                for e in sorted(spec.events)
            },
        }
        emits: dict[tuple[str, str], int] = {}
        handles = []
        model = wire_model(spec, project)
        if model is not None:
            for frame, sites in sorted(model.sites.items()):
                for site in sites:
                    key = (fn_label(site.fn), frame)
                    emits[key] = emits.get(key, 0) + 1
            for frame, body in sorted((spec.wire.get("frames") or {}
                                       ).items()):
                reads = (body or {}).get("read", []) or []
                for token, fns in sorted(
                        model.frame_consumers(frame).items()):
                    for fn in fns:
                        handles.append({
                            "consumer": fn_label(fn), "frame": frame,
                            "dispatches": any(_match_read(fn, m)
                                              for m in reads)})
        api = []
        for am in api_model(spec, project):
            for fn in am.fns:
                api.append({
                    "scope": fn_label(fn), "event": am.event,
                    "guards": sorted(am.guards),
                    "guarded": not am.missing_guards(fn)})
        entries.append({
            "protocol": spec.name,
            "machine": machine,
            "emits": [{"site": site, "frame": frame, "count": count}
                      for (site, frame), count in sorted(emits.items())],
            "handles": sorted(handles,
                              key=lambda h: (h["consumer"], h["frame"])),
            "api": sorted(api, key=lambda a: (a["scope"],
                                              a["event"] or ""))})
    return {"version": 1, "protocols": entries}
