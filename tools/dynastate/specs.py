"""Protocol spec loading for dynastate.

A *spec* is one hand-authored JSON state machine under
``tools/dynastate/protocols/*.json`` describing a frame/lifecycle
protocol the tree implements: named states with event-keyed
transitions, terminal states, failure/cancellation event classes, and
an *extraction* section binding machine events to concrete emission and
dispatch sites in the code (see docs/static-analysis.md §dynastate for
the authoring workflow). The same files drive the static rules (DS1xx-
DS5xx) and the runtime ProtocolMonitor (dynamo_tpu/runtime/
conformance.py), so the machine checked in CI is the machine enforced
in chaos runs.

Spec shape::

    {
      "version": 1,
      "protocol": "kv_stream_transfer",
      "doc": "...",
      "initial": "streaming",
      "states": {
        "streaming": {"on": {"append": "streaming", "fail": "failed"}},
        "failed":    {"terminal": true}
      },
      "events": {
        "append": {},
        "fail": {"terminal": true, "failure": true, "cancellation": true,
                  "ignores": ["some_state"]}
      },
      "wire": {...},   # frame extraction (see extraction.py)
      "api":  [...]    # object-API extraction (see extraction.py)
    }

States may set ``"idle": true``: a quiescent state with nothing in
flight, exempt from the DS301/DS401 must-reach-terminal obligations.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Optional

SPEC_DIR = pathlib.Path(__file__).resolve().parent / "protocols"
REGISTRY_NAME = "protocol_registry.json"

# Overridable for fixture trees (tests ship their own tiny spec dirs);
# the CLI's --spec-dir flag lands here too.
_active_dir: pathlib.Path = SPEC_DIR


def set_spec_dir(path: Optional[str | pathlib.Path]) -> None:
    global _active_dir
    _active_dir = SPEC_DIR if path is None else pathlib.Path(path)


def active_spec_dir() -> pathlib.Path:
    return _active_dir


@dataclasses.dataclass
class ProtocolSpec:
    name: str
    path: str  # posix path of the spec file (finding anchor)
    raw: dict
    errors: list[str]  # structural problems (DS100's business)

    # -- machine queries ---------------------------------------------------

    @property
    def states(self) -> dict:
        return self.raw.get("states", {}) or {}

    @property
    def events(self) -> dict:
        return self.raw.get("events", {}) or {}

    @property
    def initial(self) -> Optional[str]:
        return self.raw.get("initial")

    def transitions(self, state: str) -> dict:
        return (self.states.get(state) or {}).get("on", {}) or {}

    def is_terminal(self, state: str) -> bool:
        return bool((self.states.get(state) or {}).get("terminal"))

    def is_idle(self, state: str) -> bool:
        return bool((self.states.get(state) or {}).get("idle"))

    @property
    def terminal_states(self) -> set[str]:
        return {s for s in self.states if self.is_terminal(s)}

    def event_flag(self, event: str, flag: str) -> bool:
        return bool((self.events.get(event) or {}).get(flag))

    @property
    def failure_events(self) -> set[str]:
        return {e for e in self.events
                if self.event_flag(e, "failure")
                or self.event_flag(e, "cancellation")}

    @property
    def cancellation_events(self) -> set[str]:
        return {e for e in self.events
                if self.event_flag(e, "cancellation")}

    @property
    def terminal_events(self) -> set[str]:
        return {e for e in self.events if self.event_flag(e, "terminal")}

    def reaches_terminal(self) -> set[str]:
        """States from which SOME transition path ends in a terminal
        state (terminal states included)."""
        reach = set(self.terminal_states)
        changed = True
        while changed:
            changed = False
            for state in self.states:
                if state in reach:
                    continue
                if any(dst in reach
                       for dst in self.transitions(state).values()):
                    reach.add(state)
                    changed = True
        return reach

    # -- extraction sections -----------------------------------------------

    @property
    def wire(self) -> Optional[dict]:
        return self.raw.get("wire")

    @property
    def api(self) -> list[dict]:
        return self.raw.get("api", []) or []


def _validate(spec: ProtocolSpec) -> None:
    raw, errs = spec.raw, spec.errors
    if not isinstance(raw.get("protocol"), str) or not raw.get("protocol"):
        errs.append("missing 'protocol' name")
    states = raw.get("states")
    if not isinstance(states, dict) or not states:
        errs.append("missing or empty 'states'")
        return
    initial = raw.get("initial")
    if initial not in states:
        errs.append(f"initial state {initial!r} is not a declared state")
    events = raw.get("events") or {}
    for state, body in states.items():
        if not isinstance(body, dict):
            errs.append(f"state {state!r} body must be an object")
            continue
        for event, dst in (body.get("on") or {}).items():
            if event not in events:
                errs.append(f"state {state!r} transitions on undeclared "
                            f"event {event!r}")
            if dst not in states:
                errs.append(f"state {state!r} transitions to undeclared "
                            f"state {dst!r} on {event!r}")
        if body.get("terminal") and (body.get("on") or {}):
            errs.append(f"terminal state {state!r} declares outgoing "
                        "transitions")
    for event, body in events.items():
        for ignored in (body or {}).get("ignores", []) or []:
            if ignored not in states:
                errs.append(f"event {event!r} ignores undeclared state "
                            f"{ignored!r}")
    wire = raw.get("wire")
    if wire is not None:
        for frame, body in (wire.get("frames") or {}).items():
            ev = (body or {}).get("event")
            if ev is not None and ev not in events:
                errs.append(f"frame {frame!r} maps to undeclared event "
                            f"{ev!r}")
    for entry in raw.get("api", []) or []:
        for method, body in (entry.get("methods") or {}).items():
            ev = (body or {}).get("event")
            if ev is not None and ev not in events:
                errs.append(f"api method {method!r} maps to undeclared "
                            f"event {ev!r}")


def load_specs(spec_dir: Optional[pathlib.Path] = None
               ) -> list[ProtocolSpec]:
    """Parse every spec in the active dir (registry snapshot excluded).
    Unreadable files come back as specs whose `errors` carry the parse
    failure so DS100 can report instead of the run crashing."""
    root = spec_dir if spec_dir is not None else _active_dir
    specs: list[ProtocolSpec] = []
    if not root.is_dir():
        return specs
    for path in sorted(root.glob("*.json")):
        if path.name == REGISTRY_NAME:
            continue
        rel = path.as_posix()
        try:
            raw = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError) as exc:
            specs.append(ProtocolSpec(path.stem, rel, {},
                                      [f"cannot parse: {exc}"]))
            continue
        if not isinstance(raw, dict):
            specs.append(ProtocolSpec(path.stem, rel, {},
                                      ["top level must be an object"]))
            continue
        spec = ProtocolSpec(raw.get("protocol") or path.stem, rel, raw, [])
        _validate(spec)
        specs.append(spec)
    return specs
