"""Pass 1 — protocol conformance across the wire planes.

The request plane, event plane, KVBM step channel, and LLM token
protocol are dict-shaped msgpack messages hand-built at send sites and
pattern-matched at consumers; nothing but convention keeps the two
sides agreeing (the reference gets this from serde derives). This pass
extracts, per plane:

  * the literal key-set written at every send site (dicts passed to the
    plane's send functions, plus dicts returned from `to_wire`),
  * the key-set read at every consumer (`msg["k"]`, `.get("k")`,
    `"k" in msg` on the plane's receiver variables),
  * the type-tag values produced and the dispatch arms consuming them
    (`ftype == "req"` / `ftype in (...)` on a variable bound from the
    tag key).

Keys written but never read are dead payload (or a consumer that
silently ignores data); keys read but never written are a handler that
can never fire; a produced tag with no dispatch arm is a message the
peer drops on the floor. A checked-in schema snapshot per plane
(`tools/dynaflow/schemas/<plane>.json`) turns any drift into a CI diff:
evolve a wire format deliberately with
`python -m tools.dynaflow --schema-update`.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, SourceFile

from .graph import call_tail, const_key

SCHEMA_DIR = pathlib.Path(__file__).parent / "schemas"


@dataclasses.dataclass(frozen=True)
class Plane:
    name: str
    # rel-path suffixes of the files making up the plane
    suffixes: tuple[str, ...]
    # call-name tails that transmit a wire dict
    send_fns: tuple[str, ...]
    # variable/attribute names that hold a received wire dict
    receivers: tuple[str, ...]
    # header key carrying the message type tag, if the plane has one
    tag_key: Optional[str] = None
    # functions whose dict literals ARE wire messages (serializers):
    # every dict built inside them counts as a send site
    codec_fns: tuple[str, ...] = ("to_wire",)


DEFAULT_PLANES = (
    Plane("request_plane",
          # resilience.py is part of the plane: Deadline.to_wire/from_wire
          # own the x-dynt-deadline-ms header fragment every hop forwards;
          # otel.py owns the traceparent header the same way.
          ("runtime/request_plane.py", "runtime/codec.py",
           "runtime/resilience.py", "runtime/otel.py"),
          ("write_frame", "encode_frame", "_send", "send", "_http_frame",
           "put_nowait"),
          ("header", "frame"),
          tag_key="t",
          codec_fns=("to_wire", "traceparent_wire")),
    Plane("event_plane",
          ("runtime/events.py", "kv_router/protocols.py"),
          ("packb", "put", "_put_leased", "publish"),
          ("frame", "data", "value")),
    Plane("kvbm_distributed",
          ("parallel/multihost.py", "block_manager/distributed.py"),
          ("_send_frame", "publish"),
          ("msg", "obj"),
          codec_fns=("to_wire", "_enc")),
    Plane("llm_protocol",
          ("llm/protocols.py",),
          (),
          ("data",)),
)


@dataclasses.dataclass
class PlaneSchema:
    """Extracted wire shape of one plane."""

    writes: dict[str, set[str]] = dataclasses.field(default_factory=dict)
    reads: set[str] = dataclasses.field(default_factory=set)
    dispatch: set[str] = dataclasses.field(default_factory=set)
    # first write site per key / per tag, for finding locations
    key_sites: dict[str, tuple[SourceFile, ast.AST]] = \
        dataclasses.field(default_factory=dict)
    tag_sites: dict[str, tuple[SourceFile, ast.AST]] = \
        dataclasses.field(default_factory=dict)
    matched_files: int = 0

    def written_keys(self) -> set[str]:
        out: set[str] = set()
        for keys in self.writes.values():
            out |= keys
        return out

    def to_json(self) -> dict:
        return {
            "writes": {tag: sorted(keys)
                       for tag, keys in sorted(self.writes.items())},
            "reads": sorted(self.reads),
            "dispatch": sorted(self.dispatch),
        }


def _receiver_rooted(node: ast.expr, receivers: tuple[str, ...]) -> bool:
    """True if the expression chain is rooted at a receiver variable:
    msg[...], msg.get(...), data["s"]["b"], event.value.get(...)."""
    cur = node
    while True:
        if isinstance(cur, ast.Subscript):
            cur = cur.value
        elif isinstance(cur, ast.Call) and isinstance(cur.func,
                                                      ast.Attribute):
            cur = cur.func.value
        elif isinstance(cur, ast.Attribute):
            return cur.attr in receivers
        elif isinstance(cur, ast.Name):
            return cur.id in receivers
        else:
            return False


def _dict_literal_keys(node: ast.Dict) -> tuple[set[str], dict[str, ast.expr]]:
    """Constant string keys of a dict literal (and nested dict-literal
    values, flattened) plus the value expr per top-level key."""
    keys: set[str] = set()
    values: dict[str, ast.expr] = {}
    for key_node, val in zip(node.keys, node.values):
        key = const_key(key_node) if key_node is not None else None
        if key is None:
            continue
        keys.add(key)
        values[key] = val
        if isinstance(val, ast.Dict):
            sub, _ = _dict_literal_keys(val)
            keys |= sub
    return keys, values


def extract_plane(plane: Plane, files: list[SourceFile]) -> PlaneSchema:
    schema = PlaneSchema()
    for src in files:
        if not src.rel.endswith(plane.suffixes):
            continue
        schema.matched_files += 1
        _extract_writes(plane, src, schema)
        _extract_reads(plane, src, schema)
    return schema


def _record_wire_dict(plane: Plane, src: SourceFile, node: ast.Dict,
                      schema: PlaneSchema) -> None:
    keys, values = _dict_literal_keys(node)
    if not keys:
        return
    tag = "*"
    if plane.tag_key is not None and plane.tag_key in values:
        const = const_key(values[plane.tag_key])
        if const is not None:
            tag = const
            if const not in schema.tag_sites:
                schema.tag_sites[const] = (src, node)
    schema.writes.setdefault(tag, set()).update(keys)
    for key in keys:
        schema.key_sites.setdefault(key, (src, node))


def _extract_writes(plane: Plane, src: SourceFile,
                    schema: PlaneSchema) -> None:
    # dict literals bound to a local that is later passed to a send fn
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        local_dicts: dict[str, ast.Dict] = {}
        sent_names: set[str] = set()
        # Inside a serializer (to_wire, a plane codec fn) every dict
        # literal IS a wire message, including ones built up via
        # `out = {...}` / `out["k"] = ...` and returned by name.
        writer = fn.name in plane.codec_fns
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.targets[0], ast.Subscript):
                if writer:  # out["k"] = ... inside a serializer
                    key = const_key(node.targets[0].slice)
                    if key is not None:
                        schema.writes.setdefault("*", set()).add(key)
                        schema.key_sites.setdefault(key, (src, node))
                        if isinstance(node.value, ast.Dict):
                            sub_keys, _ = _dict_literal_keys(node.value)
                            schema.writes["*"] |= sub_keys
                            for k in sub_keys:
                                schema.key_sites.setdefault(
                                    k, (src, node))
            elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                             ast.Dict):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local_dicts[tgt.id] = node.value
            elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.value, ast.Dict) and isinstance(node.target,
                                                         ast.Name):
                local_dicts[node.target.id] = node.value
            elif isinstance(node, ast.Call) \
                    and call_tail(node) in plane.send_fns:
                args = list(node.args) + [k.value for k in node.keywords]
                for arg in args:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Dict):
                            _record_wire_dict(plane, src, sub, schema)
                        elif isinstance(sub, ast.Name):
                            sent_names.add(sub.id)
            elif writer and isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Dict):
                _record_wire_dict(plane, src, node.value, schema)
        if writer:
            for dct in local_dicts.values():
                _record_wire_dict(plane, src, dct, schema)
        for name in sent_names:
            if name in local_dicts:
                _record_wire_dict(plane, src, local_dicts[name], schema)


def _extract_reads(plane: Plane, src: SourceFile,
                   schema: PlaneSchema) -> None:
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tag_vars: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and _receiver_rooted(node, plane.receivers):
                key = const_key(node.slice)
                if key is not None:
                    schema.reads.add(key)
            elif isinstance(node, ast.Call) and call_tail(node) == "get" \
                    and node.args and isinstance(node.func, ast.Attribute) \
                    and _receiver_rooted(node.func.value, plane.receivers):
                key = const_key(node.args[0])
                if key is not None:
                    schema.reads.add(key)
            elif isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn))
                    for op in node.ops):
                key = const_key(node.left)
                if key is not None and node.comparators and \
                        _receiver_rooted(node.comparators[0],
                                         plane.receivers):
                    schema.reads.add(key)
        if plane.tag_key is None:
            continue
        # tag dispatch: vars bound from the tag key, compared to consts
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                val = node.value
                bound = None
                if isinstance(val, ast.Subscript) \
                        and _receiver_rooted(val, plane.receivers):
                    bound = const_key(val.slice)
                elif isinstance(val, ast.Call) \
                        and call_tail(val) == "get" and val.args \
                        and isinstance(val.func, ast.Attribute) \
                        and _receiver_rooted(val.func.value,
                                             plane.receivers):
                    bound = const_key(val.args[0])
                if bound == plane.tag_key:
                    tag_vars.add(node.targets[0].id)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            left = node.left
            is_tag = (isinstance(left, ast.Name) and left.id in tag_vars) \
                or (isinstance(left, ast.Call) and call_tail(left) == "get"
                    and left.args and const_key(left.args[0])
                    == plane.tag_key
                    and isinstance(left.func, ast.Attribute)
                    and _receiver_rooted(left.func.value, plane.receivers))
            if not is_tag:
                continue
            for comp in node.comparators:
                if isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    for elt in comp.elts:
                        val = const_key(elt)
                        if val is not None:
                            schema.dispatch.add(val)
                else:
                    val = const_key(comp)
                    if val is not None:
                        schema.dispatch.add(val)


# -- findings ----------------------------------------------------------------

# One extraction shared by the four rules below (run() hands every rule
# the same `files` list object). The cache entry holds the keyed list
# itself: an id() alone could be recycled by a LATER list at the same
# address once the first is freed, silently serving a stale schema.
_CACHE: dict = {}


def plane_schemas(files: list[SourceFile], planes: tuple[Plane, ...],
                  ) -> dict[str, PlaneSchema]:
    key = (id(files), planes)
    hit = _CACHE.get(key)
    if hit is not None and hit[0] is files:
        return hit[1]
    if len(_CACHE) > 8:
        _CACHE.clear()
    schemas = {p.name: extract_plane(p, files) for p in planes}
    _CACHE[key] = (files, schemas)
    return schemas


def extract_schemas(files: list[SourceFile],
                    planes: tuple[Plane, ...] = DEFAULT_PLANES,
                    ) -> dict[str, PlaneSchema]:
    return plane_schemas(files, planes)


def update_schemas(files: list[SourceFile],
                   schema_dir: pathlib.Path = SCHEMA_DIR,
                   planes: tuple[Plane, ...] = DEFAULT_PLANES) -> list[str]:
    """Regenerate the checked-in snapshots; returns changed plane names."""
    schema_dir.mkdir(parents=True, exist_ok=True)
    changed = []
    for name, schema in extract_schemas(files, planes).items():
        path = schema_dir / f"{name}.json"
        payload = json.dumps(schema.to_json(), indent=2,
                             sort_keys=True) + "\n"
        if not path.exists() or path.read_text() != payload:
            path.write_text(payload)
            changed.append(name)
    return changed


class _PlaneRule(ProjectRule):
    """Base for the protocol rules: plane config + finding helper."""

    def __init__(self, planes: tuple[Plane, ...] = DEFAULT_PLANES) -> None:
        self.planes = planes

    def _finding(self, src: SourceFile, node: ast.AST,
                 message: str) -> Finding:
        return Finding(self.id, self.name, src.rel,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)

    @staticmethod
    def _plane_file(plane: Plane, files: list[SourceFile]) -> SourceFile:
        return next(s for s in files if s.rel.endswith(plane.suffixes))


class WireKeyNeverRead(_PlaneRule):
    id = "DF101"
    name = "wire-key-never-read"
    description = (
        "wire-dict key written at a send site but never read by any "
        "consumer on the same plane: dead payload, or the reader was "
        "lost to drift (the serde-derive mismatch Rust rejects at "
        "compile time)")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for plane in self.planes:
            schema = plane_schemas(files, self.planes)[plane.name]
            if not schema.matched_files:
                continue
            for key in sorted(schema.written_keys() - schema.reads):
                src, node = schema.key_sites[key]
                yield self._finding(
                    src, node,
                    f"[{plane.name}] wire key {key!r} is written here "
                    "but no consumer on the plane ever reads it — dead "
                    "payload, or the reader was lost to drift")


class WireKeyNeverWritten(_PlaneRule):
    id = "DF102"
    name = "wire-key-never-written"
    description = (
        "wire-dict key read by a consumer but never written at any send "
        "site on the same plane: the handler can never fire (producer "
        "renamed or dropped the key)")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for plane in self.planes:
            schema = plane_schemas(files, self.planes)[plane.name]
            if not schema.matched_files:
                continue
            for key in sorted(schema.reads - schema.written_keys()):
                src = self._plane_file(plane, files)
                yield self._finding(
                    src, src.tree,
                    f"[{plane.name}] wire key {key!r} is read by a "
                    "consumer but no send site ever writes it — the "
                    "read can never see data (producer drift?)")


class WireTagUnhandled(_PlaneRule):
    id = "DF103"
    name = "wire-tag-unhandled"
    description = (
        "message type tag produced with no consumer dispatch arm (the "
        "peer drops it on the floor), or dispatched but never produced "
        "(dead handler arm) — the match-arm exhaustiveness Rust enums "
        "give for free")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        for plane in self.planes:
            if plane.tag_key is None:
                continue
            schema = plane_schemas(files, self.planes)[plane.name]
            if not schema.matched_files:
                continue
            produced = set(schema.writes) - {"*"}
            for tag in sorted(produced - schema.dispatch):
                src, node = schema.tag_sites[tag]
                yield self._finding(
                    src, node,
                    f"[{plane.name}] message tag {plane.tag_key}="
                    f"{tag!r} is produced here but no consumer "
                    "dispatches on it — the peer drops it on the floor")
            for tag in sorted(schema.dispatch - produced):
                src = self._plane_file(plane, files)
                yield self._finding(
                    src, src.tree,
                    f"[{plane.name}] a consumer dispatches on tag "
                    f"{plane.tag_key}={tag!r} but no send site ever "
                    "produces it — dead handler arm")


class WireSchemaDrift(_PlaneRule):
    id = "DF104"
    name = "wire-schema-drift"
    description = (
        "a plane's extracted wire shape diverged from the checked-in "
        "snapshot under tools/dynaflow/schemas/ — protocol changes must "
        "be deliberate: run `python -m tools.dynaflow --schema-update` "
        "and commit the resulting diff")

    def __init__(self, planes: tuple[Plane, ...] = DEFAULT_PLANES,
                 schema_dir: Optional[pathlib.Path] = SCHEMA_DIR) -> None:
        super().__init__(planes)
        self.schema_dir = schema_dir

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        if self.schema_dir is None:
            return
        for plane in self.planes:
            schema = plane_schemas(files, self.planes)[plane.name]
            if not schema.matched_files:
                continue
            src = self._plane_file(plane, files)
            path = self.schema_dir / f"{plane.name}.json"
            if not path.exists():
                yield self._finding(
                    src, src.tree,
                    f"[{plane.name}] no schema snapshot at {path}; run "
                    "`python -m tools.dynaflow --schema-update` and "
                    "commit the result")
                continue
            want = json.loads(path.read_text())
            got = schema.to_json()
            if got == want:
                continue
            diffs = []
            for section in ("writes", "reads", "dispatch"):
                if got.get(section) != want.get(section):
                    diffs.append(
                        f"{section}: snapshot {want.get(section)!r} "
                        f"!= tree {got.get(section)!r}")
            yield self._finding(
                src, src.tree,
                f"[{plane.name}] wire format drifted from the "
                f"checked-in snapshot ({'; '.join(diffs)}); if "
                "deliberate, run `python -m tools.dynaflow "
                "--schema-update` and commit the diff")
