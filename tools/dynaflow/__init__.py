"""dynaflow — interprocedural call-graph analysis for dynamo_tpu.

Usage::

    python -m tools.dynaflow dynamo_tpu/ [--format json]
    python -m tools.dynaflow --schema-update   # bless a wire change
    python -m tools.dynaflow --list-rules

Extends the tools/dynalint driver (shared collector, suppression
semantics, JSON output, CI gate) with whole-program passes the
per-file rules cannot express: an import graph + approximate call
graph over the tree powers protocol-conformance (DF1xx), lock-hazard
(DF2xx), reachable-consumption (DF3xx), and config/metric registry
(DF4xx) checks. Suppress on the flagged line with
``# dynaflow: disable=DF201 -- justification``.
See docs/static-analysis.md for the catalogue.
"""

from __future__ import annotations

from tools.dynalint.core import (  # noqa: F401
    Finding,
    ProjectRule,
    Registry,
    Rule,
    collect_files,
    main_for,
    render_json,
    render_text,
)
from tools.dynalint.core import run as _run

DYNAFLOW = Registry("dynaflow", "DF000")

from . import (
    passes_locks,
    passes_protocol,
    passes_reach,
    passes_registry,
    passes_spans,
)
from .passes_protocol import (  # noqa: F401
    DEFAULT_PLANES,
    SCHEMA_DIR,
    Plane,
    extract_schemas,
    update_schemas,
)

for _cls in (
    passes_protocol.WireKeyNeverRead,
    passes_protocol.WireKeyNeverWritten,
    passes_protocol.WireTagUnhandled,
    passes_protocol.WireSchemaDrift,
    passes_locks.SlowCallUnderLock,
    passes_locks.LockOrderInversion,
    passes_reach.UnreachableAcceptedField,
    passes_reach.ProtocolFieldUnread,
    passes_registry.UnregisteredEnvRead,
    passes_registry.EnvDefaultTypeMismatch,
    passes_registry.DeadConfigKnob,
    passes_registry.DuplicateMetricName,
    passes_registry.UndocumentedMetric,
    passes_registry.UnboundedMetricLabel,
    passes_spans.UndocumentedSpan,
    passes_spans.DuplicateSpanName,
):
    DYNAFLOW.register(_cls)

__all__ = ["DYNAFLOW", "run", "all_rules", "main", "extract_schemas",
           "update_schemas", "Plane", "DEFAULT_PLANES", "SCHEMA_DIR"]


def all_rules():
    return DYNAFLOW.all_rules()


def run(paths, rules=None):
    """Analyze `paths`; returns (findings after suppression, files)."""
    return _run(paths, rules=rules, registry=DYNAFLOW)


def main(argv=None) -> int:
    def extra_args(parser):
        parser.add_argument(
            "--schema-update", action="store_true",
            help="regenerate tools/dynaflow/schemas/ from the tree "
                 "(the one-command path after a deliberate wire-format "
                 "change) and exit")

    def handle_extra(args):
        if not args.schema_update:
            return None
        files, errors = collect_files(args.paths or ["dynamo_tpu"])
        for err in errors:
            print(f"{err.path}:{err.line}: {err.message}")
        changed = update_schemas(files)
        if changed:
            print("updated schema snapshot(s): " + ", ".join(changed))
        else:
            print("schema snapshots already current")
        return 1 if errors else 0

    return main_for(
        DYNAFLOW, ["dynamo_tpu"],
        "interprocedural call-graph analysis for the dynamo_tpu "
        "codebase", argv, extra_args=extra_args,
        handle_extra=handle_extra)
