"""Pass 3 — reachable consumption via call-graph reachability.

PR 1's DL302 caught the min_p failure mode textually: a sampling field
accepted by validate.py but never *mentioned* outside the parse layer.
This pass generalizes it over the call graph: a mention in dead code is
not consumption. Entry points are the places work actually enters the
system — request-plane handler registrations, HTTP route handlers, and
`main` functions — and a field counts as consumed only when a function
*reachable* from an entry point reads it.

* DF301 unreachable-accepted-field: a field accepted by
  `llm/validate.py` (_COMMON_FIELDS) and carried by SamplingOptions /
  StopConditions whose only reads outside the accept/parse layer sit in
  unreachable code. Requests setting it validate cleanly and silently
  get default behavior.

* DF302 protocol-field-unread: a dataclass field in `llm/protocols.py`
  or `kv_router/protocols.py` with no reachable reader outside its
  defining file (attribute read or wire-dict key read). A field nothing
  ever reads is dead weight on every message — or a consumer lost to
  drift.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, SourceFile

from .graph import FunctionInfo, Project, call_tail, get_project

# The accept/parse layer (same set as dynalint's DL302): mentions here
# mean "accepted", not "consumed".
PARSE_LAYER = ("llm/validate.py", "llm/protocols.py",
               "llm/preprocessor.py", "llm/logits_processing.py")

_ROUTE_FNS = {"register", "add_post", "add_get", "add_route", "add_put",
              "add_delete", "add_patch"}
_ENTRY_NAMES = {"main", "amain"}


def entry_points(project: Project) -> list[FunctionInfo]:
    """Where work enters: request-plane handler registrations, HTTP
    routes, `main`s, and every module top (imports execute)."""
    entries: list[FunctionInfo] = []
    handler_names: set[str] = set()
    for fn in project.functions.values():
        if fn.name in _ENTRY_NAMES or fn.name == "<module>":
            entries.append(fn)
        # registrations anywhere in the subtree count — over-collecting
        # across nested scopes only widens the entry set, the safe
        # direction for a reachability gate
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call) \
                    and call_tail(node) in _ROUTE_FNS:
                for arg in list(node.args) + [k.value
                                              for k in node.keywords]:
                    if isinstance(arg, ast.Attribute):
                        handler_names.add(arg.attr)
                    elif isinstance(arg, ast.Name):
                        handler_names.add(arg.id)
    for name in handler_names:
        entries.extend(project.by_name.get(name, ()))
    return entries


def _by_suffix(files: list[SourceFile], suffix: str) -> Optional[SourceFile]:
    for src in files:
        if src.rel.endswith(suffix):
            return src
    return None


def _accepted_fields(src: SourceFile) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(src.tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "_COMMON_FIELDS"
                        for t in node.targets)):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    out.add(sub.value)
    return out


def _dataclass_fields(src: SourceFile,
                      classes: Optional[set[str]] = None,
                      ) -> dict[str, tuple[str, ast.AST]]:
    """field name -> (class name, node) for @dataclass classes."""
    out: dict[str, tuple[str, ast.AST]] = {}
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        if classes is not None and cls.name not in classes:
            continue
        decorated = any("dataclass" in ast.unparse(
            dec.func if isinstance(dec, ast.Call) else dec)
            for dec in cls.decorator_list)
        if not decorated:
            continue
        for stmt in cls.body:
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                out.setdefault(stmt.target.id, (cls.name, stmt))
    return out


def entry_reachable(project: Project) -> set[str]:
    """Reachable-from-entry-points set, computed once per Project (both
    reach rules share it within a run)."""
    cached = getattr(project, "_entry_reachable", None)
    if cached is None:
        cached = project.reachable(entry_points(project))
        project._entry_reachable = cached
    return cached


class _ReachRule(ProjectRule):
    def _reachable_readers(self, project: Project,
                           reachable: set[str], field: str,
                           exclude_rels: tuple[str, ...]) -> bool:
        for qual in reachable:
            fn = project.functions[qual]
            if fn.rel.endswith(exclude_rels):
                continue
            if field in fn.attr_reads or field in fn.key_reads:
                return True
        return False


class UnreachableAcceptedField(_ReachRule):
    id = "DF301"
    name = "unreachable-accepted-field"
    description = (
        "sampling/stop field accepted by llm/validate.py and carried by "
        "SamplingOptions/StopConditions with no read in any function "
        "reachable from an entry point (request-plane handlers, HTTP "
        "routes, mains) outside the accept/parse layer — requests "
        "setting it silently get default behavior (the min_p failure "
        "mode, now checked over the call graph instead of textually)")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        validate = _by_suffix(files, "llm/validate.py")
        protocols = _by_suffix(files, "llm/protocols.py")
        if validate is None or protocols is None:
            return
        project = get_project(files)
        reachable = entry_reachable(project)
        accepted = _accepted_fields(validate)
        fields = _dataclass_fields(
            protocols, {"SamplingOptions", "StopConditions"})
        for field in sorted(accepted & set(fields)):
            if self._reachable_readers(project, reachable, field,
                                       PARSE_LAYER):
                continue
            cls, node = fields[field]
            yield Finding(
                self.id, self.name, protocols.rel, node.lineno,
                node.col_offset,
                f"accepted field {cls}.{field} has no reachable reader "
                "outside the accept/parse layer — requests setting it "
                "pass validation and silently get default behavior; "
                "wire it into the engine path or stop accepting it")


class ProtocolFieldUnread(_ReachRule):
    id = "DF302"
    name = "protocol-field-unread"
    description = (
        "dataclass field in llm/protocols.py or kv_router/protocols.py "
        "with no reachable reader outside its defining file (attribute "
        "or wire-key read): dead weight on every message, or a consumer "
        "lost to drift — the dead-field warning the Rust compiler "
        "emits for free")

    PROTOCOL_FILES = ("llm/protocols.py", "kv_router/protocols.py")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        project = get_project(files)
        reachable: Optional[set[str]] = None
        for suffix in self.PROTOCOL_FILES:
            src = _by_suffix(files, suffix)
            if src is None:
                continue
            if reachable is None:
                reachable = entry_reachable(project)
            for field, (cls, node) in sorted(
                    _dataclass_fields(src).items()):
                if self._reachable_readers(project, reachable, field,
                                           (suffix,)):
                    continue
                yield Finding(
                    self.id, self.name, src.rel, node.lineno,
                    node.col_offset,
                    f"protocol field {cls}.{field} has no reachable "
                    "reader outside its defining file — dead weight on "
                    "every message; read it somewhere real or remove "
                    "it from the protocol")
