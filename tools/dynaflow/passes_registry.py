"""Pass 4 — config-knob and metric registry conformance.

The reference registers every `DYN_*` env var in one place
(environment_names.rs) and every metric name in prometheus_names.rs;
the compiler then flags unused consts. Our equivalents:

* DF401 unregistered-env-read: an `env("DYNT_*")` read (or raw
  os.environ access of a DYNT_ name) that does not resolve to a
  `runtime/config.py` `_register(...)` entry — it would raise KeyError
  at runtime through `env()`, or silently bypass the registry raw.
* DF402 env-default-type-mismatch: a registry entry whose declared
  default's type disagrees with its parser (`_int` with a str default
  means the env-set and default paths return different types).
* DF403 dead-config-knob: a registered `DYNT_*` name never read
  anywhere outside the registry — a knob operators can set that does
  nothing (the unused-const warning the Rust compiler emits).
* DF404 duplicate-metric-name: the same Prometheus metric name
  registered twice (prometheus_client raises at import time in one
  process, but duplicates across processes silently collide on shared
  scrape pages).
* DF405 undocumented-metric: a registered metric name missing from
  docs/metrics.md — the scrape page is operator API surface; dynalint
  DL303 already enforces the dynamo_ prefix.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, SourceFile

from .graph import call_tail, const_key

CONFIG_FILE = "runtime/config.py"
METRICS_DOC = pathlib.Path(__file__).parent.parent.parent / "docs" / "metrics.md"

_PARSER_TYPES = {
    "_str": str, "str": str,
    "_int": int, "int": int,
    "_float": float, "float": float,
    "_bool": bool, "is_truthy": bool,
}

_METRIC_CTORS = {"Counter", "Gauge", "Histogram", "Summary", "Info"}


def _registry_entries(src: SourceFile) -> dict[str, tuple[ast.Call, str]]:
    """env name -> (_register call node, parser name)."""
    out: dict[str, tuple[ast.Call, str]] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call) and call_tail(node) == "_register" \
                and node.args:
            name = const_key(node.args[0])
            if name is None:
                continue
            parser = ""
            if len(node.args) >= 3:
                p = node.args[2]
                parser = p.attr if isinstance(p, ast.Attribute) else \
                    getattr(p, "id", "")
            out[name] = (node, parser)
    return out


def _env_reads(files: list[SourceFile], prefix: str,
               ) -> list[tuple[SourceFile, ast.AST, str]]:
    """Every env("NAME") call and raw os.environ/getenv access of a
    `prefix`-named variable."""
    out = []
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = call_tail(node)
            name: Optional[str] = None
            if tail == "env" and node.args:
                name = const_key(node.args[0])
            elif tail in ("getenv", "get") and node.args:
                # os.getenv("X") / os.environ.get("X")
                base = node.func
                based = ast.unparse(base.value) if isinstance(
                    base, ast.Attribute) else ""
                if based in ("os", "os.environ", "environ"):
                    name = const_key(node.args[0])
            if name is not None and name.startswith(prefix):
                out.append((src, node, name))
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Subscript) \
                    and ast.unparse(node.value) in ("os.environ",
                                                    "environ"):
                name = const_key(node.slice)
                if name is not None and name.startswith(prefix):
                    out.append((src, node, name))
    return out


class _RegistryRule(ProjectRule):
    def __init__(self, config_suffix: str = CONFIG_FILE,
                 prefix: str = "DYNT_") -> None:
        self.config_suffix = config_suffix
        self.prefix = prefix

    def _config(self, files: list[SourceFile]) -> Optional[SourceFile]:
        for src in files:
            if src.rel.endswith(self.config_suffix):
                return src
        return None


class UnregisteredEnvRead(_RegistryRule):
    id = "DF401"
    name = "unregistered-env-read"
    description = (
        "a DYNT_* env read that does not resolve to a runtime/config.py "
        "registry entry: env() raises KeyError at runtime, and raw "
        "os.environ access bypasses the declared parser/default (the "
        "reference registers every DYN_* name in environment_names.rs)")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        config = self._config(files)
        if config is None:
            return
        registered = set(_registry_entries(config))
        for src, node, name in _env_reads(files, self.prefix):
            if src.rel.endswith(self.config_suffix):
                continue
            if name not in registered:
                yield Finding(
                    self.id, self.name, src.rel, node.lineno,
                    node.col_offset,
                    f"env var {name!r} is read here but not registered "
                    f"in {self.config_suffix}; register it with a "
                    "typed default (env() will raise KeyError "
                    "otherwise)")


class EnvDefaultTypeMismatch(_RegistryRule):
    id = "DF402"
    name = "env-default-type-mismatch"
    description = (
        "a registry entry whose declared default's type disagrees with "
        "its parser: with the env var unset callers get the default's "
        "type, with it set they get the parser's — downstream code "
        "breaks only in the configured case")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        config = self._config(files)
        if config is None:
            return
        for name, (node, parser) in sorted(
                _registry_entries(config).items()):
            want = _PARSER_TYPES.get(parser)
            if want is None or len(node.args) < 2:
                continue
            default = node.args[1]
            if not isinstance(default, ast.Constant):
                continue
            val = default.value
            if val is None:
                continue
            ok = isinstance(val, want) and not (
                want in (int, float) and isinstance(val, bool))
            if want is float and isinstance(val, int) \
                    and not isinstance(val, bool):
                ok = True  # int default for a float knob parses fine
            if not ok:
                yield Finding(
                    self.id, self.name, config.rel, node.lineno,
                    node.col_offset,
                    f"knob {name!r}: default {val!r} is "
                    f"{type(val).__name__} but the parser yields "
                    f"{want.__name__} — unset and set reads disagree "
                    "on type")


class DeadConfigKnob(_RegistryRule):
    id = "DF403"
    name = "dead-config-knob"
    description = (
        "a registered DYNT_* knob whose name never appears outside "
        "runtime/config.py: operators can set it and nothing changes — "
        "the unused-const dead code the Rust compiler flags")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        config = self._config(files)
        if config is None:
            return
        entries = _registry_entries(config)
        used: set[str] = set()
        for src in files:
            if src.rel.endswith(self.config_suffix):
                # uses inside config.py beyond the _register call itself
                # (RuntimeConfig.from_env reads) still count
                for node in ast.walk(src.tree):
                    if isinstance(node, ast.Call) \
                            and call_tail(node) == "env" and node.args:
                        name = const_key(node.args[0])
                        if name:
                            used.add(name)
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and node.value.startswith(self.prefix):
                    used.add(node.value)
        for name, (node, _) in sorted(entries.items()):
            if name not in used:
                yield Finding(
                    self.id, self.name, config.rel, node.lineno,
                    node.col_offset,
                    f"knob {name!r} is registered but never read "
                    "anywhere — wire it to the code it documents or "
                    "remove the registration")


class DuplicateMetricName(ProjectRule):
    id = "DF404"
    name = "duplicate-metric-name"
    description = (
        "the same Prometheus metric name registered at two sites: "
        "within a process prometheus_client raises at import; across "
        "processes the series silently collide on shared scrape pages")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        seen: dict[str, tuple[str, int]] = {}
        for src in files:
            if not _imports_prometheus(src):
                continue
            for node in ast.walk(src.tree):
                name = _metric_name(node)
                if name is None:
                    continue
                if name in seen:
                    rel, line = seen[name]
                    yield Finding(
                        self.id, self.name, src.rel, node.lineno,
                        node.col_offset,
                        f"metric {name!r} already registered at "
                        f"{rel}:{line}")
                else:
                    seen[name] = (src.rel, node.lineno)


class UndocumentedMetric(ProjectRule):
    id = "DF405"
    name = "undocumented-metric"
    description = (
        "a registered Prometheus metric name missing from "
        "docs/metrics.md: the scrape page is operator API surface — "
        "document the metric or remove it")

    def __init__(self, doc_path: pathlib.Path = METRICS_DOC) -> None:
        self.doc_path = doc_path

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        metrics: list[tuple[SourceFile, ast.AST, str]] = []
        for src in files:
            if not _imports_prometheus(src):
                continue
            for node in ast.walk(src.tree):
                name = _metric_name(node)
                if name is not None:
                    metrics.append((src, node, name))
        if not metrics:
            return
        documented: set[str] = set()
        if self.doc_path.exists():
            documented = set(re.findall(r"`(\w+)`",
                                        self.doc_path.read_text()))
        for src, node, name in metrics:
            if name not in documented:
                yield Finding(
                    self.id, self.name, src.rel, node.lineno,
                    node.col_offset,
                    f"metric {name!r} is not documented in "
                    f"{self.doc_path.name} — the scrape page is "
                    "operator API surface; add a row for it")


def _metric_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call) \
            and call_tail(node).split(".")[-1] in _METRIC_CTORS \
            and len(node.args) >= 2:
        return const_key(node.args[0])
    return None


def _imports_prometheus(src: SourceFile) -> bool:
    return any(
        (isinstance(n, ast.Import)
         and any(a.name.split(".")[0] == "prometheus_client"
                 for a in n.names))
        or (isinstance(n, ast.ImportFrom)
            and (n.module or "").split(".")[0] == "prometheus_client")
        for n in ast.walk(src.tree))


# Label names whose values come from request / tenant / federation
# identity. Fed raw, any of these turns metric cardinality into a
# function of WHO shows up (every tenant id, session id, or peer cell a
# request ever names mints an immortal Prometheus series); the
# `runtime/metric_labels.bounded_label()` funnel caps each namespace at
# DYNT_METRIC_MAX_LABELS with an `other` overflow bucket.
_RISKY_LABELS = frozenset({
    "tenant", "session", "session_id", "origin",
    "user", "user_id", "from", "to", "cell",
})

# Call tails accepted as cardinality bounds at a .labels() site.
_BOUNDING_TAILS = frozenset({"bounded_label", "admit"})


class UnboundedMetricLabel(ProjectRule):
    id = "DF406"
    name = "unbounded-metric-label"
    description = (
        "a per-origin label (tenant/session/cell/...) fed a dynamic "
        "value straight into .labels(): every distinct origin mints an "
        "immortal Prometheus series — route the value through "
        "runtime/metric_labels.bounded_label()")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        # metric VARIABLE name -> declared labelnames, project-wide
        # (metrics are module-level consts; cross-module references
        # keep the const name: rt_metrics.TENANT_SHED).
        families: dict[str, list[str]] = {}
        for src in files:
            for node in ast.walk(src.tree):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and _metric_name(node.value) is not None):
                    continue
                labelnames = _labelnames(node.value)
                if labelnames is None:
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        families[target.id] = labelnames
        for src in files:
            for node in ast.walk(src.tree):
                yield from self._check_site(src, node, families)

    def _check_site(self, src: SourceFile, node: ast.AST,
                    families: dict[str, list[str]]) -> Iterable[Finding]:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "labels"):
            return
        base = node.func.value
        base_name = base.attr if isinstance(base, ast.Attribute) \
            else getattr(base, "id", None)
        labelnames = families.get(base_name or "")
        if labelnames is None:
            return
        pairs: list[tuple[str, ast.expr]] = [
            (labelnames[i], arg) for i, arg in enumerate(node.args)
            if i < len(labelnames)]
        for kw in node.keywords:
            if kw.arg is not None:
                pairs.append((kw.arg, kw.value))
            elif isinstance(kw.value, ast.Dict):
                # .labels(**{"from": x, ...}) — the reserved-word shape
                pairs.extend(
                    (key.value, v) for key, v in
                    zip(kw.value.keys, kw.value.values)
                    if isinstance(key, ast.Constant)
                    and isinstance(key.value, str))
        for label, value in pairs:
            if label in _RISKY_LABELS and not _bounded_value(value):
                yield Finding(
                    self.id, self.name, src.rel, value.lineno,
                    value.col_offset,
                    f"label {label!r} on {base_name} fed a dynamic "
                    f"value — wrap it in bounded_label({label!r}, ...) "
                    "so origin churn cannot mint unbounded series")


def _labelnames(node: ast.Call) -> Optional[list[str]]:
    """Declared labelnames of a metric ctor call (third positional
    sequence or labelnames= kwarg); None when label-less."""
    candidates: list[ast.expr] = []
    if len(node.args) >= 3:
        candidates.append(node.args[2])
    candidates.extend(kw.value for kw in node.keywords
                      if kw.arg == "labelnames")
    for cand in candidates:
        if isinstance(cand, (ast.List, ast.Tuple)):
            names = [e.value for e in cand.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)]
            if len(names) == len(cand.elts):
                return names
    return None


def _bounded_value(value: ast.expr) -> bool:
    """True when the fed expression cannot mint unbounded series: a
    string literal (finite by construction) or a value routed through
    the bounded_label()/LabelRegistry.admit() funnel."""
    if isinstance(value, ast.Constant):
        return True
    if isinstance(value, ast.Call) and call_tail(value) in _BOUNDING_TAILS:
        return True
    return False
