"""Whole-program model: import graph + approximate call graph.

The Rust reference gets interprocedural guarantees from its compiler —
a message struct cannot drift between producer and consumer, an unused
field is a warning, a lock misuse is a Send/Sync error. This module is
the substrate dynaflow's passes recover those checks on: every file is
parsed once, every function/method becomes a node, and call edges are
resolved *by name* (a call `self.foo()` or `mod.foo()` links to every
known function named `foo`; a bare reference handed to a wrapper like
`Thread(target=f)` or `add_done_callback(cb)` links too). Name
resolution over-approximates — which is the right direction for the
passes built on it: reachability can only over-claim (fewer false
"dead field" findings), and lock tracing can only over-trace (more
hazards surfaced, reviewed once, suppressed with a justification if
deliberate).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

from tools.dynalint.core import SourceFile


@dataclasses.dataclass
class FunctionInfo:
    qualname: str          # "rel::Class.method@line" / "rel::<module>"
    name: str              # bare name ("method", "func", "<module>")
    rel: str               # posix path of the defining file
    cls: Optional[str]     # enclosing class name, if a method
    node: ast.AST
    lineno: int
    calls: set[str] = dataclasses.field(default_factory=set)  # callee tails
    refs: set[str] = dataclasses.field(default_factory=set)   # referenced names
    attr_reads: set[str] = dataclasses.field(default_factory=set)
    key_reads: set[str] = dataclasses.field(default_factory=set)
    is_async: bool = False


def call_tail(node: ast.Call) -> str:
    """Last segment of the call target ('create_task' for
    asyncio.create_task, 'send' for conn.send)."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def const_key(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class Project:
    """Parsed view of a file set: functions by name, a name-resolved
    call graph, and per-function read sets."""

    def __init__(self, files: list[SourceFile]) -> None:
        self.files = files
        self.functions: dict[str, FunctionInfo] = {}
        self.by_name: dict[str, list[FunctionInfo]] = {}
        for src in files:
            module_fn = FunctionInfo(
                qualname=f"{src.rel}::<module>", name="<module>",
                rel=src.rel, cls=None, node=src.tree, lineno=1)
            self._collect_body(module_fn, src.tree, src, cls=None)
            self._add(module_fn)

    # -- construction ------------------------------------------------------

    def _add(self, fn: FunctionInfo) -> None:
        self.functions[fn.qualname] = fn
        self.by_name.setdefault(fn.name, []).append(fn)

    def _collect_body(self, owner: FunctionInfo, root: ast.AST,
                      src: SourceFile, cls: Optional[str]) -> None:
        """Attribute `root`'s scope to `owner`, collecting defs nested at
        ANY depth (inside if/try/with/for too — a handler defined under
        `if args.mode == ...:` is still a real function) as their own
        nodes; every non-def node is recorded exactly once."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                prefix = f"{cls}." if cls else ""
                fn = FunctionInfo(
                    qualname=f"{src.rel}::{prefix}{node.name}"
                             f"@{node.lineno}",
                    name=node.name, rel=src.rel, cls=cls, node=node,
                    lineno=node.lineno,
                    is_async=isinstance(node, ast.AsyncFunctionDef))
                self._collect_body(fn, node, src, cls=cls)
                self._add(fn)
                continue  # the definition itself is not an execution edge
            if isinstance(node, ast.ClassDef):
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fn = FunctionInfo(
                            qualname=f"{src.rel}::{node.name}.{sub.name}"
                                     f"@{sub.lineno}",
                            name=sub.name, rel=src.rel, cls=node.name,
                            node=sub, lineno=sub.lineno,
                            is_async=isinstance(sub, ast.AsyncFunctionDef))
                        self._collect_body(fn, sub, src, cls=node.name)
                        self._add(fn)
                    else:
                        stack.append(sub)
                continue
            self._record(owner, node)
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _record(owner: FunctionInfo, cur: ast.AST) -> None:
        """Record one node's calls/refs/reads (children are walked by
        the caller)."""
        if isinstance(cur, ast.Call):
            tail = call_tail(cur)
            if tail:
                owner.calls.add(tail)
            if tail == "get" and cur.args:  # d.get("k") is a key read
                key = const_key(cur.args[0])
                if key is not None:
                    owner.key_reads.add(key)
        elif isinstance(cur, ast.Attribute):
            if isinstance(cur.ctx, ast.Load):
                owner.attr_reads.add(cur.attr)
                owner.refs.add(cur.attr)
        elif isinstance(cur, ast.Name):
            if isinstance(cur.ctx, ast.Load):
                owner.refs.add(cur.id)
        elif isinstance(cur, ast.Subscript):
            key = const_key(cur.slice)
            if key is not None and isinstance(cur.ctx, ast.Load):
                owner.key_reads.add(key)
        elif isinstance(cur, ast.Compare):
            if any(isinstance(op, (ast.In, ast.NotIn))
                   for op in cur.ops):  # '"k" in d' is a key read
                key = const_key(cur.left)
                if key is not None:
                    owner.key_reads.add(key)

    # -- queries -----------------------------------------------------------

    def callees(self, fn: FunctionInfo,
                refs_too: bool = True) -> Iterator[FunctionInfo]:
        """Functions this one may invoke (name-resolved; with refs_too,
        bare references handed to wrappers like Thread(target=...) count
        as execution edges)."""
        seen: set[str] = set()
        names = fn.calls | fn.refs if refs_too else fn.calls
        for name in names:
            for cand in self.by_name.get(name, ()):
                if cand.name == "<module>":
                    continue
                if cand.qualname not in seen:
                    seen.add(cand.qualname)
                    yield cand

    def reachable(self, entries: list[FunctionInfo]) -> set[str]:
        """Qualnames reachable from `entries` over name-resolved edges."""
        out: set[str] = set()
        stack = list(entries)
        while stack:
            fn = stack.pop()
            if fn.qualname in out:
                continue
            out.add(fn.qualname)
            stack.extend(c for c in self.callees(fn)
                         if c.qualname not in out)
        return out


# One Project shared by every pass in a run (run() hands all rules the
# same `files` list). The entry holds the keyed list itself so a freed
# address reused by a different list can never serve a stale graph.
_PROJECT_CACHE: dict[int, tuple[list, Project]] = {}


def get_project(files: list[SourceFile]) -> Project:
    hit = _PROJECT_CACHE.get(id(files))
    if hit is not None and hit[0] is files:
        return hit[1]
    if len(_PROJECT_CACHE) > 8:
        _PROJECT_CACHE.clear()
    project = Project(files)
    _PROJECT_CACHE[id(files)] = (files, project)
    return project
