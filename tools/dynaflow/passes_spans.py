"""Pass 5 — span-name registry conformance.

Trace span names are operator API surface exactly like metric names:
dashboards, trace queries, and alert routing key on them, and
docs/observability.md is their canonical catalogue (the
prometheus_names.rs analog for the tracing plane). Two rules, the same
shape as the DF404/DF405 metric-registry rules:

* DF501 undocumented-span: a literal span name passed to
  `start_span(...)` / `record_span(...)` that does not appear (in
  backticks) in the docs/observability.md catalogue — new spans must be
  documented in the same PR.
* DF502 duplicate-span-name: the same literal span name created at two
  distinct call sites — span names identify one instrumentation point;
  two sites sharing one name make traces unattributable.

Name extraction handles plain string constants and conditional
expressions whose branches are both constants
(`"http.chat" if kind == "chat" else "http.completions"`). Dynamic
names are invisible to the registry — keep span names literal.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Iterable

from tools.dynalint.core import Finding, ProjectRule, SourceFile

from .graph import call_tail

OBSERVABILITY_DOC = (pathlib.Path(__file__).parent.parent.parent
                     / "docs" / "observability.md")

# Call tails that create a span whose first positional argument is its
# name (runtime/otel.py Tracer API).
SPAN_FNS = ("start_span", "record_span")


def _span_names(node: ast.AST) -> list[str]:
    """Literal span name(s) at a span-creating call site, [] otherwise."""
    if not (isinstance(node, ast.Call) and call_tail(node) in SPAN_FNS
            and node.args):
        return []
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value]
    if isinstance(arg, ast.IfExp) \
            and isinstance(arg.body, ast.Constant) \
            and isinstance(arg.body.value, str) \
            and isinstance(arg.orelse, ast.Constant) \
            and isinstance(arg.orelse.value, str):
        return [arg.body.value, arg.orelse.value]
    return []


def span_sites(files: list[SourceFile],
               ) -> list[tuple[SourceFile, ast.AST, str]]:
    out = []
    for src in files:
        for node in ast.walk(src.tree):
            for name in _span_names(node):
                out.append((src, node, name))
    return out


class _SpanRule(ProjectRule):
    def __init__(self, doc_path: pathlib.Path = OBSERVABILITY_DOC) -> None:
        self.doc_path = doc_path


def _catalogue_names(text: str) -> set[str]:
    """Span names documented in the catalogue: the first backticked cell
    of each table row, scoped to the "Span-name catalogue" section when
    that heading exists (so attribute/phase words backticked in prose or
    other tables don't count as documented spans)."""
    section = re.search(r"^##[^\n]*catalogue[^\n]*$(.*?)(?=^## |\Z)",
                        text, re.MULTILINE | re.DOTALL | re.IGNORECASE)
    if section:
        text = section.group(1)
    return set(re.findall(r"^\|\s*`([A-Za-z0-9_.]+)`\s*\|",
                          text, re.MULTILINE))


class UndocumentedSpan(_SpanRule):
    id = "DF501"
    name = "undocumented-span"
    description = (
        "a literal span name passed to start_span/record_span that is "
        "missing from the docs/observability.md catalogue: span names "
        "are operator API surface (trace queries and dashboards key on "
        "them) — document the span or remove it")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        sites = span_sites(files)
        if not sites:
            return
        documented: set[str] = set()
        if self.doc_path.exists():
            documented = _catalogue_names(self.doc_path.read_text())
        for src, node, name in sites:
            if name not in documented:
                yield Finding(
                    self.id, self.name, src.rel, node.lineno,
                    node.col_offset,
                    f"span {name!r} is not documented in "
                    f"{self.doc_path.name} — add it to the span-name "
                    "catalogue in the same PR")


class DuplicateSpanName(_SpanRule):
    id = "DF502"
    name = "duplicate-span-name"
    description = (
        "the same literal span name created at two call sites: a span "
        "name identifies ONE instrumentation point — two sites sharing "
        "it make trace durations and error rates unattributable")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        seen: dict[str, tuple[str, int]] = {}
        for src, node, name in span_sites(files):
            if name in seen:
                rel, line = seen[name]
                yield Finding(
                    self.id, self.name, src.rel, node.lineno,
                    node.col_offset,
                    f"span name {name!r} already created at {rel}:{line} "
                    "— give each instrumentation point its own name")
            else:
                seen[name] = (src.rel, node.lineno)
