"""Pass 2 — lock hazards traced through direct callees.

Rust's Send/Sync rules out whole classes of lock misuse at compile
time; asyncio gives us nothing. Two interprocedural checks:

* DF201 slow-call-under-lock: inside an `async def`, an `await` of a
  known-slow operation (transport send/connect, subprocess, sleep,
  to_thread, queue waits) while a tracked lock is held — including
  slow awaits inside a *direct callee* of the locked region. Holding a
  lock across a slow await serializes every other task on that lock
  behind a network peer or the thread pool. Exemption: locks whose
  name contains "send" may cover transport writes (`drain`, `send*`)
  — serializing the transport is precisely what a send lock is for.

* DF202 lock-order-inversion: two lock attributes acquired in both
  orders somewhere in the tree (nested `with` blocks, traced one call
  deep). Inconsistent pairwise order is the classic ABBA deadlock;
  the reference's equivalents are reviewed lock hierarchies in
  leader.rs/worker.rs.

Tracked locks: `self.X = asyncio.Lock()/threading.Lock()/RLock()/
Condition()` attributes (identity `Class.X`), module-level locks, and
function-local locks. `with`/`async with` acquisitions only — the
codebase idiom everywhere.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from tools.dynalint.core import Finding, ProjectRule, SourceFile

from .graph import FunctionInfo, Project, call_tail, get_project

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

# Awaited-call name tails considered slow while a lock is held.
SLOW_TAILS = {
    "sleep", "to_thread", "run_in_executor", "gather",
    "open_connection", "connect", "create_subprocess_exec",
    "create_subprocess_shell",
    "drain", "send", "send_multipart", "recv_multipart",
    "wait", "wait_for", "get", "put", "post", "request",
    "read", "readexactly", "readline",
}

# Transport writes a send lock legitimately covers.
_SEND_OK = {"drain", "send", "send_multipart", "write"}


@dataclasses.dataclass(frozen=True)
class LockId:
    scope: str  # class name, "<module>", or the function qualname
    attr: str   # attribute / variable name

    def __str__(self) -> str:
        return f"{self.scope}.{self.attr}"


def _is_lock_ctor(node: ast.expr) -> bool:
    return isinstance(node, ast.Call) and call_tail(node) in _LOCK_CTORS


def _is_lock_factory_field(node: ast.expr) -> bool:
    """dataclass idiom: `_lock: Lock = field(default_factory=threading.Lock)`.
    The factory is a *reference* to the ctor, not a call, so _is_lock_ctor
    never sees it."""
    if not (isinstance(node, ast.Call) and call_tail(node) == "field"):
        return False
    for kw in node.keywords:
        if kw.arg == "default_factory":
            v = kw.value
            name = v.attr if isinstance(v, ast.Attribute) else (
                v.id if isinstance(v, ast.Name) else None)
            return name in _LOCK_CTORS
    return False


def collect_locks(files: list[SourceFile]) -> set[LockId]:
    """All tracked lock identities in the tree."""
    locks: set[LockId] = set()
    for src in files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) \
                            and _is_lock_ctor(sub.value):
                        for tgt in sub.targets:
                            if isinstance(tgt, ast.Attribute) \
                                    and isinstance(tgt.value, ast.Name) \
                                    and tgt.value.id == "self":
                                locks.add(LockId(node.name, tgt.attr))
                # Dataclass lock fields live in the class body as annotated
                # assignments, accessed at runtime as self.<name>.
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and stmt.value is not None \
                            and isinstance(stmt.target, ast.Name) \
                            and _is_lock_factory_field(stmt.value):
                        locks.add(LockId(node.name, stmt.target.id))
        for stmt in src.tree.body:
            if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        locks.add(LockId("<module>", tgt.id))
    return locks


def _local_locks(fn: FunctionInfo) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _acquired(item: ast.withitem, fn: FunctionInfo,
              locks: set[LockId], local: set[str]) -> Optional[LockId]:
    expr = item.context_expr
    if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                      ast.Name) \
            and expr.value.id == "self" and fn.cls is not None:
        lid = LockId(fn.cls, expr.attr)
        if lid in locks:
            return lid
    if isinstance(expr, ast.Name):
        if expr.id in local:
            return LockId(fn.qualname, expr.id)
        lid = LockId("<module>", expr.id)
        if lid in locks:
            return lid
    return None


def _function_acquisitions(fn: FunctionInfo,
                           locks: set[LockId]) -> set[LockId]:
    """Attribute/module locks this function acquires anywhere (used for
    one-call-deep tracing; local locks excluded — they are invisible to
    callers)."""
    out: set[LockId] = set()
    local = _local_locks(fn)
    for node in ast.walk(fn.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lid = _acquired(item, fn, locks, local)
                if lid is not None and lid.scope != fn.qualname:
                    out.add(lid)
    return out


def _slow_awaits(fn: FunctionInfo) -> list[tuple[ast.AST, str]]:
    """(await-node, slow tail) pairs anywhere in this function."""
    out = []
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Await) and isinstance(node.value,
                                                      ast.Call):
            tail = call_tail(node.value)
            if tail in SLOW_TAILS:
                out.append((node, tail))
    return out


def _call_base(node: ast.Call) -> tuple[str, str]:
    """('self' | 'selfattr' | 'name' | 'bare', base descriptor) for
    callee resolution: self.m() -> same class; self.X.m() -> the class
    assigned to self.X; f() -> same file; anything else unresolved."""
    func = node.func
    if isinstance(func, ast.Name):
        return "bare", ""
    if isinstance(func, ast.Attribute):
        base = func.value
        if isinstance(base, ast.Name) and base.id == "self":
            return "self", ""
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self":
            return "selfattr", base.attr
    return "other", ""


def attr_classes(files: list[SourceFile]) -> dict[str, set[str]]:
    """`self.X = ClassName(...)` assignments project-wide: attribute
    name -> possible classes (one-step type inference for resolving
    self.X.m() calls)."""
    out: dict[str, set[str]] = {}
    class_names = {n.name for src in files
                   for n in ast.walk(src.tree)
                   if isinstance(n, ast.ClassDef)}
    for src in files:
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            cls = call_tail(node.value)
            if cls not in class_names:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    out.setdefault(tgt.attr, set()).add(cls)
    return out


def resolve_callees(project: Project, fn: FunctionInfo, node: ast.Call,
                    attr_map: dict[str, set[str]]) -> list[FunctionInfo]:
    """Direct callees of a call site, resolved conservatively (unlike
    the reachability graph, which over-approximates on purpose)."""
    tail = call_tail(node)
    kind, base = _call_base(node)
    cands = [c for c in project.by_name.get(tail, ())
             if c.qualname != fn.qualname]
    if kind == "self":
        return [c for c in cands if c.cls == fn.cls and c.cls is not None]
    if kind == "selfattr":
        classes = attr_map.get(base, set())
        return [c for c in cands if c.cls in classes]
    if kind == "bare":
        return [c for c in cands if c.rel == fn.rel and c.cls is None]
    return []


class _LockWalker:
    """Walks one function tracking the held-lock stack; reports
    acquisitions, slow awaits, and calls with the stack at that point.
    Nested function/class defs are skipped (they run later, not under
    the lock)."""

    def __init__(self, fn: FunctionInfo, locks: set[LockId]) -> None:
        self.fn = fn
        self.locks = locks
        self.local = _local_locks(fn)
        self.events: list[tuple[str, ast.AST, object, tuple]] = []
        self._walk(fn.node, ())

    def _walk(self, node: ast.AST, held: tuple) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                inner = held
                for item in child.items:
                    lid = _acquired(item, self.fn, self.locks, self.local)
                    if lid is not None:
                        self.events.append(("acquire", child, lid, inner))
                        inner = inner + (lid,)
                self._walk(child, inner)
                continue
            if isinstance(child, ast.Await) \
                    and isinstance(child.value, ast.Call):
                tail = call_tail(child.value)
                self.events.append(("await", child, tail, held))
            if isinstance(child, ast.Call):
                self.events.append(("call", child, child, held))
            self._walk(child, held)


def _send_exempt(lid: LockId, tail: str) -> bool:
    return "send" in lid.attr.lower() and tail in _SEND_OK


class SlowCallUnderLock(ProjectRule):
    id = "DF201"
    name = "slow-call-under-lock"
    description = (
        "an async function awaits a known-slow call (transport "
        "send/connect, subprocess, sleep, to_thread, queue waits) while "
        "holding a lock — traced through direct callees — serializing "
        "every other task on that lock behind a peer or the thread "
        "pool; locks named *send* are exempt for transport writes "
        "(serializing the transport is their purpose)")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        project = get_project(files)
        locks = collect_locks(files)
        attr_map = attr_classes(files)
        for fn in project.functions.values():
            if not fn.is_async:
                continue
            walker = _LockWalker(fn, locks)
            for kind, node, payload, held in walker.events:
                if not held:
                    continue
                if kind == "await" and payload in SLOW_TAILS:
                    bad = [lid for lid in held
                           if not _send_exempt(lid, str(payload))]
                    if bad:
                        yield Finding(
                            self.id, self.name, fn.rel, node.lineno,
                            node.col_offset,
                            f"await of slow call '{payload}' while "
                            f"holding {', '.join(map(str, bad))} — "
                            "move the slow operation outside the "
                            "locked region")
                elif kind == "call":
                    # one call deep: a callee that awaits slow ops runs
                    # them under our lock
                    for callee in resolve_callees(project, fn, payload,
                                                  attr_map):
                        for sub_node, tail in _slow_awaits(callee):
                            bad = [lid for lid in held
                                   if not _send_exempt(lid, tail)]
                            if bad:
                                yield Finding(
                                    self.id, self.name, fn.rel,
                                    node.lineno, node.col_offset,
                                    f"call to '{callee.name}' (which "
                                    f"awaits slow call '{tail}' at "
                                    f"{callee.rel}:{sub_node.lineno}) "
                                    f"while holding "
                                    f"{', '.join(map(str, bad))}")
                                break  # one finding per callee


class LockOrderInversion(ProjectRule):
    id = "DF202"
    name = "lock-order-inversion"
    description = (
        "two locks acquired in opposite orders somewhere across "
        "engine/, block_manager/, and runtime/ (nested with-blocks, "
        "traced one call deep): the classic ABBA deadlock the "
        "reference avoids with reviewed lock hierarchies")

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        project = get_project(files)
        locks = collect_locks(files)
        attr_map = attr_classes(files)
        # (outer, inner) -> first observed (rel, line, description)
        orders: dict[tuple[LockId, LockId], tuple[str, int, str]] = {}
        callee_locks: dict[str, set[LockId]] = {}
        for fn in project.functions.values():
            walker = _LockWalker(fn, locks)
            for kind, node, payload, held in walker.events:
                if kind == "acquire":
                    for outer in held:
                        self._note(orders, outer, payload, fn, node,
                                   f"{outer} then {payload}")
                elif kind == "call" and held:
                    for callee in resolve_callees(project, fn, payload,
                                                  attr_map):
                        acq = callee_locks.get(callee.qualname)
                        if acq is None:
                            acq = _function_acquisitions(callee, locks)
                            callee_locks[callee.qualname] = acq
                        for inner in acq:
                            for outer in held:
                                self._note(
                                    orders, outer, inner, fn, node,
                                    f"{outer} then {inner} (via "
                                    f"{callee.name})")
        seen: set[frozenset] = set()
        for (outer, inner), (rel, line, desc) in sorted(
                orders.items(), key=lambda kv: (kv[1][0], kv[1][1])):
            if (inner, outer) not in orders or outer == inner:
                continue
            pair = frozenset((outer, inner))
            if pair in seen:
                continue
            seen.add(pair)
            o_rel, o_line, o_desc = orders[(inner, outer)]
            yield Finding(
                self.id, self.name, rel, line, 0,
                f"inconsistent lock order: {desc} here, but "
                f"{o_desc} at {o_rel}:{o_line} — an ABBA deadlock "
                "waiting for the right interleaving; pick one order")

    @staticmethod
    def _note(orders: dict, outer: LockId, inner, fn: FunctionInfo,
              node: ast.AST, desc: str) -> None:
        if outer == inner:
            return
        key = (outer, inner)
        if key not in orders:
            orders[key] = (fn.rel, node.lineno, desc)
