"""dynalint — project-specific AST hazard linter for dynamo_tpu.

Usage: ``python -m tools.dynalint dynamo_tpu/ [--format json]``

Rules cover the hazard classes the Rust reference rules out at compile
time: leaked/blocked asyncio tasks (DL1xx), JAX hot-path host syncs and
recompile traps (DL2xx), and wire-protocol / observability invariants
(DL3xx). See docs/static-analysis.md for the full catalogue.
"""

from . import rules_async, rules_jax, rules_runtime  # noqa: F401 — register
from .core import (  # noqa: F401
    DYNALINT,
    Finding,
    ProjectRule,
    Registry,
    Rule,
    all_rules,
    main_for,
    render_json,
    render_text,
    run,
)

__all__ = ["Finding", "Rule", "ProjectRule", "Registry", "DYNALINT",
           "all_rules", "run", "render_text", "render_json", "main"]


def main(argv=None) -> int:
    return main_for(
        DYNALINT, ["dynamo_tpu"],
        "AST-based hazard linter for the dynamo_tpu codebase", argv)
