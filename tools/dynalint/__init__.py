"""dynalint — project-specific AST hazard linter for dynamo_tpu.

Usage: ``python -m tools.dynalint dynamo_tpu/ [--format json]``

Rules cover the hazard classes the Rust reference rules out at compile
time: leaked/blocked asyncio tasks (DL1xx), JAX hot-path host syncs and
recompile traps (DL2xx), and wire-protocol / observability invariants
(DL3xx). See docs/static-analysis.md for the full catalogue.
"""

from . import rules_async, rules_jax, rules_runtime  # noqa: F401 — register
from .core import (  # noqa: F401
    Finding,
    ProjectRule,
    Rule,
    all_rules,
    render_json,
    render_text,
    run,
)

__all__ = ["Finding", "Rule", "ProjectRule", "all_rules", "run",
           "render_text", "render_json", "main"]


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m tools.dynalint",
        description="AST-based hazard linter for the dynamo_tpu codebase")
    parser.add_argument("paths", nargs="*", default=["dynamo_tpu"],
                        help="files or directories to lint "
                             "(default: dynamo_tpu)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id} [{rule.name}]\n    {rule.description}")
        return 0

    findings, files_checked = run(args.paths or ["dynamo_tpu"])
    render = render_json if args.format == "json" else render_text
    print(render(findings, files_checked))
    return 1 if findings else 0
