"""dynalint framework: rule registry, suppressions, runner, output.

The Rust reference gets whole hazard classes ruled out by its compiler
(leaked tasks, blocking the runtime, unserializable protocol types).
This is the Python reproduction's equivalent: a stdlib-``ast`` pass with
project-specific rules over the async runtime and the JAX hot paths.

Suppression syntax (on the flagged line)::

    do_hazardous_thing()  # dynalint: disable=DL101 -- justification

Multiple rules separate with commas; rule names are accepted in place of
ids. A suppression naming an unknown rule is itself reported (DL000) so
typos cannot silently disable nothing.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Iterable, Optional


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # rule id, e.g. "DL101"
    name: str  # rule slug, e.g. "fire-and-forget-task"
    path: str  # posix path as given on the command line
    line: int
    col: int
    message: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SourceFile:
    path: pathlib.Path
    rel: str  # posix relative path used for rule scoping
    tree: ast.Module
    lines: list[str]


class Rule:
    """Per-file rule. Subclasses set id/name/description and implement
    check_file; override applies() to scope to a path subset."""

    id: str = ""
    name: str = ""
    description: str = ""

    def applies(self, rel: str) -> bool:
        return True

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        return ()

    def finding(self, src: SourceFile, node: ast.AST, message: str) -> Finding:
        return Finding(self.id, self.name, src.rel,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


class ProjectRule(Rule):
    """Cross-file rule: sees every collected file at once."""

    def check_project(self, files: list[SourceFile]) -> Iterable[Finding]:
        return ()


class Registry:
    """One linter's rule catalogue + suppression dialect. dynalint and
    dynaflow each hold one; the driver, suppression semantics, and
    output formats are shared through it."""

    def __init__(self, tool: str, bad_id: str) -> None:
        self.tool = tool  # suppression marker: `# <tool>: disable=...`
        self.bad_id = bad_id  # the bad-suppression rule id (DL000/DF000)
        self.rules: dict[str, Rule] = {}
        self._suppress_re = re.compile(
            rf"#\s*{re.escape(tool)}:\s*disable=([^#]*)")

    def register(self, cls: type) -> type:
        rule = cls()
        if rule.id in self.rules:
            raise ValueError(f"duplicate rule id {rule.id}")
        self.rules[rule.id] = rule
        return cls

    def all_rules(self) -> list[Rule]:
        return [self.rules[k] for k in sorted(self.rules)]

    def known_tokens(self) -> set[str]:
        out = {self.bad_id, "bad-suppression"}
        for rule in self.rules.values():
            out.add(rule.id)
            out.add(rule.name)
        return out

    def suppressions(self, lines: list[str],
                     rel: str) -> tuple[dict, list]:
        """Per-line suppressed rule tokens plus bad-suppression findings
        for unknown rule names (a typo'd suppression must not silently
        disable nothing)."""
        known = self.known_tokens()
        per_line: dict[int, set[str]] = {}
        bad: list[Finding] = []
        for i, text in enumerate(lines, start=1):
            m = self._suppress_re.search(text)
            if not m:
                continue
            # Everything after ` -- ` is the justification, not rules.
            spec = m.group(1).split("--", 1)[0]
            tokens = {t.strip() for t in spec.split(",") if t.strip()}
            for tok in sorted(tokens - known):
                bad.append(Finding(
                    self.bad_id, "bad-suppression", rel, i, m.start(),
                    f"suppression names unknown rule {tok!r}; known "
                    "rules: "
                    + ", ".join(sorted(r.id for r in self.rules.values()))))
            per_line[i] = tokens & known
        return per_line, bad


DYNALINT = Registry("dynalint", "DL000")


def register(cls: type) -> type:
    return DYNALINT.register(cls)


def all_rules() -> list[Rule]:
    return DYNALINT.all_rules()


def collect_files(paths: list[str]) -> tuple[list[SourceFile], list[Finding]]:
    files: list[SourceFile] = []
    errors: list[Finding] = []
    seen: set[pathlib.Path] = set()
    for raw in paths:
        root = pathlib.Path(raw)
        if root.is_dir():
            # Hidden-dir filter applies only BELOW the given root — a
            # checkout that happens to live under a dot-directory must
            # not silently lint zero files.
            candidates = sorted(
                p for p in root.rglob("*.py")
                if not any(part.startswith(".")
                           for part in p.relative_to(root).parts))
        else:
            candidates = [root]
        for path in candidates:
            if path in seen:
                continue
            seen.add(path)
            try:
                source = path.read_text(encoding="utf-8")
                tree = ast.parse(source, filename=str(path))
            except (OSError, SyntaxError, ValueError) as exc:
                errors.append(Finding(
                    "DL001", "unparseable-file", path.as_posix(), 1, 0,
                    f"cannot parse: {exc}"))
                continue
            files.append(SourceFile(path, path.as_posix(), tree,
                                    source.splitlines()))
    return files, errors


def run(paths: list[str],
        rules: Optional[list[Rule]] = None,
        registry: Registry = DYNALINT) -> tuple[list[Finding], int]:
    """Lint `paths`; returns (findings after suppression, files checked)."""
    rules = registry.all_rules() if rules is None else rules
    files, findings = collect_files(paths)
    suppress: dict[str, dict[int, set[str]]] = {}
    for src in files:
        per_line, bad = registry.suppressions(src.lines, src.rel)
        suppress[src.rel] = per_line
        findings.extend(bad)
        for rule in rules:
            if not isinstance(rule, ProjectRule) and rule.applies(src.rel):
                findings.extend(rule.check_file(src))
    for rule in rules:
        if isinstance(rule, ProjectRule):
            findings.extend(rule.check_project(files))
    kept = [f for f in findings
            if not {f.rule, f.name}
            & suppress.get(f.path, {}).get(f.line, set())]
    return sorted(kept, key=lambda f: (f.path, f.line, f.rule)), len(files)


def render_text(findings: list[Finding], files_checked: int,
                registry: Registry = DYNALINT) -> str:
    out = [f"{f.path}:{f.line}:{f.col}: {f.rule} [{f.name}] {f.message}"
           for f in findings]
    out.append(f"{len(findings)} finding(s) in {files_checked} file(s) "
               f"({len(registry.rules)} rules)")
    return "\n".join(out)


def render_json(findings: list[Finding], files_checked: int,
                registry: Registry = DYNALINT) -> str:
    return json.dumps({
        "version": 1,
        "files_checked": files_checked,
        "rules": [{"id": r.id, "name": r.name,
                   "description": r.description}
                  for r in registry.all_rules()],
        "findings": [f.to_json() for f in findings],
    }, indent=2)


def main_for(registry: Registry, default_paths: list[str],
             description: str, argv=None,
             extra_args=None, handle_extra=None) -> int:
    """Shared CLI driver: paths, --format, --list-rules, exit codes.
    `extra_args(parser)` may add tool-specific flags; `handle_extra(args)`
    may fully handle them (return an exit code, or None to proceed)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog=f"python -m tools.{registry.tool}", description=description)
    parser.add_argument("paths", nargs="*", default=list(default_paths),
                        help="files or directories to lint "
                             f"(default: {' '.join(default_paths)})")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    if extra_args is not None:
        extra_args(parser)
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in registry.all_rules():
            print(f"{rule.id} [{rule.name}]\n    {rule.description}")
        return 0
    if handle_extra is not None:
        code = handle_extra(args)
        if code is not None:
            return code

    findings, files_checked = run(args.paths or list(default_paths),
                                  registry=registry)
    render = render_json if args.format == "json" else render_text
    print(render(findings, files_checked, registry))
    return 1 if findings else 0


# -- shared AST helpers ------------------------------------------------------


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: 'asyncio.create_task', 'np.asarray',
    'loop.create_task' (best effort; unresolvable pieces dropped)."""
    parts: list[str] = []
    cur: ast.AST = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def walk_skip_functions(body: list[ast.stmt]) -> Iterable[ast.AST]:
    """Walk statements without descending into nested function/class
    scopes (their bodies execute in a different context)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
