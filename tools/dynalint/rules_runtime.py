"""Runtime-invariant rules: wire-protocol and observability contracts.

Rust's serde derives make an unserializable protocol type a compile
error and the reference's prometheus_names.rs centralizes metric
naming; these rules are the Python stand-ins, plus the project-specific
"accepted-but-unconsumed sampling field" check distilled from a real
production bug (min_p validated, parsed, and silently ignored)."""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .core import Finding, ProjectRule, Rule, SourceFile, call_name, register

# Types msgpack packs losslessly (plus containers of them). Tuples pack
# but decode as lists; sets/ndarrays/datetimes fail outright.
_SAFE_NAMES = {"int", "float", "str", "bool", "bytes", "None", "Any",
               "dict", "list", "object"}
_SAFE_GENERICS = {"list", "List", "dict", "Dict", "Optional", "Union"}
_LOSSY = {
    "tuple": "tuples decode as lists",
    "Tuple": "tuples decode as lists",
    "set": "sets do not pack",
    "Set": "sets do not pack",
    "frozenset": "sets do not pack",
    "ndarray": "ndarrays do not pack (send shape + bytes instead)",
    "datetime": "datetimes do not pack (send a unix timestamp)",
    "complex": "complex numbers do not pack",
}


def _ann_problem(node: Optional[ast.AST],
                 local_types: set[str]) -> Optional[str]:
    """None if the annotation round-trips through msgpack, else why not."""
    if node is None:
        return None
    if isinstance(node, ast.Constant):
        if node.value is None:
            return None
        if isinstance(node.value, str):
            try:
                return _ann_problem(ast.parse(node.value, mode="eval").body,
                                    local_types)
            except SyntaxError:
                return "unparseable string annotation"
        return None
    if isinstance(node, (ast.Name, ast.Attribute)):
        name = node.attr if isinstance(node, ast.Attribute) else node.id
        if name in _SAFE_NAMES or name in _SAFE_GENERICS \
                or name in local_types:
            return None
        return _LOSSY.get(name, f"{name} is not a msgpack-native type")
    if isinstance(node, ast.Subscript):
        base = node.value
        name = base.attr if isinstance(base, ast.Attribute) else \
            getattr(base, "id", "")
        if name not in _SAFE_GENERICS:
            return _LOSSY.get(name, f"{name}[...] is not msgpack-native")
        inner = node.slice
        elts = inner.elts if isinstance(inner, ast.Tuple) else [inner]
        for elt in elts:
            problem = _ann_problem(elt, local_types)
            if problem:
                return problem
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return (_ann_problem(node.left, local_types)
                or _ann_problem(node.right, local_types))
    return f"annotation {ast.unparse(node)!r} is not msgpack-native"


def _is_dataclass(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if "dataclass" in ast.unparse(target):
            return True
    return False


@register
class UnserializableProtocolField(Rule):
    id = "DL301"
    name = "unserializable-protocol-field"
    description = (
        "wire-protocol dataclass (defines to_wire/from_wire) with a field "
        "the msgpack codec cannot round-trip — tuples come back as lists, "
        "sets/ndarrays/datetimes fail to pack; the serde-derive class of "
        "bug Rust rejects at compile time")

    def applies(self, rel: str) -> bool:
        return "protocols" in rel.rsplit("/", 1)[-1]

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        local_types = {n.name for n in ast.walk(src.tree)
                       if isinstance(n, ast.ClassDef)}
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef) or not _is_dataclass(cls):
                continue
            methods = {m.name for m in cls.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            if not {"to_wire", "from_wire"} & methods:
                continue
            for stmt in cls.body:
                if not (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)):
                    continue
                problem = _ann_problem(stmt.annotation, local_types)
                if problem:
                    yield self.finding(
                        src, stmt,
                        f"field '{stmt.target.id}: "
                        f"{ast.unparse(stmt.annotation)}' of wire type "
                        f"{cls.name!r} won't survive a msgpack round-trip "
                        f"({problem}); use a native type or convert "
                        "explicitly in to_wire/from_wire")


# The accept/parse layer: files whose mention of a sampling field means
# "accepted", not "consumed".
_PARSE_LAYER = ("llm/validate.py", "llm/protocols.py",
                "llm/preprocessor.py", "llm/logits_processing.py")


@register
class UnconsumedSamplingField(ProjectRule):
    id = "DL302"
    name = "unconsumed-sampling-field"
    description = (
        "sampling/stop field accepted by validate.py and carried by "
        "SamplingOptions/StopConditions but never read outside the "
        "accept/parse layer: requests setting it pass validation and get "
        "silently wrong output (the min_p failure mode)")

    def check_project(self,
                      files: list[SourceFile]) -> Iterable[Finding]:
        validate = self._by_suffix(files, "llm/validate.py")
        protocols = self._by_suffix(files, "llm/protocols.py")
        if validate is None or protocols is None:
            return
        accepted = self._accepted_fields(validate)
        fields = self._carrier_fields(protocols)
        consumed: set[str] = set()
        for src in files:
            if src.rel.endswith(_PARSE_LAYER):
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Attribute):
                    consumed.add(node.attr)
        for name, node in sorted(fields.items()):
            if name in accepted and name not in consumed:
                yield self.finding(
                    protocols, node,
                    f"sampling field {name!r} is validated and parsed but "
                    "never consumed by the engine — requests setting it "
                    "silently get default behavior; wire it into "
                    "engine/scheduler.py or stop accepting it")

    @staticmethod
    def _by_suffix(files: list[SourceFile],
                   suffix: str) -> Optional[SourceFile]:
        for src in files:
            if src.rel.endswith(suffix):
                return src
        return None

    @staticmethod
    def _accepted_fields(src: SourceFile) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "_COMMON_FIELDS"
                            for t in node.targets)):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        out.add(sub.value)
        return out

    @staticmethod
    def _carrier_fields(src: SourceFile) -> dict[str, ast.AST]:
        out: dict[str, ast.AST] = {}
        for cls in ast.walk(src.tree):
            if isinstance(cls, ast.ClassDef) and cls.name in (
                    "SamplingOptions", "StopConditions"):
                for stmt in cls.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                            stmt.target, ast.Name):
                        out[stmt.target.id] = stmt
        return out


_METRIC_CTORS = {"Counter", "Gauge", "Histogram", "Summary", "Info"}
METRIC_PREFIX = "dynamo_"


@register
class MetricNamePrefix(Rule):
    id = "DL303"
    name = "metric-name-prefix"
    description = (
        "Prometheus metric whose name does not start with the project "
        "prefix 'dynamo_' (the reference centralizes naming in "
        "prometheus_names.rs); unprefixed metrics collide on shared "
        "scrape pages and break dashboard queries")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        imports_prom = any(
            (isinstance(n, ast.Import)
             and any(a.name.split(".")[0] == "prometheus_client"
                     for a in n.names))
            or (isinstance(n, ast.ImportFrom)
                and (n.module or "").split(".")[0] == "prometheus_client")
            for n in ast.walk(src.tree))
        if not imports_prom:
            return
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node).split(".")[-1] in _METRIC_CTORS
                    and len(node.args) >= 2
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            metric = node.args[0].value
            if not metric.startswith(METRIC_PREFIX):
                base = metric[5:] if metric.startswith("dynt_") else metric
                yield self.finding(
                    src, node,
                    f"metric {metric!r} violates the {METRIC_PREFIX!r} "
                    f"naming convention; rename to "
                    f"'{METRIC_PREFIX}{base}'")
