"""asyncio hazard rules: leaked tasks, blocked event loops, fake-async.

These are the runtime bugs Rust's ownership/Send bounds surface at
compile time in the reference stack; in Python they fail silently under
load (a dropped task is garbage-collected mid-flight, a blocking call
stalls every request on the loop)."""

from __future__ import annotations

import ast
from typing import Iterable

from .core import (
    Finding,
    ProjectRule,
    Rule,
    SourceFile,
    call_name,
    register,
    walk_skip_functions,
)

_SPAWN_CALLS = ("create_task", "ensure_future")


def _is_spawn(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    return name.split(".")[-1] in _SPAWN_CALLS


def _scopes(tree: ast.Module) -> Iterable[ast.AST]:
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


@register
class FireAndForgetTask(Rule):
    id = "DL101"
    name = "fire-and-forget-task"
    description = (
        "asyncio.create_task/ensure_future whose result is discarded (or "
        "bound to a name that is never read): the event loop holds only a "
        "weak reference, so the task can be garbage-collected mid-flight "
        "and its exceptions are never observed")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for scope in _scopes(src.tree):
            body = scope.body if hasattr(scope, "body") else []
            for node in walk_skip_functions(body):
                if isinstance(node, ast.Expr) and _is_spawn(node.value):
                    yield self.finding(
                        src, node,
                        f"result of {call_name(node.value)}() is discarded; "
                        "retain the task (self._tasks.append / a module "
                        "task set) and log its exception in a done "
                        "callback")
                elif (isinstance(node, ast.Assign)
                      and len(node.targets) == 1
                      and isinstance(node.targets[0], ast.Name)
                      and _is_spawn(node.value)
                      and not _read_after(scope, node)):
                    yield self.finding(
                        src, node,
                        f"task bound to {node.targets[0].id!r} is never "
                        "read afterwards — equivalent to a discard; retain "
                        "it somewhere the loop can't garbage-collect and "
                        "observe its exception")


def _read_after(scope: ast.AST, assign: ast.Assign) -> bool:
    """Is the bound name read AFTER this assignment? Flow-approximate:
    a Load counts if it appears later in the source, or if assignment
    and Load share an enclosing loop (wrap-around use on the next
    iteration). A Load only before a rebinding does not retain the NEW
    task bound here."""
    target = assign.targets[0].id
    loads = [n for n in ast.walk(scope)
             if isinstance(n, ast.Name) and n.id == target
             and isinstance(n.ctx, ast.Load)]
    if any(n.lineno > assign.lineno for n in loads):
        return True
    if not loads:
        return False
    for node in ast.walk(scope):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            members = set()
            for sub in ast.walk(node):
                members.add(id(sub))
            if id(assign) in members and any(id(n) in members
                                             for n in loads):
                return True
    return False


# Exact dotted call names that block the calling thread, with the async
# replacement the finding suggests.
_BLOCKING = {
    "time.sleep": "await asyncio.sleep(...)",
    "subprocess.run": "asyncio.create_subprocess_exec",
    "subprocess.call": "asyncio.create_subprocess_exec",
    "subprocess.check_call": "asyncio.create_subprocess_exec",
    "subprocess.check_output": "asyncio.create_subprocess_exec",
    "urllib.request.urlopen": "aiohttp.ClientSession",
    "socket.create_connection": "asyncio.open_connection",
    "socket.getaddrinfo": "loop.getaddrinfo",
}


@register
class BlockingCallInAsync(Rule):
    id = "DL102"
    name = "blocking-call-in-async"
    description = (
        "synchronous blocking call (time.sleep, subprocess, requests, "
        "sync sockets) inside an async def: stalls the entire event loop "
        "— every in-flight request on this loop waits behind it")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for scope in ast.walk(src.tree):
            if not isinstance(scope, ast.AsyncFunctionDef):
                continue
            for node in walk_skip_functions(scope.body):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name in _BLOCKING:
                    yield self.finding(
                        src, node,
                        f"{name}() blocks the event loop inside async def "
                        f"{scope.name!r}; use {_BLOCKING[name]} (or "
                        "asyncio.to_thread / run_in_executor)")
                elif name.startswith("requests."):
                    yield self.finding(
                        src, node,
                        f"{name}() is synchronous HTTP inside async def "
                        f"{scope.name!r}; use aiohttp (or asyncio."
                        "to_thread)")


def _has_await(body: list) -> bool:
    for node in walk_skip_functions(body):
        if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            if any(gen.is_async for gen in node.generators):
                return True
    return False


def _is_async_gen(body: list) -> bool:
    return any(isinstance(n, (ast.Yield, ast.YieldFrom))
               for n in walk_skip_functions(body))


def _is_stub(fn: ast.AsyncFunctionDef) -> bool:
    body = fn.body
    if body and isinstance(body[0], ast.Expr) and isinstance(
            body[0].value, ast.Constant) and isinstance(
            body[0].value.value, str):
        body = body[1:]  # docstring
    if not body:
        return True
    return all(
        isinstance(stmt, (ast.Pass, ast.Raise))
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)
        # `return None` / `return <const>` default impls of an async
        # interface — the await lives in the real implementations.
        or (isinstance(stmt, ast.Return)
            and (stmt.value is None
                 or isinstance(stmt.value, ast.Constant)))
        for stmt in body)


def _is_handler(fn: ast.AsyncFunctionDef) -> bool:
    """HTTP/RPC handlers must be async regardless of body: detect the
    conventional `request` parameter or a *Request annotation."""
    for arg in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        if arg.arg in ("request", "_request"):
            return True
        if arg.annotation is not None and \
                ast.unparse(arg.annotation).endswith("Request"):
            return True
    return False


@register
class AsyncWithoutAwait(ProjectRule):
    id = "DL103"
    name = "async-without-await"
    description = (
        "async def whose body never awaits: either it does synchronous "
        "work while holding the event loop (should be a plain def or use "
        "to_thread), or the async is vestigial and misleads callers into "
        "thinking it yields. Exempt: async generators, dunder protocol "
        "methods, handlers taking a `request` parameter, and methods "
        "whose name is implemented WITH an await elsewhere in the tree "
        "(duck-typed interface conformity)")

    def check_project(self, files: list) -> Iterable[Finding]:
        # Names implemented with a real await anywhere: an awaitless
        # sibling is conforming to that duck interface, not vestigial.
        awaiting_names: set[str] = set()
        candidates: list = []
        for src in files:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.AsyncFunctionDef):
                    continue
                if _has_await(node.body):
                    awaiting_names.add(node.name)
                    continue
                decorators = {call_name(d) if isinstance(d, ast.Call)
                              else ast.unparse(d)
                              for d in node.decorator_list}
                if any("abstractmethod" in d or "overload" in d
                       for d in decorators):
                    continue
                if (node.name.startswith("__")
                        or _is_stub(node)
                        or _is_async_gen(node.body)
                        or _is_handler(node)):
                    continue
                candidates.append((src, node))
        for src, node in candidates:
            if node.name in awaiting_names:
                continue
            yield self.finding(
                src, node,
                f"async def {node.name!r} never awaits (and no sibling "
                "implementation of that name does): make it a plain def, "
                "or route the blocking work through asyncio.to_thread")
