"""JAX hot-path rules: per-iteration host syncs and recompile hazards.

The TPU dispatch model rewards keeping the device queue full; a hidden
``.item()``/``np.asarray`` inside a serving-loop iteration serializes
host and device once per step, and a Python scalar leaking into a
``jax.jit`` signature either breaks tracing (used in control flow) or
compiles a fresh executable per distinct value (marked static)."""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .core import Finding, Rule, SourceFile, call_name, register

# Calls that force a device->host readback (or a host round-trip) when
# handed a device array.
_SYNC_NAMES = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
               "jax.device_get"}
_SYNC_METHODS = {"item", "block_until_ready"}


def _sync_call(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if name in _SYNC_NAMES:
        return name
    last = name.split(".")[-1]
    if last in _SYNC_METHODS and not node.args and not node.keywords:
        return f".{last}()"
    return None


@register
class HostSyncInLoop(Rule):
    id = "DL201"
    name = "host-sync-in-loop"
    description = (
        "host-device synchronization (.item(), np.asarray, "
        "jax.device_get, .block_until_ready()) inside a loop on an "
        "engine/kv_router hot path: one blocking round-trip per "
        "iteration; hoist a single batched transfer out of the loop or "
        "keep the values device-resident")

    def applies(self, rel: str) -> bool:
        parts = rel.split("/")
        return "engine" in parts or "kv_router" in parts

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        yield from self._visit(src, src.tree.body, in_loop=False)

    def _visit(self, src: SourceFile, nodes,
               in_loop: bool) -> Iterable[Finding]:
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # A nested callable runs when called, not where defined.
                body = node.body if isinstance(node.body, list) \
                    else [ast.Expr(node.body)]
                yield from self._visit(src, body, in_loop=False)
                continue
            if isinstance(node, ast.Call) and in_loop:
                name = _sync_call(node)
                if name:
                    yield self.finding(
                        src, node,
                        f"{name} inside a loop forces a host-device sync "
                        "every iteration; batch the readback outside the "
                        "loop (single transfer of a stacked result)")
            if isinstance(node, (ast.For, ast.AsyncFor)):
                # The iterable expression evaluates once, not per step.
                yield from self._visit(src, [node.iter], in_loop)
                yield from self._visit(src, node.body + node.orelse, True)
                continue
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                # First generator's iterable evaluates once; the element
                # expression and later generators run per item.
                per_iter = [node.generators[0].target]
                per_iter += node.generators[0].ifs
                for gen in node.generators[1:]:
                    per_iter += [gen.target, gen.iter] + gen.ifs
                if isinstance(node, ast.DictComp):
                    per_iter += [node.key, node.value]
                else:
                    per_iter.append(node.elt)
                yield from self._visit(src, [node.generators[0].iter],
                                       in_loop)
                yield from self._visit(src, per_iter, True)
                continue
            yield from self._visit(
                src, ast.iter_child_nodes(node),
                in_loop=in_loop or isinstance(node, ast.While))


_SCALARS = {"int", "float", "bool"}


def _static_params(call: ast.Call, params: list[str]) -> set[str]:
    """Parameter names declared static via static_argnums/static_argnames
    kwargs of a jax.jit(...) / partial(jax.jit, ...) call."""
    out: set[str] = set()
    for kw in call.keywords:
        vals: list = []
        if isinstance(kw.value, (ast.Tuple, ast.List, ast.Set)):
            vals = [e.value for e in kw.value.elts
                    if isinstance(e, ast.Constant)]
        elif isinstance(kw.value, ast.Constant):
            vals = [kw.value.value]
        if kw.arg == "static_argnames":
            out.update(v for v in vals if isinstance(v, str))
        elif kw.arg == "static_argnums":
            out.update(params[v] for v in vals
                       if isinstance(v, int) and v < len(params))
    return out


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The jax.jit(...) call carrying static_arg* kwargs, whether `node`
    is `jax.jit(...)` itself or `partial(jax.jit, ...)`."""
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if name in ("jax.jit", "jit"):
        return node
    if name in ("partial", "functools.partial") and node.args:
        inner = node.args[0]
        if isinstance(inner, (ast.Attribute, ast.Name)) and \
                ast.unparse(inner) in ("jax.jit", "jit"):
            return node
    return None


@register
class JitScalarArg(Rule):
    id = "DL202"
    name = "jit-scalar-arg"
    description = (
        "Python scalar (int/float/bool annotated) parameter in a "
        "jax.jit-traced signature without a static_argnums/"
        "static_argnames declaration: used in control flow or shapes it "
        "fails tracing, and every workaround recompiles per value — "
        "declare it static deliberately or pass an array")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        defs: dict[str, ast.FunctionDef] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef):
                defs[node.name] = node
        checked: set[str] = set()
        # Decorator form: @jax.jit / @partial(jax.jit, static_argnames=..)
        for fn in defs.values():
            for dec in fn.decorator_list:
                call = _jit_call(dec)
                if call is None and not (
                        isinstance(dec, (ast.Attribute, ast.Name))
                        and ast.unparse(dec) in ("jax.jit", "jit")):
                    continue
                checked.add(fn.name)
                yield from self._check_fn(src, fn, call)
                break
        # Call form: jax.jit(step, ...) where `step` is a local def.
        for node in ast.walk(src.tree):
            call = _jit_call(node)
            if (call is None or call is not node
                    or call_name(node) not in ("jax.jit", "jit")
                    or not node.args):
                continue
            target = node.args[0]
            if isinstance(target, ast.Name) and target.id in defs \
                    and target.id not in checked:
                checked.add(target.id)
                yield from self._check_fn(src, defs[target.id], node)

    def _check_fn(self, src: SourceFile, fn: ast.FunctionDef,
                  jit_call: Optional[ast.Call]) -> Iterable[Finding]:
        args = fn.args
        params = [a.arg for a in args.posonlyargs + args.args]
        static = _static_params(jit_call, params) if jit_call else set()
        for arg in (args.posonlyargs + args.args + args.kwonlyargs):
            if arg.arg in static or arg.arg == "self":
                continue
            ann = arg.annotation
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                ann = ast.Name(id=ann.value)  # "int" string annotation
            if isinstance(ann, ast.Name) and ann.id in _SCALARS:
                yield self.finding(
                    src, arg,
                    f"parameter '{arg.arg}: {ann.id}' of jit-traced "
                    f"{fn.name!r} is a Python scalar with no static "
                    "declaration; add it to static_argnames (accepting a "
                    "recompile per distinct value) or pass it as a jnp "
                    "array")
