"""Serving-loop overlap: the scheduler must keep fused decode blocks in
flight on the device while it admits arrivals and advances prefill —
the round-5 async serving loop (dispatch → prefill/admit → drain).

The reference bar is vLLM AsyncLLM's overlapped scheduling behind
components/src/dynamo/vllm/handlers.py:1498: scheduling work and device
stepping are never serialized per token. Here the equivalents are
(a) fused blocks dispatched while prefill work is pending
    (stats.fused_steps_with_prefill), and
(b) sequences admitted between a block's dispatch and its drain
    (stats.admitted_during_inflight),
with token streams byte-identical to per-token mode.
"""

import time
import uuid

import numpy as np

from dynamo_tpu.engine import InferenceScheduler, ModelRunner, RunnerConfig
from dynamo_tpu.llm.protocols import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import get_config
from dynamo_tpu.parallel import MeshConfig, make_mesh


def _runner():
    return ModelRunner(
        get_config("tiny-test"),
        RunnerConfig(page_size=4, num_pages=64, max_batch=4,
                     max_pages_per_seq=16, prefill_buckets=(8, 16, 32)),
        make_mesh(MeshConfig()),
        seed=0,
    )


class _Collect:
    def __init__(self):
        self.outputs = []

    def __call__(self, out: EngineOutput):
        self.outputs.append(out)

    def tokens(self):
        return [t for o in self.outputs for t in o.token_ids]

    @property
    def finish(self):
        for o in self.outputs:
            if o.finish_reason:
                return o.finish_reason
        return None


def _request(prompt, max_tokens):
    return PreprocessedRequest(
        request_id=uuid.uuid4().hex, token_ids=prompt,
        sampling=SamplingOptions(max_tokens=max_tokens, temperature=0.0),
        stop=StopConditions(ignore_eos=True),
    )


def _wait(collectors, deadline_s=120):
    deadline = time.time() + deadline_s
    while (any(c.finish is None for c in collectors)
           and time.time() < deadline):
        time.sleep(0.02)


PROMPT_A = list(range(1, 7))
PROMPT_B = list(range(3, 15))  # 12 tokens: >1 prefill chunk at bucket 8


def _reference_streams():
    """Per-token mode (block=1) streams for A-then-B with B arriving
    after A generated its first tokens."""
    runner = _runner()
    sched = InferenceScheduler(runner)
    sched.decode_block = 1
    sched.start()
    col_a, col_b = _Collect(), _Collect()
    try:
        sched.submit(_request(PROMPT_A, 24), col_a)
        deadline = time.time() + 60
        while len(col_a.tokens()) < 2 and time.time() < deadline:
            time.sleep(0.01)
        sched.submit(_request(PROMPT_B, 8), col_b)
        _wait([col_a, col_b])
    finally:
        sched.stop()
    assert col_a.finish == col_b.finish == "length"
    return col_a.tokens(), col_b.tokens()


def test_overlap_admission_and_prefill_with_inflight_blocks():
    ref_a, ref_b = _reference_streams()

    runner = _runner()
    sched = InferenceScheduler(runner)
    sched.decode_block = 4
    sched.decode_pipeline = 2
    col_a, col_b = _Collect(), _Collect()
    submitted_b = [False]

    # Inject B's arrival at DISPATCH time of one of A's fused blocks:
    # the block is then provably in flight (not yet drained) when the
    # mid-step admission pass picks B up — deterministic, no sleeps.
    real_decode_multi = runner.decode_multi

    def wrapped(*args, **kwargs):
        out = real_decode_multi(*args, **kwargs)
        if not submitted_b[0] and len(col_a.tokens()) >= 2:
            submitted_b[0] = True
            sched.submit(_request(PROMPT_B, 8), col_b)
        return out

    runner.decode_multi = wrapped
    sched.start()
    try:
        sched.submit(_request(PROMPT_A, 24), col_a)
        _wait([col_a])
        assert submitted_b[0], "B was never injected"
        _wait([col_b])
    finally:
        sched.stop()

    assert col_a.finish == col_b.finish == "length"
    # (a) B was admitted while a dispatched block had not been drained
    assert sched.stats.admitted_during_inflight >= 1
    # (b) fused blocks kept running while B's prefill was pending —
    # the round-4 all-or-nothing bail would have forced per-token here
    assert sched.stats.fused_steps_with_prefill >= 1
    # streams are byte-identical to per-token mode despite the overlap
    assert col_a.tokens() == ref_a
    assert col_b.tokens() == ref_b


def test_cross_sequence_prefill_batching_streams_identical():
    """Two prompts submitted together prefill their chunks in ONE
    batched dispatch (prefill_chunk_batch, the small-model MFU shape
    fix) — and the streams stay bit-identical to sequential submission
    (the sampler is row-independent)."""
    # Reference: each request alone on a fresh engine (order-free).
    refs = []
    for prompt, n in ((PROMPT_A, 8), (PROMPT_B, 8)):
        runner = _runner()
        sched = InferenceScheduler(runner)
        sched.decode_block = 1
        sched.start()
        col = _Collect()
        try:
            sched.submit(_request(prompt, n), col)
            _wait([col])
        finally:
            sched.stop()
        assert col.finish == "length"
        refs.append(col.tokens())

    runner = _runner()
    sched = InferenceScheduler(runner)
    sched.decode_block = 1
    col_a, col_b = _Collect(), _Collect()
    try:
        # Submit BEFORE starting the loop: both admit in the first
        # iteration, so their chunks deterministically share one
        # batched dispatch.
        sched.submit(_request(PROMPT_A, 8), col_a)
        sched.submit(_request(PROMPT_B, 8), col_b)
        sched.start()
        _wait([col_a, col_b])
    finally:
        sched.stop()
    assert col_a.finish == col_b.finish == "length"
    assert sched.stats.prefill_batched_steps >= 1, sched.stats
    assert col_a.tokens() == refs[0]
    assert col_b.tokens() == refs[1]


def test_fused_block_with_prefill_pending_streams_identical():
    """Two requests staggered so one decodes while the other prefills:
    block mode must fuse (not bail to per-token) and still match the
    per-token streams exactly."""
    ref_a, ref_b = _reference_streams()

    runner = _runner()
    sched = InferenceScheduler(runner)
    sched.decode_block = 4
    sched.decode_pipeline = 1
    sched.start()
    col_a, col_b = _Collect(), _Collect()
    try:
        sched.submit(_request(PROMPT_A, 24), col_a)
        deadline = time.time() + 60
        while len(col_a.tokens()) < 2 and time.time() < deadline:
            time.sleep(0.01)
        sched.submit(_request(PROMPT_B, 8), col_b)
        _wait([col_a, col_b])
    finally:
        sched.stop()
    assert col_a.finish == col_b.finish == "length"
    assert col_a.tokens() == ref_a
    assert col_b.tokens() == ref_b
    assert sched.stats.fused_steps_with_prefill >= 1
