"""Multimodal tests: vision encoder, media resolution, embedding splice in
the engine, E/P/D flow through encode workers, KV identity salting (ref
surface: sglang multimodal E/P/D + preprocessor/media.rs +
common/multimodal/async_encoder_cache.py)."""

import asyncio
import base64
import io
import uuid

import numpy as np
import pytest

import jax

from dynamo_tpu.engine import ModelRunner, RunnerConfig, TpuWorker
from dynamo_tpu.frontend import Frontend
from dynamo_tpu.llm.media import (
    MediaError,
    extract_image_parts,
    media_hash,
    resolve_image,
)
from dynamo_tpu.llm.protocols import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import get_config
from dynamo_tpu.models.vision import VisionEncoder, get_vision_config
from dynamo_tpu.multimodal import EmbeddingCache, EncodeWorker
from dynamo_tpu.parallel import MeshConfig, make_mesh
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


def _raw_tensor_url(side=32, seed=0) -> str:
    rng = np.random.default_rng(seed)
    arr = rng.random((side, side, 3), dtype=np.float32)
    b64 = base64.b64encode(arr.tobytes()).decode()
    return f"data:application/x-raw-tensor;base64,{b64}"


def _png_url(side=16, color=(255, 0, 0)) -> str:
    from PIL import Image

    img = Image.new("RGB", (side, side), color)
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return ("data:image/png;base64,"
            + base64.b64encode(buf.getvalue()).decode())


class TestMedia:
    def test_raw_tensor_roundtrip(self):
        url = _raw_tensor_url(side=32, seed=1)
        arr = resolve_image(url, 32)
        assert arr.shape == (32, 32, 3) and arr.dtype == np.float32

    def test_png_decode_and_resize(self):
        arr = resolve_image(_png_url(side=16), 32)
        assert arr.shape == (32, 32, 3)
        assert abs(float(arr[0, 0, 0]) - 1.0) < 1e-6  # red channel
        assert float(arr[0, 0, 1]) == 0.0

    def test_rejects_remote_and_garbage(self):
        with pytest.raises(MediaError, match="data: URLs"):
            resolve_image("https://example.com/x.png", 32)
        with pytest.raises(MediaError, match="base64"):
            resolve_image("data:image/png,notb64", 32)
        with pytest.raises(MediaError, match="decode"):
            resolve_image("data:image/png;base64,"
                          + base64.b64encode(b"junk").decode(), 32)

    def test_extract_image_parts(self):
        from dynamo_tpu.llm.media import IMAGE_MARKER

        messages = [
            {"role": "user", "content": [
                {"type": "text", "text": "look: "},
                {"type": "image_url", "image_url": {"url": "data:x"}},
                {"type": "text", "text": " thanks"},
            ]},
            {"role": "assistant", "content": "plain"},
        ]
        out, urls = extract_image_parts(messages)
        assert out[0]["content"] == f"look: {IMAGE_MARKER} thanks"
        assert out[1]["content"] == "plain"
        assert urls == ["data:x"]

    def test_literal_image_string_and_nuls_cannot_forge_markers(self):
        from dynamo_tpu.llm.media import IMAGE_MARKER

        messages = [{"role": "user", "content": [
            {"type": "text", "text": "what does <image> do? \x00image\x00"},
            {"type": "image_url", "image_url": {"url": "data:x"}},
        ]}]
        out, urls = extract_image_parts(messages)
        # exactly ONE marker (the real image); user text survives minus NULs
        assert out[0]["content"].count(IMAGE_MARKER) == 1
        assert "<image> do?" in out[0]["content"]
        assert len(urls) == 1

    def test_media_hash_stable(self):
        assert media_hash("abc") == media_hash("abc") != media_hash("abd")


class TestVisionEncoder:
    def test_shapes_and_determinism(self):
        enc = VisionEncoder(get_vision_config("tiny-vit-test"), seed=0)
        img = np.random.default_rng(0).random((32, 32, 3),
                                              dtype=np.float32)
        out1 = enc.encode(img)
        out2 = enc.encode(img)
        assert out1.shape == (1, 16, 64)  # n_patches x out_dim(=llm hidden)
        np.testing.assert_array_equal(out1, out2)
        other = enc.encode(np.zeros((32, 32, 3), np.float32))
        assert not np.allclose(out1, other)


class TestEmbeddingCache:
    def test_lru(self):
        cache = EmbeddingCache(capacity=2)
        a, b, c = (np.ones(1), np.ones(2), np.ones(3))
        cache.put(1, a)
        cache.put(2, b)
        assert cache.get(1) is a  # touches 1
        cache.put(3, c)  # evicts 2 (LRU)
        assert cache.get(2) is None
        assert cache.get(3) is c
        assert cache.hits == 2 and cache.misses == 1


def _mm_runner():
    return ModelRunner(
        get_config("tiny-mm-test"),
        RunnerConfig(page_size=4, num_pages=64, max_batch=4,
                     max_pages_per_seq=16, prefill_buckets=(8, 16, 32)),
        make_mesh(MeshConfig()),
        seed=0,
    )


class TestEmbedSplice:
    def test_image_embeddings_change_output(self):
        """Same placeholder tokens with different image embeddings must
        produce different streams (the splice actually feeds the model),
        and identical embeddings must reproduce exactly."""
        runner = _mm_runner()
        img_id = runner.model_config.image_token_id
        prompt = [1, 2, img_id, img_id, img_id, img_id, 3, 4]
        table = np.zeros(16, np.int32)
        table[:8] = np.arange(1, 9)
        rng = np.random.default_rng(0)
        e1 = rng.standard_normal((4, 64)).astype(np.float32)
        e2 = rng.standard_normal((4, 64)).astype(np.float32)

        def first_token(embeds):
            # fresh runner each time: the KV cache is donated + mutated
            r = _mm_runner()
            chunk = np.zeros((len(prompt), 64), np.float32)
            positions = [i for i, t in enumerate(prompt) if t == img_id]
            chunk[positions] = embeds
            return r.prefill_chunk(
                np.asarray(prompt, np.int32), 0, table, len(prompt),
                (0.0, 1.0, 0, 0), chunk_embeds=chunk)

        t1 = first_token(e1)
        t1b = first_token(e1)
        t2 = first_token(e2)
        assert t1 == t1b
        # Regression (positional-binding bug): through the RUNNER path —
        # no LoRA pack configured — strongly contrasting embeddings must
        # change the greedy token; if splicing were silently dropped both
        # would sample from identical logits.
        big = np.full((4, 64), 20.0, np.float32)
        neg = np.full((4, 64), -20.0, np.float32)
        assert first_token(big) != first_token(neg)
        # different images -> (almost surely) different greedy next token;
        # tolerate collision but require the logits path to differ via a
        # direct forward check
        from dynamo_tpu.models import forward, make_kv_cache

        cfg = runner.model_config
        kv = make_kv_cache(cfg, 64, 4)
        toks = np.asarray([prompt], np.int32)
        pos = np.arange(8, dtype=np.int32)[None, :]
        mask = (toks == img_id)

        def logits_for(e):
            extra = np.zeros((1, 8, 64), np.float32)
            extra[0, mask[0]] = e
            _, lg = forward(runner.params, cfg, toks, pos, kv,
                            np.asarray(table[None, :]),
                            np.asarray([8], np.int32),
                            extra_embeds=extra, extra_mask=mask)
            return np.asarray(lg)

        assert not np.allclose(logits_for(e1), logits_for(e2))

    def test_kv_salt_distinguishes_images(self):
        r1 = PreprocessedRequest(
            request_id="a", token_ids=[1, 2], sampling=SamplingOptions(),
            stop=StopConditions(), media_hashes=[111])
        r2 = PreprocessedRequest(
            request_id="b", token_ids=[1, 2], sampling=SamplingOptions(),
            stop=StopConditions(), media_hashes=[222])
        r3 = PreprocessedRequest(
            request_id="c", token_ids=[1, 2], sampling=SamplingOptions(),
            stop=StopConditions())
        assert r1.kv_salt() != r2.kv_salt()
        assert r3.kv_salt() is None
        # lora + media combine
        r4 = PreprocessedRequest(
            request_id="d", token_ids=[1], sampling=SamplingOptions(),
            stop=StopConditions(), lora_name="x", media_hashes=[111])
        assert r4.kv_salt() not in (r1.kv_salt(), None)

        def salt(hashes):
            return PreprocessedRequest(
                request_id="x", token_ids=[1],
                sampling=SamplingOptions(), stop=StopConditions(),
                media_hashes=hashes).kv_salt()

        # order-sensitive: swapped images must not share KV identity
        assert salt([111, 222]) != salt([222, 111])
        # repeated images must not cancel to the unsalted identity
        assert salt([111, 111]) != salt([222, 222])
        assert salt([111, 111]) is not None


def _cfg(cluster):
    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = cluster
    cfg.request_plane = "tcp"
    cfg.tcp_host = "127.0.0.1"
    cfg.event_plane = "mem"
    cfg.system_enabled = False
    cfg.lease_ttl_secs = 1.0
    return cfg


class TestMultimodalE2E:
    def test_epd_flow_through_frontend(self, run):
        """Full E/P/D: chat request with an image -> frontend expands
        placeholders -> MultimodalEngine encodes via the encoder pool ->
        worker splices embeddings -> tokens stream back. Second request
        with the same image hits the encoder cache."""

        async def body():
            import aiohttp

            cluster = uuid.uuid4().hex
            rt_w = await DistributedRuntime(_cfg(cluster)).start()
            worker = TpuWorker(
                rt_w, model_name="tiny-mm-test",
                runner_config=RunnerConfig(
                    page_size=4, num_pages=64, max_batch=4,
                    max_pages_per_seq=32, prefill_buckets=(8, 16, 32, 64)),
                warmup=False,
            )
            await worker.start()
            rt_e = await DistributedRuntime(_cfg(cluster)).start()
            encoder = EncodeWorker(rt_e, "tiny-mm-test",
                                   vision_preset="tiny-vit-test")
            await encoder.start()
            rt_f = await DistributedRuntime(_cfg(cluster)).start()
            frontend = Frontend(rt_f, host="127.0.0.1", port=0)
            await frontend.start()
            for _ in range(100):
                if frontend.manager.get("tiny-mm-test") is not None:
                    break
                await asyncio.sleep(0.05)
            entry = frontend.manager.get("tiny-mm-test")
            assert entry.card.runtime_config["multimodal"][
                "n_image_tokens"] == 16

            url = _raw_tensor_url(side=32, seed=7)
            payload = {
                "model": "tiny-mm-test",
                "messages": [{"role": "user", "content": [
                    {"type": "text", "text": "describe "},
                    {"type": "image_url", "image_url": {"url": url}},
                ]}],
                "max_tokens": 4,
                "temperature": 0,
            }
            base = f"http://127.0.0.1:{frontend.port}"
            async with aiohttp.ClientSession() as session:
                async with session.post(f"{base}/v1/chat/completions",
                                        json=payload) as resp:
                    assert resp.status == 200, await resp.text()
                    data = await resp.json()
                    assert data["choices"][0]["finish_reason"] == "length"
                    # prompt includes the 16 expanded placeholder tokens
                    assert data["usage"]["prompt_tokens"] > 16
                first_text = data["choices"][0]["message"]["content"]
                # same request again: encoder cache hit, same greedy output
                async with aiohttp.ClientSession() as s2, s2.post(
                        f"{base}/v1/chat/completions", json=payload) as resp:
                    data2 = await resp.json()
                assert data2["choices"][0]["message"]["content"] == first_text
                assert encoder.cache.hits >= 1

                # different image -> different KV identity; request succeeds
                payload2 = {**payload, "messages": [
                    {"role": "user", "content": [
                        {"type": "text", "text": "describe "},
                        {"type": "image_url",
                         "image_url": {"url": _raw_tensor_url(side=32,
                                                              seed=9)}},
                    ]}]}
                async with aiohttp.ClientSession() as s3, s3.post(
                        f"{base}/v1/chat/completions", json=payload2) as resp:
                    assert resp.status == 200

            await frontend.close()
            await rt_f.shutdown()
            await encoder.close()
            await rt_e.shutdown()
            await worker.close()
            await rt_w.shutdown()

        run(body(), timeout=240)

    def test_no_encoder_pool_is_explicit_error(self, run):
        async def body():
            import aiohttp

            cluster = uuid.uuid4().hex
            rt_w = await DistributedRuntime(_cfg(cluster)).start()
            worker = TpuWorker(
                rt_w, model_name="tiny-mm-test",
                runner_config=RunnerConfig(
                    page_size=4, num_pages=64, max_batch=4,
                    max_pages_per_seq=32, prefill_buckets=(8, 16, 32, 64)),
                warmup=False,
            )
            await worker.start()
            rt_f = await DistributedRuntime(_cfg(cluster)).start()
            frontend = Frontend(rt_f, host="127.0.0.1", port=0)
            await frontend.start()
            for _ in range(100):
                if frontend.manager.get("tiny-mm-test") is not None:
                    break
                await asyncio.sleep(0.05)
            payload = {
                "model": "tiny-mm-test",
                "messages": [{"role": "user", "content": [
                    {"type": "image_url",
                     "image_url": {"url": _raw_tensor_url()}},
                ]}],
                "max_tokens": 2,
            }
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        f"http://127.0.0.1:{frontend.port}"
                        "/v1/chat/completions", json=payload) as resp:
                    assert resp.status == 502
                    body_ = await resp.json()
                    assert "encoder" in body_["error"]["message"]
            await frontend.close()
            await rt_f.shutdown()
            await worker.close()
            await rt_w.shutdown()

        run(body(), timeout=180)

    def test_text_only_model_rejects_images(self, run):
        async def body():
            import aiohttp

            cluster = uuid.uuid4().hex
            rt_w = await DistributedRuntime(_cfg(cluster)).start()
            worker = TpuWorker(
                rt_w, model_name="tiny-test",
                runner_config=RunnerConfig(
                    page_size=4, num_pages=64, max_batch=4,
                    max_pages_per_seq=16, prefill_buckets=(8, 16, 32)),
                warmup=False,
            )
            await worker.start()
            rt_f = await DistributedRuntime(_cfg(cluster)).start()
            frontend = Frontend(rt_f, host="127.0.0.1", port=0)
            await frontend.start()
            for _ in range(100):
                if frontend.manager.get("tiny-test") is not None:
                    break
                await asyncio.sleep(0.05)
            payload = {
                "model": "tiny-test",
                "messages": [{"role": "user", "content": [
                    {"type": "image_url",
                     "image_url": {"url": _raw_tensor_url()}},
                ]}],
                "max_tokens": 2,
            }
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        f"http://127.0.0.1:{frontend.port}"
                        "/v1/chat/completions", json=payload) as resp:
                    assert resp.status == 400
                    body_ = await resp.json()
                    assert "image input" in body_["error"]["message"]
            await frontend.close()
            await rt_f.shutdown()
            await worker.close()
            await rt_w.shutdown()

        run(body(), timeout=180)
