"""Pallas kernels vs XLA reference oracle (interpret mode on CPU).

Mirrors the reference's kernel test strategy (CUDA kernels tested against
torch reference impls in lib/kvbm-kernels); here the oracle is
`paged_attention_xla` and pure-numpy layouts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models import ModelConfig, make_kv_cache
from dynamo_tpu.models.transformer import paged_attention_xla, write_kv_pages
from dynamo_tpu.ops import (
    gather_kv_blocks,
    paged_attention,
    paged_decode_attention,
    scatter_kv_blocks,
    swap_kv_blocks,
)
from dynamo_tpu.ops.layout import (
    layered_to_universal,
    nhd_to_universal,
    reshard_heads,
    universal_to_layered,
    universal_to_nhd,
)
from jax_capabilities import (
    requires_pallas_compiler_params,
    requires_shard_map,
)


def _make_case(b=4, qh=8, kh=4, hd=64, ps=8, n_pages=32, max_pages=6,
               seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, qh, hd)), dtype)
    k_pages = jnp.asarray(rng.normal(size=(n_pages, ps, kh, hd)), dtype)
    v_pages = jnp.asarray(rng.normal(size=(n_pages, ps, kh, hd)), dtype)
    # distinct pages per sequence, page 0 reserved
    ids = rng.permutation(n_pages - 1)[: b * max_pages].reshape(b, max_pages)
    block_tables = jnp.asarray(ids + 1, jnp.int32) % n_pages
    kv_lens = jnp.asarray(rng.integers(1, ps * max_pages, size=b), jnp.int32)
    return q, k_pages, v_pages, block_tables, kv_lens


def _oracle(q, k_pages, v_pages, block_tables, kv_lens):
    """Dense masked attention over gathered pages (fp32)."""
    b, qh, hd = q.shape
    _, ps, kh, _ = k_pages.shape
    group = qh // kh
    ctx = block_tables.shape[1] * ps
    k = np.asarray(k_pages)[np.asarray(block_tables)].reshape(b, ctx, kh, hd)
    v = np.asarray(v_pages)[np.asarray(block_tables)].reshape(b, ctx, kh, hd)
    qn = np.asarray(q, np.float32).reshape(b, kh, group, hd)
    scores = np.einsum("bkgh,bskh->bkgs", qn,
                       k.astype(np.float32)) / np.sqrt(hd)
    mask = np.arange(ctx)[None, :] < np.asarray(kv_lens)[:, None]
    scores = np.where(mask[:, None, None, :], scores, -np.inf)
    scores -= scores.max(axis=-1, keepdims=True)
    probs = np.exp(scores)
    probs /= probs.sum(axis=-1, keepdims=True)
    out = np.einsum("bkgs,bskh->bkgh", probs, v.astype(np.float32))
    return out.reshape(b, qh, hd)


@requires_pallas_compiler_params
class TestPagedDecodeAttention:
    def test_matches_oracle_fp32(self):
        q, kp, vp, bt, kl = _make_case()
        got = paged_decode_attention(q, kp, vp, bt, kl, interpret=True)
        want = _oracle(q, kp, vp, bt, kl)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5,
                                   atol=2e-5)

    def test_matches_oracle_bf16(self):
        q, kp, vp, bt, kl = _make_case(dtype=jnp.bfloat16)
        got = paged_decode_attention(q, kp, vp, bt, kl, interpret=True)
        want = _oracle(q, kp, vp, bt, kl)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), want, rtol=5e-2, atol=5e-2
        )

    def test_mha_group1(self):
        q, kp, vp, bt, kl = _make_case(qh=4, kh=4)
        got = paged_decode_attention(q, kp, vp, bt, kl, interpret=True)
        want = _oracle(q, kp, vp, bt, kl)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5,
                                   atol=2e-5)

    def test_short_sequences(self):
        q, kp, vp, bt, kl = _make_case()
        kl = jnp.ones_like(kl)  # every sequence sees exactly 1 token
        got = paged_decode_attention(q, kp, vp, bt, kl, interpret=True)
        want = _oracle(q, kp, vp, bt, kl)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5,
                                   atol=2e-5)

    def test_matches_xla_attention_fn_path(self):
        """The attention_fn wrapper agrees with the model's XLA path on a
        real paged cache written through write_kv_pages."""
        config = ModelConfig(name="t", vocab_size=64, hidden=32, n_layers=1,
                             n_q_heads=4, n_kv_heads=2, head_dim=16,
                             mlp_hidden=64, dtype="float32")
        ps, n_pages, max_pages, b, t = 4, 16, 4, 2, 8
        rng = np.random.default_rng(1)
        kv = make_kv_cache(config, n_pages, ps, "float32")
        bt = jnp.asarray(
            rng.permutation(n_pages - 1)[: b * max_pages].reshape(
                b, max_pages) + 1, jnp.int32) % n_pages
        k = jnp.asarray(rng.normal(size=(b, t, 2, 16)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, t, 2, 16)), jnp.float32)
        positions = jnp.tile(jnp.arange(t)[None], (b, 1))
        valid = jnp.ones((b, t), bool)
        kv = write_kv_pages(kv, 0, k, v, bt, positions, valid)

        q = jnp.asarray(rng.normal(size=(b, 1, 4, 16)), jnp.float32)
        qpos = jnp.full((b, 1), t - 1, jnp.int32)
        kv_lens = jnp.full((b,), t, jnp.int32)
        got = paged_attention(q, kv, 0, bt, qpos, kv_lens, interpret=True)
        want = paged_attention_xla(q, kv, 0, bt, qpos, kv_lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


class TestPagedDecodeAttentionPartial:
    """The unnormalized flash-partials kernel (acc, m, l over the paged
    HISTORY) vs the dense oracle: acc / l must equal masked softmax
    attention, and the partials must be foldable (the contract the
    deferred-write combine in forward_decode relies on)."""

    @staticmethod
    def _guard():
        from dynamo_tpu.ops.paged_attention import pltpu

        if not hasattr(pltpu, "CompilerParams"):
            pytest.skip("this jax predates pltpu.CompilerParams "
                        "(kernel tests run where the env is current)")

    def test_normalized_partials_match_oracle(self):
        self._guard()
        from dynamo_tpu.ops.paged_attention import (
            paged_decode_attention_partial,
        )

        q, kp, vp, bt, kl = _make_case()
        acc, m, l = paged_decode_attention_partial(q, kp, vp, bt, kl,
                                                   interpret=True)
        b, qh, hd = q.shape
        kh = kp.shape[2]
        out = (np.asarray(acc) / np.asarray(l)[..., None]).reshape(
            b, qh, hd)
        want = _oracle(q, kp, vp, bt, kl)
        np.testing.assert_allclose(out, want, rtol=2e-5, atol=2e-5)

    def test_partials_fold_across_a_page_split(self):
        """m is the row max and l the exp-sum at that max: the standard
        flash rescale over the partials of the first two pages and the
        last two pages must reproduce attention over the full history —
        the exact combine forward_decode's deferred-write path runs."""
        self._guard()
        from dynamo_tpu.ops.paged_attention import (
            paged_decode_attention_partial,
        )

        ps = 8
        q, kp, vp, bt, kl = _make_case(max_pages=4, ps=ps)
        lo_len = np.minimum(np.asarray(kl), 2 * ps)
        hi_len = np.clip(np.asarray(kl) - 2 * ps, 0, 2 * ps)
        a1, m1, l1 = paged_decode_attention_partial(
            q, kp, vp, bt[:, :2], jnp.asarray(lo_len, jnp.int32),
            interpret=True)
        a2, m2, l2 = paged_decode_attention_partial(
            q, kp, vp, bt[:, 2:], jnp.asarray(hi_len, jnp.int32),
            interpret=True)
        a1, m1, l1 = (np.asarray(x, np.float64) for x in (a1, m1, l1))
        a2, m2, l2 = (np.asarray(x, np.float64) for x in (a2, m2, l2))
        m12 = np.maximum(m1, m2)
        c1 = np.exp(m1 - m12)
        c2 = np.exp(m2 - m12)
        acc = a1 * c1[..., None] + a2 * c2[..., None]
        tot = l1 * c1 + l2 * c2
        want = _oracle(q, kp, vp, bt, kl)
        b, qh, hd = q.shape
        got = (acc / tot[..., None]).reshape(b, qh, hd)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@requires_pallas_compiler_params
class TestPagedAttentionDecodeFused:
    """The deferred-write Pallas path (history partials + in-register
    current token) vs paged_attention_decode_xla as oracle."""

    def _case(self, b=4, qh=8, kh=4, hd=64, ps=8, n_pages=32, max_pages=6,
              seed=3, dtype=jnp.float32, min_len=1):
        rng = np.random.default_rng(seed)
        L = 2
        kv_cache = jnp.asarray(
            rng.normal(size=(L, 2, n_pages, ps, kh, hd)), dtype)
        q = jnp.asarray(rng.normal(size=(b, 1, qh, hd)), dtype)
        k_cur = jnp.asarray(rng.normal(size=(b, 1, kh, hd)), dtype)
        v_cur = jnp.asarray(rng.normal(size=(b, 1, kh, hd)), dtype)
        ids = rng.permutation(n_pages - 1)[: b * max_pages] \
            .reshape(b, max_pages)
        bt = jnp.asarray(ids + 1, jnp.int32) % n_pages
        # kv_lens INCLUDE the current token
        kl = jnp.asarray(
            rng.integers(min_len, ps * max_pages, size=b), jnp.int32)
        return q, kv_cache, bt, kl, k_cur, v_cur

    def test_matches_xla_deferred_path(self):
        from dynamo_tpu.models.transformer import paged_attention_decode_xla
        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_fused,
        )

        q, kv, bt, kl, kc, vc = self._case()
        for layer in (0, 1):
            got = paged_attention_decode_fused(
                q, kv, layer, bt, kl, kc, vc, interpret=True)
            want = paged_attention_decode_xla(q, kv, layer, bt, kl, kc, vc)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-5)

    def test_first_token_no_history(self):
        """kv_len == 1: only the in-register current token attends (the
        kernel's history pass sees zero tokens -> m=-inf branch)."""
        from dynamo_tpu.models.transformer import paged_attention_decode_xla
        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_fused,
        )

        q, kv, bt, kl, kc, vc = self._case()
        kl = jnp.ones_like(kl)
        got = paged_attention_decode_fused(
            q, kv, 0, bt, kl, kc, vc, interpret=True)
        want = paged_attention_decode_xla(q, kv, 0, bt, kl, kc, vc)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        # degenerate case is exactly v_cur
        np.testing.assert_allclose(np.asarray(got)[:, 0, 0],
                                   np.asarray(vc)[:, 0, 0],
                                   rtol=2e-5, atol=2e-5)

    def test_bf16(self):
        from dynamo_tpu.models.transformer import paged_attention_decode_xla
        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_fused,
        )

        q, kv, bt, kl, kc, vc = self._case(dtype=jnp.bfloat16)
        got = paged_attention_decode_fused(
            q, kv, 0, bt, kl, kc, vc, interpret=True)
        want = paged_attention_decode_xla(q, kv, 0, bt, kl, kc, vc)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=5e-2, atol=5e-2)

    def test_forward_decode_with_fused_kernel_matches_xla(self):
        """Whole forward_decode equality: kernel path vs XLA path on a
        real model config and populated cache."""
        import functools

        from dynamo_tpu.models import get_config, init_params, make_kv_cache
        from dynamo_tpu.models.transformer import forward_decode
        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_fused,
        )

        cfg = get_config("tiny-test")
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        kv = make_kv_cache(cfg, 32, 4)
        kv = jnp.asarray(rng.normal(size=kv.shape), kv.dtype)
        b = 2
        bt = jnp.asarray([[1, 2, 3, 0], [4, 5, 6, 0]], jnp.int32)
        kv_lens = jnp.asarray([7, 11], jnp.int32)
        tokens = jnp.asarray([3, 5], jnp.int32)
        positions = kv_lens - 1
        active = jnp.ones((b,), bool)

        kv_x, logits_x = forward_decode(params, cfg, tokens, positions, kv,
                                        bt, kv_lens, active)
        kv_p, logits_p = forward_decode(
            params, cfg, tokens, positions, kv, bt, kv_lens, active,
            decode_attention_fn=functools.partial(
                paged_attention_decode_fused, interpret=True))
        np.testing.assert_allclose(np.asarray(logits_p),
                                   np.asarray(logits_x),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(kv_p, np.float32), np.asarray(kv_x, np.float32),
            rtol=1e-5, atol=1e-5)


@requires_pallas_compiler_params
class TestPagedAttentionDecodePool:
    """The production TPU decode path: whole-pool chunked-DMA kernel
    (paged_decode_attention_pool + combine) vs paged_attention_decode_xla
    as oracle, across layers, chunk sizes, history lengths, and dtypes."""

    def _case(self, b=4, qh=8, kh=4, hd=64, ps=8, n_pages=32, max_pages=6,
              seed=5, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        L = 2
        kv = jnp.asarray(rng.normal(size=(L, 2, n_pages, ps, kh, hd)),
                         dtype)
        q = jnp.asarray(rng.normal(size=(b, 1, qh, hd)), dtype)
        kc = jnp.asarray(rng.normal(size=(b, 1, kh, hd)), dtype)
        vc = jnp.asarray(rng.normal(size=(b, 1, kh, hd)), dtype)
        ids = rng.permutation(n_pages - 1)[: b * max_pages] \
            .reshape(b, max_pages)
        bt = jnp.asarray(ids + 1, jnp.int32) % n_pages
        kl = jnp.asarray(rng.integers(1, ps * max_pages, size=b),
                         jnp.int32)
        return q, kv, bt, kl, kc, vc

    @pytest.mark.parametrize("ppc", [1, 2, 3, 6])
    def test_matches_xla_across_chunk_sizes(self, ppc):
        from dynamo_tpu.models.transformer import paged_attention_decode_xla
        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_pool,
        )

        q, kv, bt, kl, kc, vc = self._case()
        for layer in (0, 1):
            got = paged_attention_decode_pool(
                q, kv, layer, bt, kl, kc, vc, pages_per_chunk=ppc,
                interpret=True)
            want = paged_attention_decode_xla(q, kv, layer, bt, kl, kc, vc)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-5)

    def test_zero_history_and_mixed_lengths(self):
        """kv_len == 1 slots (no history: kernel never DMAs for them) mixed
        with long ones — the next_active skip logic must not corrupt
        neighbours."""
        from dynamo_tpu.models.transformer import paged_attention_decode_xla
        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_pool,
        )

        q, kv, bt, kl, kc, vc = self._case()
        kl = jnp.asarray([1, 47, 1, 13], jnp.int32)
        got = paged_attention_decode_pool(q, kv, 0, bt, kl, kc, vc,
                                          pages_per_chunk=2, interpret=True)
        want = paged_attention_decode_xla(q, kv, 0, bt, kl, kc, vc)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        # zero-history rows degenerate to exactly v_cur
        for row in (0, 2):
            np.testing.assert_allclose(
                np.asarray(got)[row, 0].reshape(4, 2, -1)[:, 0],
                np.asarray(vc)[row, 0], rtol=2e-5, atol=2e-5)

    def test_bf16(self):
        from dynamo_tpu.models.transformer import paged_attention_decode_xla
        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_pool,
        )

        q, kv, bt, kl, kc, vc = self._case(dtype=jnp.bfloat16)
        got = paged_attention_decode_pool(q, kv, 1, bt, kl, kc, vc,
                                          pages_per_chunk=3, interpret=True)
        want = paged_attention_decode_xla(q, kv, 1, bt, kl, kc, vc)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=5e-2, atol=5e-2)

    def test_forward_decode_with_pool_kernel_matches_xla(self):
        """Whole forward_decode equality on a real model config — the
        integration the runner wires on TPU."""
        import functools

        from dynamo_tpu.models import get_config, init_params, make_kv_cache
        from dynamo_tpu.models.transformer import forward_decode
        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_pool,
        )

        cfg = get_config("tiny-test")
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        kv = make_kv_cache(cfg, 32, 4)
        kv = jnp.asarray(rng.normal(size=kv.shape), kv.dtype)
        bt = jnp.asarray([[1, 2, 3, 0], [4, 5, 6, 0]], jnp.int32)
        kv_lens = jnp.asarray([7, 11], jnp.int32)
        tokens = jnp.asarray([3, 5], jnp.int32)
        active = jnp.ones((2,), bool)

        kv_x, logits_x = forward_decode(params, cfg, tokens, kv_lens - 1,
                                        kv, bt, kv_lens, active)
        kv_p, logits_p = forward_decode(
            params, cfg, tokens, kv_lens - 1, kv, bt, kv_lens, active,
            decode_attention_fn=functools.partial(
                paged_attention_decode_pool, pages_per_chunk=2,
                interpret=True))
        np.testing.assert_allclose(np.asarray(logits_p),
                                   np.asarray(logits_x),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(kv_p, np.float32), np.asarray(kv_x, np.float32),
            rtol=1e-5, atol=1e-5)


@requires_pallas_compiler_params
@requires_shard_map
class TestPagedAttentionDecodePoolTp:
    """The pool kernel under tensor parallelism (VERDICT r2 weak #3):
    shard_map over the kv-head axis, each shard streaming its local pool
    slice. Oracle = single-device kernel / XLA path on the same data."""

    def _mesh(self, tp):
        from dynamo_tpu.parallel import MeshConfig, make_mesh

        return make_mesh(MeshConfig(tp=tp))

    @pytest.mark.parametrize("tp", [2, 4])
    def test_matches_xla_oracle(self, tp):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dynamo_tpu.models.transformer import paged_attention_decode_xla
        from dynamo_tpu.ops.paged_attention import (
            make_paged_attention_decode_pool_tp,
        )

        mesh = self._mesh(tp)
        rng = np.random.default_rng(11)
        b, qh, kh, hd, ps, n_pages, max_pages = 4, 8, 4, 64, 8, 32, 6
        kv = jnp.asarray(rng.normal(size=(2, 2, n_pages, ps, kh, hd)),
                         jnp.float32)
        kv = jax.device_put(kv, NamedSharding(
            mesh, P(None, None, None, None, "tp", None)))
        q = jnp.asarray(rng.normal(size=(b, 1, qh, hd)), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(b, 1, kh, hd)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(b, 1, kh, hd)), jnp.float32)
        ids = rng.permutation(n_pages - 1)[: b * max_pages] \
            .reshape(b, max_pages)
        bt = jnp.asarray(ids + 1, jnp.int32) % n_pages
        kl = jnp.asarray([1, 13, 47, 30], jnp.int32)

        fn = make_paged_attention_decode_pool_tp(mesh, pages_per_chunk=2,
                                                 interpret=True)
        for layer in (0, 1):
            got = fn(q, kv, layer, bt, kl, kc, vc)
            want = paged_attention_decode_xla(q, kv, layer, bt, kl, kc, vc)
            assert got.shape == want.shape == (b, 1, qh, hd)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-5)

    def test_forward_decode_tp2_matches_xla(self):
        """Whole forward_decode under a tp=2 mesh with the sharded kernel —
        the exact integration the runner wires on multi-chip TPU."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dynamo_tpu.models import get_config, init_params
        from dynamo_tpu.models.transformer import forward_decode
        from dynamo_tpu.ops.paged_attention import (
            make_paged_attention_decode_pool_tp,
        )
        from dynamo_tpu.parallel import kv_cache_sharding, param_shardings
        from dynamo_tpu.models import param_axes

        mesh = self._mesh(2)
        cfg = get_config("tiny-test")
        params = init_params(jax.random.PRNGKey(0), cfg)
        params = jax.tree.map(jax.device_put, params,
                              param_shardings(mesh, param_axes(cfg)))
        rng = np.random.default_rng(0)
        kv = jnp.asarray(rng.normal(size=(cfg.n_layers, 2, 32, 4,
                                          cfg.n_kv_heads, cfg.head_dim)),
                         jnp.dtype(cfg.dtype))
        kv = jax.device_put(kv, kv_cache_sharding(mesh))
        bt = jnp.asarray([[1, 2, 3, 0], [4, 5, 6, 0]], jnp.int32)
        kv_lens = jnp.asarray([7, 11], jnp.int32)
        tokens = jnp.asarray([3, 5], jnp.int32)
        active = jnp.ones((2,), bool)

        kv_x, logits_x = forward_decode(params, cfg, tokens, kv_lens - 1,
                                        kv, bt, kv_lens, active)
        kv_p, logits_p = forward_decode(
            params, cfg, tokens, kv_lens - 1, kv, bt, kv_lens, active,
            decode_attention_fn=make_paged_attention_decode_pool_tp(
                mesh, pages_per_chunk=2, interpret=True))
        np.testing.assert_allclose(np.asarray(logits_p),
                                   np.asarray(logits_x),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(kv_p, np.float32), np.asarray(kv_x, np.float32),
            rtol=1e-5, atol=1e-5)

    def test_runner_selects_tp_kernel(self, monkeypatch):
        """The gate: DYNT_ATTENTION=pallas on a tp-only mesh selects the
        sharded kernel (was: disabled on every multi-device mesh), and the
        runner's decode output matches its own XLA-mode twin."""
        from dynamo_tpu.engine.model_runner import (
            ModelRunner,
            RunnerConfig,
            _default_decode_attention_fn,
        )
        from dynamo_tpu.models import get_config
        from dynamo_tpu.parallel import MeshConfig, make_mesh

        mesh = self._mesh(2)
        monkeypatch.setenv("DYNT_ATTENTION", "pallas")
        assert _default_decode_attention_fn(mesh) is not None
        # dp>1 mesh still falls back to XLA
        assert _default_decode_attention_fn(
            make_mesh(MeshConfig(dp=2, tp=2))) is None

        rc = RunnerConfig(page_size=4, num_pages=32, max_batch=2,
                          max_pages_per_seq=8, prefill_buckets=(16,))
        r_pallas = ModelRunner(get_config("tiny-test"), rc, mesh, seed=0)
        assert r_pallas._decode_attention_fn is not None
        monkeypatch.setenv("DYNT_ATTENTION", "xla")
        r_xla = ModelRunner(get_config("tiny-test"), rc, self._mesh(2),
                            seed=0)
        table = np.zeros(8, np.int32)
        table[:4] = np.arange(1, 5)
        prompt = np.arange(1, 11, dtype=np.int32)
        t1 = r_pallas.prefill_chunk(prompt, 0, table, 10, (0.0, 1.0, 0, 0))
        t2 = r_xla.prefill_chunk(prompt, 0, table, 10, (0.0, 1.0, 0, 0))
        assert t1 == t2
        args = ([t1], [10], table[None, :], [11], [True],
                np.zeros(1, np.float32), np.ones(1, np.float32),
                np.zeros(1, np.int32), np.zeros(1, np.uint32))
        n1 = r_pallas.decode(*[np.asarray(a) for a in args])
        n2 = r_xla.decode(*[np.asarray(a) for a in args])
        assert int(n1[0]) == int(n2[0])


class TestBlockCopy:
    def _cache(self, L=2, P=16, ps=4, kh=2, hd=8, seed=0):
        rng = np.random.default_rng(seed)
        return jnp.asarray(
            rng.normal(size=(L, 2, P, ps, kh, hd)), jnp.float32
        )

    def test_gather_scatter_roundtrip(self):
        kv = self._cache()
        ids = jnp.asarray([3, 7, 1], jnp.int32)
        bundle = gather_kv_blocks(kv, ids)
        assert bundle.shape == (3, 2, 2, 4, 2, 8)
        kv2 = jnp.zeros_like(kv)
        kv2 = scatter_kv_blocks(kv2, ids, bundle)
        np.testing.assert_array_equal(
            np.asarray(kv2[:, :, np.asarray(ids)]),
            np.asarray(kv[:, :, np.asarray(ids)]),
        )

    def test_swap(self):
        kv = self._cache()
        orig = np.asarray(kv)
        out = swap_kv_blocks(kv, jnp.asarray([2, 5], jnp.int32),
                             jnp.asarray([9, 11], jnp.int32))
        np.testing.assert_array_equal(np.asarray(out[:, :, 9]), orig[:, :, 2])
        np.testing.assert_array_equal(np.asarray(out[:, :, 11]), orig[:, :, 5])


class TestLayout:
    def test_universal_layered_roundtrip(self):
        rng = np.random.default_rng(0)
        blocks = jnp.asarray(rng.normal(size=(3, 2, 2, 4, 2, 8)), jnp.float32)
        back = layered_to_universal(universal_to_layered(blocks))
        np.testing.assert_array_equal(np.asarray(back), np.asarray(blocks))

    def test_nhd_roundtrip(self):
        rng = np.random.default_rng(0)
        blocks = jnp.asarray(rng.normal(size=(3, 2, 2, 4, 2, 8)), jnp.float32)
        back = nhd_to_universal(universal_to_nhd(blocks), kv_heads=2)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(blocks))

    def test_reshard_heads(self):
        rng = np.random.default_rng(0)
        full = jnp.asarray(rng.normal(size=(2, 1, 2, 4, 8, 4)), jnp.float32)
        # tp=2 -> tp=4: dst shard 1 owns heads [2:4]
        shard = reshard_heads(full, src_shards=2, dst_shards=4, shard_index=1)
        np.testing.assert_array_equal(
            np.asarray(shard), np.asarray(full[:, :, :, :, 2:4])
        )
