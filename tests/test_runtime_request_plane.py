"""Request plane tests: streaming RPC, multiplexing, errors, cancellation
over BOTH transports — TCP (two-part frames) and HTTP (chunked frame
stream) — behind one contract (ref: lib/runtime/src/pipeline/network/
tcp client/server + egress/http_router.rs, DYN_REQUEST_PLANE)."""

import asyncio

import pytest

from dynamo_tpu.runtime.request_plane import (
    EndpointNotFound,
    HttpRequestServer,
    RemoteError,
    RequestClient,
    TcpRequestServer,
)


async def _start_server(kind="tcp"):
    cls = {"tcp": TcpRequestServer, "http": HttpRequestServer}[kind]
    server = cls("127.0.0.1", 0, advertise_host="127.0.0.1")
    await server.start()
    return server


@pytest.mark.parametrize("kind", ["tcp", "http"])
class TestRequestPlane:
    def test_stream_roundtrip(self, run, kind):
        async def body():
            server = await _start_server(kind)

            async def handler(req, ctx):
                for i in range(req["n"]):
                    yield {"i": i, "echo": req["msg"]}

            server.registry.register("ns/c/e/1", handler)
            client = RequestClient()
            out = [x async for x in client.call(server.address, "ns/c/e/1",
                                                {"n": 3, "msg": "hi"})]
            assert out == [{"i": 0, "echo": "hi"}, {"i": 1, "echo": "hi"},
                           {"i": 2, "echo": "hi"}]
            await client.close()
            await server.close()

        run(body())

    def test_concurrent_multiplexed_requests(self, run, kind):
        async def body():
            server = await _start_server(kind)

            async def handler(req, ctx):
                for i in range(5):
                    await asyncio.sleep(0.01)
                    yield {"req": req["id"], "i": i}

            server.registry.register("s/1", handler)
            client = RequestClient()

            async def one(rid):
                return [x async for x in client.call(server.address, "s/1",
                                                     {"id": rid})]

            results = await asyncio.gather(*[one(i) for i in range(8)])
            for rid, res in enumerate(results):
                assert [x["req"] for x in res] == [rid] * 5
                assert [x["i"] for x in res] == list(range(5))
            await client.close()
            await server.close()

        run(body())

    def test_handler_error_propagates(self, run, kind):
        async def body():
            server = await _start_server(kind)

            async def handler(req, ctx):
                yield {"ok": True}
                raise ValueError("boom")

            server.registry.register("s/err", handler)
            client = RequestClient()
            stream = client.call(server.address, "s/err", {})
            assert (await stream.__anext__()) == {"ok": True}
            with pytest.raises(RemoteError, match="boom"):
                await stream.__anext__()
            await client.close()
            await server.close()

        run(body())

    def test_unknown_endpoint(self, run, kind):
        async def body():
            server = await _start_server(kind)
            client = RequestClient()
            with pytest.raises(EndpointNotFound):
                async for _ in client.call(server.address, "nope", {}):
                    pass
            await client.close()
            await server.close()

        run(body())

    def test_client_cancellation_stops_handler(self, run, kind):
        async def body():
            server = await _start_server(kind)
            cancelled = asyncio.Event()

            async def handler(req, ctx):
                try:
                    i = 0
                    while True:
                        yield {"i": i}
                        i += 1
                        await asyncio.sleep(0.01)
                except asyncio.CancelledError:
                    cancelled.set()
                    raise

            server.registry.register("s/inf", handler)
            client = RequestClient()
            stream = client.call(server.address, "s/inf", {})
            got = []
            async for item in stream:
                got.append(item)
                if len(got) == 3:
                    break
            await stream.aclose()
            await asyncio.wait_for(cancelled.wait(), 2.0)
            await client.close()
            await server.close()

        run(body())

    def test_binary_payload_passthrough(self, run, kind):
        async def body():
            server = await _start_server(kind)

            async def handler(req, ctx):
                yield {"data": req["data"] + b"\x00\x01", "len": len(req["data"])}

            server.registry.register("s/bin", handler)
            client = RequestClient()
            blob = bytes(range(256)) * 100
            out = [x async for x in client.call(server.address, "s/bin",
                                                {"data": blob})]
            assert out[0]["len"] == len(blob)
            assert out[0]["data"] == blob + b"\x00\x01"
            await client.close()
            await server.close()

        run(body())


class TestHttpPlaneEndToEnd:
    def test_runtime_pair_over_http(self, run):
        """Full DistributedRuntime pair with DYNT_REQUEST_PLANE=http:
        serve, discover, stream — the transport choice is invisible to the
        rest of the stack (addresses carry their scheme)."""
        import uuid

        from dynamo_tpu.runtime import (
            DistributedRuntime,
            PushRouter,
            RuntimeConfig,
        )

        async def body():
            cluster = uuid.uuid4().hex

            def cfg():
                c = RuntimeConfig.from_env()
                c.discovery_backend = "mem"
                c.discovery_path = cluster
                c.request_plane = "http"
                c.tcp_host = "127.0.0.1"
                c.event_plane = "mem"
                c.system_enabled = False
                return c

            server = await DistributedRuntime(cfg()).start()
            assert server.request_server.address.startswith("http://")
            client_rt = await DistributedRuntime(cfg()).start()
            try:
                endpoint = (server.namespace("httpns").component("w")
                            .endpoint("gen"))

                async def handler(body_, ctx=None):
                    for i in range(3):
                        yield {"i": i, "echo": body_["x"]}

                await endpoint.serve_endpoint(handler, instance_id=3)
                cep = (client_rt.namespace("httpns").component("w")
                       .endpoint("gen").client())
                await cep.wait_for_instances(1, timeout=10.0)
                router = PushRouter(cep, mode="round_robin")
                out = [o async for o in router.generate({"x": "hi"})]
                assert out == [{"i": 0, "echo": "hi"}, {"i": 1, "echo": "hi"},
                               {"i": 2, "echo": "hi"}]
            finally:
                await client_rt.shutdown()
                await server.shutdown()

        run(body(), timeout=60.0)

    def test_mixed_transport_cluster(self, run):
        """A tcp worker and an http worker behind ONE client: the address
        scheme selects the transport per call."""
        import uuid

        from dynamo_tpu.runtime import (
            DistributedRuntime,
            PushRouter,
            RuntimeConfig,
        )

        async def body():
            cluster = uuid.uuid4().hex

            def cfg(plane):
                c = RuntimeConfig.from_env()
                c.discovery_backend = "mem"
                c.discovery_path = cluster
                c.request_plane = plane
                c.tcp_host = "127.0.0.1"
                c.event_plane = "mem"
                c.system_enabled = False
                return c

            rt_tcp = await DistributedRuntime(cfg("tcp")).start()
            rt_http = await DistributedRuntime(cfg("http")).start()
            rt_client = await DistributedRuntime(cfg("tcp")).start()
            try:
                for rt, iid, tag in ((rt_tcp, 1, "tcp"),
                                     (rt_http, 2, "http")):
                    async def handler(body_, ctx=None, tag=tag):
                        yield {"via": tag}

                    await (rt.namespace("mix").component("w")
                           .endpoint("gen")
                           .serve_endpoint(handler, instance_id=iid))
                cep = (rt_client.namespace("mix").component("w")
                       .endpoint("gen").client())
                await cep.wait_for_instances(2, timeout=10.0)
                router = PushRouter(cep, mode="round_robin")
                seen = set()
                for _ in range(4):
                    out = [o async for o in router.generate({})]
                    seen.add(out[0]["via"])
                assert seen == {"tcp", "http"}
            finally:
                await rt_client.shutdown()
                await rt_http.shutdown()
                await rt_tcp.shutdown()

        run(body(), timeout=60.0)


class TestPing:
    """Client-side liveness probe: ping/pong round-trips the peer's frame
    loop without dispatching a handler (the 'ping' arm the server always
    had; dynaflow DF103 flagged the missing producer)."""

    def test_ping_round_trips(self, run):
        async def body():
            server = await _start_server("tcp")
            client = RequestClient()
            rtt = await client._tcp.ping(server.address)
            assert rtt >= 0.0
            # ping consumes no endpoint and leaves no stream behind
            assert not any(c.streams for c in client._tcp._conns.values())
            await client.close()
            await server.close()

        run(body())

    def test_ping_works_alongside_streams(self, run):
        async def body():
            server = await _start_server("tcp")

            async def handler(req, ctx):
                await asyncio.sleep(0.05)
                yield {"ok": True}

            server.registry.register("s/slow", handler)
            client = RequestClient()
            stream = client.call(server.address, "s/slow", {})
            task = asyncio.ensure_future(anext(stream.__aiter__()))
            rtt = await client._tcp.ping(server.address, timeout=2.0)
            assert rtt < 2.0  # pong flows while the handler is busy
            assert (await task) == {"ok": True}
            await client.close()
            await server.close()

        run(body())
