"""Request plane tests: TCP streaming RPC, multiplexing, errors, cancellation
(ref contract: lib/runtime/src/pipeline/network/ tcp client/server +
push_endpoint)."""

import asyncio

import pytest

from dynamo_tpu.runtime.request_plane import (
    EndpointNotFound,
    RemoteError,
    RequestClient,
    TcpRequestServer,
)


async def _start_server():
    server = TcpRequestServer("127.0.0.1", 0, advertise_host="127.0.0.1")
    await server.start()
    return server


class TestTcpRequestPlane:
    def test_stream_roundtrip(self, run):
        async def body():
            server = await _start_server()

            async def handler(req, ctx):
                for i in range(req["n"]):
                    yield {"i": i, "echo": req["msg"]}

            server.registry.register("ns/c/e/1", handler)
            client = RequestClient()
            out = [x async for x in client.call(server.address, "ns/c/e/1",
                                                {"n": 3, "msg": "hi"})]
            assert out == [{"i": 0, "echo": "hi"}, {"i": 1, "echo": "hi"},
                           {"i": 2, "echo": "hi"}]
            await client.close()
            await server.close()

        run(body())

    def test_concurrent_multiplexed_requests(self, run):
        async def body():
            server = await _start_server()

            async def handler(req, ctx):
                for i in range(5):
                    await asyncio.sleep(0.01)
                    yield {"req": req["id"], "i": i}

            server.registry.register("s/1", handler)
            client = RequestClient()

            async def one(rid):
                return [x async for x in client.call(server.address, "s/1",
                                                     {"id": rid})]

            results = await asyncio.gather(*[one(i) for i in range(8)])
            for rid, res in enumerate(results):
                assert [x["req"] for x in res] == [rid] * 5
                assert [x["i"] for x in res] == list(range(5))
            await client.close()
            await server.close()

        run(body())

    def test_handler_error_propagates(self, run):
        async def body():
            server = await _start_server()

            async def handler(req, ctx):
                yield {"ok": True}
                raise ValueError("boom")

            server.registry.register("s/err", handler)
            client = RequestClient()
            stream = client.call(server.address, "s/err", {})
            assert (await stream.__anext__()) == {"ok": True}
            with pytest.raises(RemoteError, match="boom"):
                await stream.__anext__()
            await client.close()
            await server.close()

        run(body())

    def test_unknown_endpoint(self, run):
        async def body():
            server = await _start_server()
            client = RequestClient()
            with pytest.raises(EndpointNotFound):
                async for _ in client.call(server.address, "nope", {}):
                    pass
            await client.close()
            await server.close()

        run(body())

    def test_client_cancellation_stops_handler(self, run):
        async def body():
            server = await _start_server()
            cancelled = asyncio.Event()

            async def handler(req, ctx):
                try:
                    i = 0
                    while True:
                        yield {"i": i}
                        i += 1
                        await asyncio.sleep(0.01)
                except asyncio.CancelledError:
                    cancelled.set()
                    raise

            server.registry.register("s/inf", handler)
            client = RequestClient()
            stream = client.call(server.address, "s/inf", {})
            got = []
            async for item in stream:
                got.append(item)
                if len(got) == 3:
                    break
            await stream.aclose()
            await asyncio.wait_for(cancelled.wait(), 2.0)
            await client.close()
            await server.close()

        run(body())

    def test_binary_payload_passthrough(self, run):
        async def body():
            server = await _start_server()

            async def handler(req, ctx):
                yield {"data": req["data"] + b"\x00\x01", "len": len(req["data"])}

            server.registry.register("s/bin", handler)
            client = RequestClient()
            blob = bytes(range(256)) * 100
            out = [x async for x in client.call(server.address, "s/bin",
                                                {"data": blob})]
            assert out[0]["len"] == len(blob)
            assert out[0]["data"] == blob + b"\x00\x01"
            await client.close()
            await server.close()

        run(body())
