from .runtime.config import env

GOOD = env("DYNT_GOOD")
BADTYPE = env("DYNT_BADTYPE")
UNREGISTERED = env("DYNT_UNREGISTERED")  # -> DF401
