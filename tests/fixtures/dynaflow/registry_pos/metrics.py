from prometheus_client import Counter

FIRST = Counter("dynamo_dup_total", "first registration")
SECOND = Counter("dynamo_dup_total", "same name again -> DF404")
SECRET = Counter("dynamo_secret_total", "absent from the doc -> DF405")
