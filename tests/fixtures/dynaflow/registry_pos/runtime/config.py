_REGISTRY = {}


def _register(name, default, parse, doc):
    _REGISTRY[name] = (default, parse, doc)


def env(name):
    return _REGISTRY[name][0]


_str = str
_int = int


_register("DYNT_GOOD", 1, _int, "wired knob")
_register("DYNT_DEAD", 1, _int, "read by nothing -> DF403")
_register("DYNT_BADTYPE", "sixteen", _int, "str default, int parser -> DF402")
