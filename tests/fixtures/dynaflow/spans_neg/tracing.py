"""Span-registry negative fixtures: documented, unique, dynamic-skipped."""


def documented(tracer):
    with tracer.start_span("fixture.documented"):
        pass


def conditional(tracer, kind):
    with tracer.start_span(
            "fixture.chat" if kind == "chat" else "fixture.completions"):
        pass


def phase(tracer, parent):
    tracer.record_span("fixture.phase", parent, 1, 2)


def dynamic(tracer, name):
    # Dynamic names are invisible to the registry (kept literal in the
    # real tree); must not crash or report.
    with tracer.start_span(name):
        pass
