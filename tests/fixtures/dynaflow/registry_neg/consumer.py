from prometheus_client import Counter

from .runtime.config import env

GOOD = env("DYNT_GOOD")
RATIO = env("DYNT_RATIO")
OPTIONAL = env("DYNT_OPTIONAL")

DOCUMENTED = Counter("dynamo_documented_total", "listed in the doc")
