_REGISTRY = {}


def _register(name, default, parse, doc):
    _REGISTRY[name] = (default, parse, doc)


def env(name):
    return _REGISTRY[name][0]


_int = int
_float = float


_register("DYNT_GOOD", 1, _int, "wired knob")
_register("DYNT_RATIO", 0.5, _float, "float knob, float default")
_register("DYNT_OPTIONAL", None, _float, "None default is always fine")
