"""DF406 negative fixture: every per-origin label literal or funneled
through bounded_label()/LabelRegistry.admit()."""

from prometheus_client import Counter

from dynamo_tpu.runtime.metric_labels import bounded_label, get_label_registry

SHED = Counter("dynamo_fixture_shed_total", "per-tenant sheds",
               ["tenant", "reason"])
SPILL = Counter("dynamo_fixture_spill_total", "cross-cell spills",
                ["from", "to", "reason"])
OUTCOMES = Counter("dynamo_fixture_outcomes_total", "bounded by design",
                   ["outcome"])


def record(tenant, src, dst, outcome):
    SHED.labels(tenant=bounded_label("tenant", tenant),
                reason="quota").inc()
    SHED.labels(tenant="untagged", reason="queue").inc()
    SPILL.labels(bounded_label("cell", src),
                 bounded_label("cell", dst), "pressure").inc()
    SPILL.labels(**{"from": bounded_label("cell", src),
                    "to": "home", "reason": "evac"}).inc()
    # admit() is the registry-level funnel — equally bounded
    SHED.labels(tenant=get_label_registry().admit("tenant", tenant),
                reason="quota").inc()
    # non-risky label names stay free-form
    OUTCOMES.labels(outcome=outcome).inc()
