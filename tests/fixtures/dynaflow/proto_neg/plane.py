"""Protocol fixture (negative): producer and consumer agree exactly."""


def producer(sock):
    send(sock, {"t": "msg", "k": 1})
    send(sock, {"t": "end"})


def consumer(msg):
    ftype = msg.get("t")
    if ftype == "msg":
        return msg["k"]
    if ftype == "end":
        return None
    return None


def send(sock, frame):
    sock.write(frame)
