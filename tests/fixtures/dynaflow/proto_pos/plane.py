"""Protocol fixture (positive): one plane with every drift flavor."""


def producer(sock):
    # tag 'msg' carries a key no consumer reads ('dead') -> DF101
    send(sock, {"t": "msg", "k": 1, "dead": 2})
    # tag 'orphan' has no dispatch arm -> DF103
    send(sock, {"t": "orphan", "k": 3})


def consumer(msg):
    ftype = msg.get("t")
    if ftype == "msg":
        return msg["k"]
    if ftype == "ghost":  # never produced -> DF103
        return msg["gone"]  # never written -> DF102
    return None


def send(sock, frame):
    sock.write(frame)
