import dataclasses


@dataclasses.dataclass
class SamplingOptions:
    temperature: float = 1.0
    min_p: float = 0.0


@dataclasses.dataclass
class EngineOutput:
    token_ids: list = dataclasses.field(default_factory=list)
