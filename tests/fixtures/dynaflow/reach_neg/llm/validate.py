_COMMON_FIELDS = {"temperature", "min_p"}
