def main(request):
    return step(request)


def step(request):
    penalty = request.sampling.min_p + request.sampling.temperature
    return penalty + sum(request.output.token_ids)
