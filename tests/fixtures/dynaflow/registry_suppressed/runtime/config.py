_REGISTRY = {}


def _register(name, default, parse, doc):
    _REGISTRY[name] = (default, parse, doc)


_int = int


_register("DYNT_FUTURE", 1, _int, "reserved")  # dynaflow: disable=DF403 -- reserved for the next release
_register("DYNT_TYPO", 1, _int, "typo'd suppression")  # dynaflow: disable=DF999 -- bad rule name
