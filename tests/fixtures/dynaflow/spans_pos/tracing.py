"""Span-registry positive fixtures: undocumented + duplicate names."""


def documented(tracer):
    with tracer.start_span("fixture.documented"):
        pass


def undocumented(tracer):
    # DF501: not in the catalogue doc
    with tracer.start_span("fixture.mystery"):
        pass


def duplicate_site(tracer):
    # DF502: same name as documented() above
    with tracer.start_span("fixture.documented"):
        pass


def phase(tracer, parent):
    tracer.record_span("fixture.phase", parent, 1, 2)
