def main(request):
    # entry point consumes temperature, so only min_p dangles
    return request.sampling.temperature + sum(consume(request).token_ids)


def consume(request):
    return request.output


def dead_code(request):
    # reads min_p, but nothing reachable ever calls this -> DF301
    return request.sampling.min_p
