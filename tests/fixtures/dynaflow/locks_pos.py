"""Lock fixture (positive): slow awaits under locks + ABBA ordering."""

import asyncio
import threading


class SlowUnderLock:
    def __init__(self):
        self._lock = asyncio.Lock()

    async def direct(self):
        async with self._lock:
            await asyncio.sleep(1.0)  # DF201: slow await under lock

    async def via_callee(self):
        async with self._lock:
            await self._helper()  # DF201: callee awaits slow call

    async def _helper(self):
        await asyncio.sleep(0.5)


class OrderAB:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:  # order a -> b
                pass

    def backward(self):
        with self._b:
            with self._a:  # DF202: order b -> a elsewhere
                pass
