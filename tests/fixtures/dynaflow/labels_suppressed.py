"""DF406 suppression fixture: a justified disable on the flagged line."""

from prometheus_client import Counter

CELLS = Counter("dynamo_fixture_cell_total", "per-cell events", ["cell"])


def record(cell):
    CELLS.labels(cell=cell).inc()  # dynaflow: disable=DF406 -- cell set is fixed at deploy time
