"""DF406 positive fixture: per-origin labels fed raw dynamic values."""

from prometheus_client import Counter

SHED = Counter("dynamo_fixture_shed_total", "per-tenant sheds",
               ["tenant", "reason"])
SPILL = Counter("dynamo_fixture_spill_total", "cross-cell spills",
                ["from", "to", "reason"])


def record(tenant, src, dst):
    # keyword form: raw tenant -> DF406
    SHED.labels(tenant=tenant, reason="quota").inc()
    # **dict form (reserved-word labels): raw from/to -> DF406 x2
    SPILL.labels(**{"from": src, "to": dst, "reason": "evac"}).inc()
    # positional form: raw from/to -> DF406 x2 (reason is a literal)
    SPILL.labels(src, dst, "pressure").inc()
