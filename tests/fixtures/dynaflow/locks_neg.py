"""Lock fixture (negative): send-lock transport writes, consistent
ordering, slow work outside the locked region."""

import asyncio
import threading


class SendLockOk:
    def __init__(self, writer):
        self.send_lock = asyncio.Lock()
        self.writer = writer

    async def send(self, frame):
        # serializing the transport is the send lock's purpose
        async with self.send_lock:
            self.writer.write(frame)
            await self.writer.drain()


class SlowOutsideLock:
    def __init__(self):
        self._lock = asyncio.Lock()
        self.value = 0

    async def update(self):
        async with self._lock:
            self.value += 1
        await asyncio.sleep(1.0)  # slow, but the lock is released


class OrderConsistent:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._a:
            with self._b:
                pass
