"""DR301 negatives: locked region stays synchronous; await happens
outside, or the lock is an asyncio.Lock taken with async with."""

import asyncio
import threading


class ShrunkFlusher:
    def __init__(self):
        self._lock = threading.Lock()
        self.batch = []

    def add(self, item):
        with self._lock:
            self.batch.append(item)

    async def flush(self):
        with self._lock:
            batch, self.batch = self.batch, []
        await self._send(batch)

    async def _send(self, batch):
        pass


class AsyncFlusher:
    def __init__(self):
        self._alock = asyncio.Lock()
        self.batch = []

    async def flush(self):
        async with self._alock:
            batch, self.batch = self.batch, []
            await self._send(batch)

    async def _send(self, batch):
        pass
