"""DR101 suppressed: the race exists, but the suppression carries a
justification citing the interleaving test that earns it."""

import asyncio
import threading


class AuditedPump:
    def __init__(self):
        self.count = 0
        self._thread = threading.Thread(target=self._worker,
                                        name="pump-worker", daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            self.count += 1  # dynarace: disable=DR101 -- single-writer by design; adversarial schedule pinned by tests/test_interleave.py::test_locked_counter_survives_every_schedule

    async def poll(self):
        await asyncio.sleep(1)
        return self.count
