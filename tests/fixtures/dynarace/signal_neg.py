"""DR401 negative: the runtime/signals.py contract — a handler only
resolves an idempotent event and logs; once-semantics live in the
converging callee."""

import asyncio
import logging
import signal

log = logging.getLogger("fixture")


async def wait_for_shutdown():
    loop = asyncio.get_running_loop()
    event = asyncio.Event()

    def _handler(signame):
        log.info("received %s", signame)
        event.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, _handler, sig.name)
    await event.wait()
