"""DR501 suppressed with justification."""

import threading


class PinnedWorker:
    def __init__(self):
        self._worker = threading.Thread(target=self._loop)  # dynarace: disable=DR501 -- interpreter-lifetime metrics pump; process exit IS its shutdown story (ops runbook §monitoring)
        self._worker.start()

    def _loop(self):
        pass
