"""DR201 negatives: the call_soon_threadsafe hop, or loop-side touches."""

import asyncio
import threading


class HoppedNotifier:
    """The event-plane idiom: foreign threads hop in through
    loop.call_soon_threadsafe; the mutation itself runs on the loop."""

    def __init__(self, loop):
        self.loop = loop
        self._ready = asyncio.Event()
        self._thread = threading.Thread(target=self._worker,
                                        name="notify-worker", daemon=True)
        self._thread.start()

    def _worker(self):
        self.loop.call_soon_threadsafe(self._ready.set)

    async def wait_ready(self):
        await self._ready.wait()


class LoopLocal:
    """Loop-domain code may touch asyncio primitives freely."""

    def __init__(self):
        self._ready = asyncio.Event()

    async def fire(self):
        self._ready.set()
        task = asyncio.ensure_future(self._pump())
        await task

    async def _pump(self):
        await self._ready.wait()
