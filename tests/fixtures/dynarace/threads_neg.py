"""DR501 negatives: every thread is joined or deliberately daemon."""

import threading


class JoinedWorker:
    def __init__(self):
        self._worker = threading.Thread(target=self._loop)
        self._worker.start()

    def _loop(self):
        pass

    def close(self):
        self._worker.join(timeout=5.0)


class DaemonWorker:
    def __init__(self):
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _loop(self):
        pass


def scoped_join():
    t = threading.Thread(target=print)
    t.start()
    t.join()


def late_daemon_flag():
    t = threading.Thread(target=print)
    t.daemon = True
    t.start()
