"""DR101 positives: cross-domain mutable state with no mediation."""

import asyncio
import threading


class Pump:
    """Worker thread and event loop both mutate `count` — no lock,
    no channel, no sentinel: a lost-update race."""

    def __init__(self):
        self.count = 0
        self._thread = threading.Thread(target=self._worker,
                                        name="pump-worker", daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            self.count += 1

    async def poll(self):
        self.count = 0
        await asyncio.sleep(1)
        return self.count


class Loader:
    """Executor body (asyncio.to_thread) writes what the loop reads."""

    def __init__(self):
        self.blob = None

    def _build(self):
        self.blob = object()
        self.blob = [self.blob]

    async def refresh(self):
        await asyncio.to_thread(self._build)
        while self.blob is None:
            await asyncio.sleep(0)
