"""DR201 positives: asyncio primitives touched from foreign domains."""

import asyncio
import threading


class Notifier:
    """Worker thread resolves an asyncio.Event directly — waiters are
    woken via call_soon, which is loop-affine, so they may never wake."""

    def __init__(self):
        self._ready = asyncio.Event()
        self._thread = threading.Thread(target=self._worker,
                                        name="notify-worker", daemon=True)
        self._thread.start()

    def _worker(self):
        self._ready.set()

    async def wait_ready(self):
        await self._ready.wait()


class Spawner:
    """Thread body creating loop tasks without the threadsafe hop."""

    def __init__(self, loop):
        self.loop = loop
        self._thread = threading.Thread(target=self._worker,
                                        name="spawn-worker", daemon=True)
        self._thread.start()

    def _worker(self):
        asyncio.ensure_future(self._pump())
        self.loop.call_soon(print, "done")

    async def _pump(self):
        await asyncio.sleep(0)
