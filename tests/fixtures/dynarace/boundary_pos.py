"""DR301 positive: await while holding a threading lock."""

import threading


class Flusher:
    def __init__(self):
        self._lock = threading.Lock()
        self.batch = []

    def add(self, item):
        with self._lock:
            self.batch.append(item)

    async def flush(self):
        with self._lock:
            batch, self.batch = self.batch, []
            await self._send(batch)

    async def _send(self, batch):
        pass
