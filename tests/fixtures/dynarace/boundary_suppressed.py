"""DR301 suppressed with justification."""

import threading


class AuditedFlusher:
    def __init__(self):
        self._lock = threading.Lock()
        self.batch = []

    async def flush(self):
        with self._lock:
            await self._send(self.batch)  # dynarace: disable=DR301 -- no thread ever takes _lock (loop-confined; kept sync for a C-extension callback contract)

    async def _send(self, batch):
        pass
