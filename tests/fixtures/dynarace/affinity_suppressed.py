"""DR201 suppressed with justification."""

import asyncio
import threading


class PinnedNotifier:
    def __init__(self):
        self._ready = asyncio.Event()
        self._thread = threading.Thread(target=self._worker,
                                        name="notify-worker", daemon=True)
        self._thread.start()

    def _worker(self):
        self._ready.set()  # dynarace: disable=DR201 -- loop is single-threaded in this tool and parked on run_until_complete; no waiter can race the set
