"""DR401 positives: signal handlers that compound on repeated delivery."""

import asyncio
import queue
import signal
import threading

DELIVERIES = []
_SIGNAL_Q = queue.Queue()


def _on_term(signum, frame):
    DELIVERIES.append(signum)
    worker = threading.Thread(target=_drain, daemon=True)
    worker.start()


def _drain():
    pass


def install():
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, lambda s, f: _SIGNAL_Q.put(s))


class App:
    def __init__(self, loop):
        self.loop = loop
        self.shutdowns = 0

    def _on_signal(self):
        self.shutdowns += 1
        self.loop.create_task(self._teardown())

    async def _teardown(self):
        await asyncio.sleep(0)

    def install(self):
        self.loop.add_signal_handler(signal.SIGTERM, self._on_signal)
