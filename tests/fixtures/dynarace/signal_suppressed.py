"""DR401 suppressed: the compounding call is converged by the callee,
and the suppression cites the interleaving test that pins it."""

import asyncio
import signal


class DrainingApp:
    def __init__(self, loop, coordinator):
        self.loop = loop
        self.coordinator = coordinator

    def _on_signal(self):
        self.loop.create_task(self.coordinator.drain("signal"))  # dynarace: disable=DR401 -- every delivery joins the ONE shielded ladder run inside drain(); convergence pinned by tests/test_interleave.py::test_double_drain_converges

    def install(self):
        self.loop.add_signal_handler(signal.SIGTERM, self._on_signal)
