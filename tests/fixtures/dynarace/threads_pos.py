"""DR501 positives: threads with no shutdown story."""

import threading


class LeakyWorker:
    """Stored but never joined, and not daemon: close() abandons it."""

    def __init__(self):
        self._worker = threading.Thread(target=self._loop)
        self._worker.start()

    def _loop(self):
        pass

    def close(self):
        pass


def fire_and_forget():
    threading.Thread(target=print).start()
