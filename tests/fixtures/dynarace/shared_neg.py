"""DR101 negatives: every cross-domain touch is mediated."""

import asyncio
import dataclasses
import queue
import threading


class LockedPump:
    """Same shape as the positive fixture, but every access to the
    shared counter holds the same threading.Lock."""

    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._worker,
                                        name="pump-worker", daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            with self._lock:
                self.count += 1

    async def poll(self):
        with self._lock:
            self.count = 0
        await asyncio.sleep(1)
        with self._lock:
            return self.count


@dataclasses.dataclass
class MeterState:
    """Dataclass-held lock (field(default_factory=threading.Lock)) —
    the collector must see it just like an __init__ assignment."""

    total: int = 0
    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False)

    def bump(self, n):
        with self._lock:
            self.total += n

    async def snapshot(self):
        with self._lock:
            return self.total


def _meter_worker(state):
    state.bump(1)


def spawn_meter():
    state = MeterState()
    t = threading.Thread(target=_meter_worker, args=(state,),
                         name="meter-worker", daemon=True)
    t.start()
    return state


class QueuePump:
    """Channel-typed attribute: the queue IS the mediation."""

    def __init__(self):
        self._q = queue.Queue()
        self._thread = threading.Thread(target=self._worker,
                                        name="queue-worker", daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            self._q.put(1)

    async def drain(self):
        out = []
        while not self._q.empty():
            out.append(self._q.get_nowait())
        return out
