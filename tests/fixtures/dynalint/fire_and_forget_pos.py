"""DL101 positive: the deliberately reintroduced fire-and-forget task."""
import asyncio


async def discard_expression():
    asyncio.create_task(asyncio.sleep(1))  # line 6: bare discard


async def discard_ensure_future():
    asyncio.ensure_future(asyncio.sleep(1))  # line 10: bare discard


async def assigned_never_read():
    task = asyncio.create_task(asyncio.sleep(1))  # line 14: dead binding
    del task  # a Del is not a Load; the task is still unobserved


async def rebound_after_use():
    task = asyncio.create_task(asyncio.sleep(1))
    await task
    task = asyncio.create_task(asyncio.sleep(1))  # line 21: leaked rebind
