"""Mini carrier layer: the parsed request shape."""
import dataclasses


@dataclasses.dataclass
class SamplingOptions:
    max_tokens: int = 256
    temperature: float = 1.0
    min_p: float = 0.0  # line 9: accepted, parsed, never consumed


@dataclasses.dataclass
class StopConditions:
    ignore_eos: bool = False
