"""Mini accept layer: fields the API admits."""

_COMMON_FIELDS = {"model", "max_tokens", "temperature", "min_p"}


def validate_request(body: dict) -> None:
    unknown = sorted(k for k in body if k not in _COMMON_FIELDS)
    if unknown:
        raise ValueError(f"Unsupported parameter: {unknown}")
