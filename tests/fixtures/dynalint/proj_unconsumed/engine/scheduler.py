"""Mini consumer: reads temperature and max_tokens, never min_p."""


def build(sampling):
    return {
        "temp": sampling.temperature,
        "budget": sampling.max_tokens,
    }
