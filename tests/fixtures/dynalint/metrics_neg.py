"""DL303 negative: convention-conforming names, and non-metric
Counters."""
import collections

from prometheus_client import Counter, Histogram

REQS = Counter("dynamo_requests_total", "Requests handled")
LAT = Histogram("dynamo_latency_seconds", "Latency")
WORDS = collections.Counter("abracadabra")  # one arg: not a metric ctor
