"""DL103 positive: vestigial async (no sibling of the name awaits)."""


async def crunch_numbers():  # line 4
    total = 0
    for i in range(1000):
        total += i
    return total
