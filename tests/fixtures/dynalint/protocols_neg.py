"""DL301 negative: msgpack-native fields, local nested wire types, and
non-wire dataclasses (no to_wire/from_wire) with exotic fields."""
import dataclasses
from typing import Any, Optional


@dataclasses.dataclass
class Inner:
    block_hashes: list[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class WireEvent:
    worker_id: int
    payload: Optional[dict] = None
    inner: Optional[Inner] = None  # local type, flattened in to_wire
    scores: dict[str, float] = dataclasses.field(default_factory=dict)
    blob: bytes = b""
    anything: Any = None

    def to_wire(self) -> dict:
        out = dataclasses.asdict(self)
        out.pop("inner", None)
        return out


@dataclasses.dataclass
class HostOnly:  # never crosses the wire: exotic fields are fine
    span: tuple[int, int] = (0, 0)
