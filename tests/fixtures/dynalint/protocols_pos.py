"""DL301 positive: wire dataclasses with fields msgpack can't
round-trip (filename contains 'protocols' so the rule applies)."""
import dataclasses
from typing import Optional


@dataclasses.dataclass
class TransferRequest:
    request_id: str
    span: tuple[int, int]  # line 10: decodes as a list
    tags: set[str]  # line 11: fails to pack
    payload: Optional[bytes] = None

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)
