"""Suppression semantics: a justified disable silences exactly that
rule on that line; unknown rule names are themselves findings."""
import asyncio
import time


async def justified():
    asyncio.create_task(asyncio.sleep(1))  # dynalint: disable=DL101 -- fixture: exercising suppression


async def wrong_rule_still_fires():
    asyncio.create_task(asyncio.sleep(1))  # dynalint: disable=DL102


async def by_name():
    asyncio.create_task(asyncio.sleep(1))  # dynalint: disable=fire-and-forget-task


async def typo():
    time.sleep(1)  # dynalint: disable=DL999
