"""DL303 negative: no prometheus_client import — Counter here is
someone else's Counter, whatever its arguments look like."""
from mylib import Counter  # noqa

REQS = Counter("requests_total", "not a prometheus metric")
