"""DL201 positive: per-iteration host-device syncs (path contains
'engine' so the hot-path rule applies)."""
import numpy as np

import jax


def per_step_readback(device_tokens, chunks):
    out = []
    for tok in device_tokens:
        out.append(np.asarray(tok))  # line 11: sync per iteration
    i = 0
    while i < len(device_tokens):
        device_tokens[i].block_until_ready()  # line 14
        i += 1
    scalars = [t.item() for t in device_tokens]  # line 16: comp elt
    hosts = [jax.device_get(c) for c in chunks]  # line 17: comp elt
    return out, scalars, hosts
