"""DL201 negative: syncs outside loops, and loop-adjacent non-syncs."""
import numpy as np


def batched_readback(device_tokens):
    stacked = np.asarray(device_tokens)  # one transfer, outside any loop
    out = []
    for row in np.asarray(device_tokens):  # iterable evaluates once
        out.append(int(row))
    total = sum(t for t in stacked)  # loop without sync calls
    return out, total


def loop_defines_callback(device_tokens):
    fns = []
    for tok in device_tokens:
        # defining a closure in a loop is not a per-iteration sync
        fns.append(lambda t=tok: np.asarray(t))
    return fns
