"""DL102 negative: async-safe equivalents, and sync contexts."""
import asyncio
import subprocess
import time


async def polite():
    await asyncio.sleep(0.5)
    await asyncio.to_thread(subprocess.run, ["true"])

    def helper():  # nested sync def runs off-loop (executor/thread)
        time.sleep(0.5)

    await asyncio.to_thread(helper)


def plain_sync():
    time.sleep(0.5)  # not on the event loop
