"""DL101 negative: every spawn is retained or observed."""
import asyncio


class Owner:
    def __init__(self):
        self._tasks = []

    async def retained_on_self(self):
        self._tasks.append(asyncio.create_task(asyncio.sleep(1)))

    async def awaited(self):
        await asyncio.create_task(asyncio.sleep(1))

    async def observed(self):
        task = asyncio.create_task(asyncio.sleep(1))
        task.add_done_callback(lambda t: t.exception())

    async def returned(self):
        task = asyncio.create_task(asyncio.sleep(1))
        return task

    async def loop_wraparound(self):
        task = None
        while True:
            if task is not None:
                await task  # previous iteration's task consumed here
            task = asyncio.create_task(asyncio.sleep(1))
