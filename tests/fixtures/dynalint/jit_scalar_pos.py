"""DL202 positive: Python scalars in jit signatures, not declared
static."""
import functools

import jax
import jax.numpy as jnp


@jax.jit
def decorated_bare(x, k: int):  # k at line 10
    return x * k


@functools.partial(jax.jit, static_argnames=("flag",))
def decorated_partial(x, flag: bool, depth: int):  # depth at line 15
    return x if flag else x * depth


def call_form():
    def step(kv, temp: float):  # temp at line 20
        return kv * temp

    return jax.jit(step, donate_argnums=(0,))
