"""Mini consumer that DOES read min_p — the wired state."""


def build(sampling):
    procs = []
    if sampling.min_p:
        procs.append(("min_p", sampling.min_p, sampling.temperature))
    return {"budget": sampling.max_tokens, "procs": procs}
