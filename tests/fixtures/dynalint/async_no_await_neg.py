"""DL103 negative: every exemption class in one file."""
import abc
import asyncio


async def really_awaits():
    await asyncio.sleep(0)


async def generator_interface():  # async gens are structurally async
    yield 1


async def handler(request):  # HTTP/RPC handler convention
    return {"ok": True}


async def handler_underscore(_request):
    return {"ok": True}


class Iface(abc.ABC):
    @abc.abstractmethod
    async def work(self): ...

    async def default_impl(self):
        return None  # trivial default of an async interface


class MemImpl:
    async def fetch(self):  # duck-sibling: NetImpl.fetch awaits
        return 42


class NetImpl:
    async def fetch(self):
        return await asyncio.sleep(0, result=42)
