"""DL202 negative: statics declared, arrays passed, or no jit at all."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k", "flag"))
def statics_by_name(x, k: int, flag: bool):
    return x * k if flag else x


@functools.partial(jax.jit, static_argnums=(1,))
def statics_by_num(x, k: int):
    return x * k


@jax.jit
def arrays_only(x: jnp.ndarray, scale: np.ndarray):
    return x * scale


def plain(x, k: int):  # not jitted
    return x * k
