"""DL102 positive: blocking calls on the event loop."""
import subprocess
import time

import requests


async def stalls_everyone():
    time.sleep(0.5)  # line 9
    subprocess.run(["true"])  # line 10
    requests.get("http://localhost")  # line 11
