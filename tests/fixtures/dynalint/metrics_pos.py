"""DL303 positive: unprefixed Prometheus metric names."""
from prometheus_client import Counter, Gauge

REQS = Counter("requests_total", "Requests handled")  # line 4
DEPTH = Gauge("dynt_queue_depth", "Queue depth")  # line 5: legacy prefix
