"""DS201 api negative: every machine-driving method reads its spec'd
terminal flags before mutating (first terminal event wins)."""


class Session:
    def __init__(self):
        self.closed = False
        self.failed = False
        self.items = []

    def update(self, item):
        if self.closed or self.failed:
            return
        self.items.append(item)

    def close(self):
        if self.closed or self.failed:
            return
        self.closed = True

    def fail(self):
        if self.closed:
            return
        self.failed = True
