"""DS201 api positives against specs_api/session.json: update reads
neither terminal flag and fail skips its spec'd closed guard — a
call racing or following close()/fail() mutates a settled
lifecycle. close() itself is properly guarded."""


class Session:
    def __init__(self):
        self.closed = False
        self.failed = False
        self.items = []

    def update(self, item):
        self.items.append(item)

    def close(self):
        if self.closed or self.failed:
            return
        self.closed = True

    def fail(self):
        self.failed = True
