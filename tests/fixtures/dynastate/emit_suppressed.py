"""Suppressed variants of the DS201/DS501 wire positives, each
citing the invariant that makes the flagged site safe."""


def send_stream(sock, parts):
    for i, part in enumerate(parts):
        sock.send({"chunk": i, "data": part})
    sock.send({"done": True})
    sock.send({"chunk": -1, "data": b""})  # dynastate: disable=DS201 -- specs_wire/stream.json: trailing flush sentinel the peer discards after done (fixture contract)


def send_error(sock, excs):
    for exc in excs:
        sock.send({"error": str(exc)})  # dynastate: disable=DS501 -- specs_wire/stream.json: callers pass a single-element tuple, one error per stream by construction
