"""DS501 api positive: the spec binds the terminal close event to
Session.close, but the method no longer exists in the tree — the
machine's terminal event lost its only emitter."""


class Session:
    def __init__(self):
        self.closed = False
        self.failed = False
        self.items = []

    def update(self, item):
        if self.closed or self.failed:
            return
        self.items.append(item)

    def fail(self):
        if self.closed:
            return
        self.failed = True
