"""DS101 positives against specs_wire/stream.json: the spec'd
send_error producer no longer exists, the reset frame is never
emitted (dead spec arm), and recv_loop never reads the terminal
done marker — the consumer silently drops the frame that should
settle its machine (the cancelled-frame-hang bug class)."""


def send_stream(sock, parts):
    for i, part in enumerate(parts):
        sock.send({"chunk": i, "data": part})
    sock.send({"done": True})


def recv_loop(sock, out):
    while True:
        frame = sock.recv()
        if frame.get("chunk") is not None:
            out.append(frame["data"])
