"""Suppressed variant of the DS201 api positives: the unguarded
methods are reviewed, with the serializing invariant cited."""


class Session:
    def __init__(self):
        self.closed = False
        self.failed = False
        self.items = []

    def update(self, item):  # dynastate: disable=DS201 -- specs_api/session.json: callers hold the session lock across the whole lifecycle, no call can race close (fixture contract)
        self.items.append(item)

    def close(self):
        if self.closed or self.failed:
            return
        self.closed = True

    def fail(self):  # dynastate: disable=DS201 -- specs_api/session.json: fail only reachable from the ctor's error path, before close can exist (fixture contract)
        self.failed = True
