"""Wire-ordering positives against specs_wire/stream.json: a chunk
frame emitted lexically after the terminal done frame in the same
block (DS201 — the stream already ended), and the terminal error
frame emitted inside a loop without an immediate exit (DS501 — one
instance's stream could terminate twice)."""


def send_stream(sock, parts):
    for i, part in enumerate(parts):
        sock.send({"chunk": i, "data": part})
    sock.send({"done": True})
    sock.send({"chunk": -1, "data": b""})


def send_error(sock, excs):
    for exc in excs:
        sock.send({"error": str(exc)})
