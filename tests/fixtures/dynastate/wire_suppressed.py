"""The suppressed variant of the consumer-side DS101: recv_loop
really never reads the done key, but the drop is reviewed — the
suppression cites the invariant that makes it safe."""


def send_stream(sock, parts):
    for i, part in enumerate(parts):
        sock.send({"chunk": i, "data": part})
    sock.send({"reset": True})
    sock.send({"done": True})


def send_error(sock, exc):
    sock.send({"error": str(exc)})


def recv_loop(sock, out):  # dynastate: disable=DS101 -- specs_wire/stream.json done frame: the transport's close callback settles the machine, tests/fixtures cover the drop
    while True:
        frame = sock.recv()
        if frame.get("error") is not None:
            raise RuntimeError(frame["error"])
        if frame.get("chunk") is not None:
            out.append(frame["data"])
