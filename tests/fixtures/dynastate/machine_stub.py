"""Anchor file for the spec-level rule fixtures (DS100/DS301/DS401):
those rules judge the active spec dir, not this code — the run just
needs at least one collected file."""


def noop():
    return None
