"""Wire-ordering negatives: the terminal done frame is the last
statement of its block (nothing can follow it), and the looped
terminal error emission breaks immediately — exactly-once holds."""


def send_stream(sock, parts):
    for i, part in enumerate(parts):
        sock.send({"chunk": i, "data": part})
    sock.send({"done": True})


def send_error(sock, exc):
    for _attempt in range(3):
        if not sock.ready():
            continue
        sock.send({"error": str(exc)})
        break
