"""DS101 negative: every spec'd producer and consumer exists, every
frame has an emission site, and the consumer dispatches on each
frame's marker key."""


def send_stream(sock, parts):
    for i, part in enumerate(parts):
        sock.send({"chunk": i, "data": part})
    if sock.needs_reset():
        sock.send({"reset": True})
        return
    sock.send({"done": True})


def send_error(sock, exc):
    sock.send({"error": str(exc)})


def recv_loop(sock, out):
    while True:
        frame = sock.recv()
        if frame.get("error") is not None:
            raise RuntimeError(frame["error"])
        if frame.get("done"):
            return out
        if frame.get("chunk") is not None:
            out.append(frame["data"])
