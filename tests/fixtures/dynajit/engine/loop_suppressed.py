"""DJ201 suppressed: the designed drain point, justified."""

import numpy as np


def _drain_decode(pending):
    return np.asarray(pending)  # dynajit: disable=DJ201 -- the loop's one designed drain point
