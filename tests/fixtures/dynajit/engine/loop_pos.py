"""DJ201 positive: a host sync three calls deep under the dispatch
loop (the regression class the interprocedural pass exists for)."""

import numpy as np


def _dispatch_decode(batch):
    tokens = _issue(batch)
    return tokens


def _issue(batch):
    return _collect(batch)


def _collect(batch):
    count = batch.total.item()  # sync on the dispatch path
    stats = np.asarray(batch.device_stats)  # bare readback, no dtype
    host = np.asarray(batch.host_list, np.int32)  # dtype-carrying: exempt
    return count, stats, host
