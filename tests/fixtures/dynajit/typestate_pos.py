"""DJ5xx positives: leaked claim, unsafe release, double release,
probe-verdict leak."""


class Puller:
    def serve_unsafe(self, table, transfer_id, wire):
        transfer = table.claim(transfer_id)
        wire.send_header(transfer.layout)  # can raise: release leaks
        wire.send_pages(transfer.page_ids)
        transfer.release()  # DJ501: not under a finally
        return True

    def serve_leak(self, table, transfer_id):
        transfer = table.claim(transfer_id)
        if transfer is None:
            return None
        return transfer.page_ids.copy()  # DJ501: never released

    def serve_twice(self, table, transfer_id):
        transfer = table.claim(transfer_id)
        try:
            return transfer.page_ids
        finally:
            transfer.release()
            transfer.release()  # DJ502: second release in one block


class Router:
    def dispatch(self, breaker, client, body):
        if not breaker.try_acquire():  # DJ503: no finally settles it
            return None
        out = client.send(body)
        breaker.record_success()
        return out
