"""DJ4xx suppressed: a justified unguarded grid."""

import jax
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def fixed_geometry(x, block):
    n = x.shape[0]
    return pl.pallas_call(
        _kernel,
        grid=(n // block,),  # dynajit: disable=DJ401 -- geometry fixed by the caller contract (n is always 8*block)
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
