"""DJ4xx negatives: guarded grids and honest q8 variants pass clean."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


def _divisor(dim, pref):
    b = min(pref, dim)
    while b > 1 and dim % b:
        b //= 2
    return b


def guarded_kernel(x, block):
    n = x.shape[0]
    bs = _divisor(n, block)
    return pl.pallas_call(
        _kernel,
        grid=(n // bs,),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def padded_kernel(x, block):
    n = x.shape[0]
    npad = -(-n // block) * block
    x = jnp.pad(x, ((0, npad - n),))
    return pl.pallas_call(
        _kernel,
        grid=(npad // block,),
        out_shape=jax.ShapeDtypeStruct((npad,), x.dtype),
    )(x)[:n]


def asserted_kernel(x, block):
    n = x.shape[0]
    assert n % block == 0
    return pl.pallas_call(
        _kernel,
        grid=(n // block,),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def dequant_rows(x, scale):
    return x.astype(jnp.float32) * scale


def dequant_rows_q8(x, scale):
    return x.view(jnp.int8).astype(jnp.float32) * scale
