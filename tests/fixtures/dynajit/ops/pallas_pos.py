"""DJ4xx positives: truncating grid division, q8 variant drift, and a
kernel with no oracle test."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


def orphan_kernel(x, block):
    n = x.shape[0]
    return pl.pallas_call(  # DJ403: no test references this name
        _kernel,
        grid=(n // block,),  # DJ401: unguarded division truncates
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def scale_rows(x):
    return x * 2.0


def scale_rows_q8(x):
    return x * 2.0  # DJ402: "quantized" variant never touches int8


def pack_rows(x):
    return jnp.asarray(x, jnp.int8)  # DJ402: base fn doing q8 work


def pack_rows_q8(x):
    return jnp.asarray(x, jnp.int8)
