"""DJ5xx negatives: finally-owned releases, ownership hand-off, and
idempotent span double-end all pass clean."""


class Puller:
    def serve(self, table, transfer_id, wire):
        transfer = table.claim(transfer_id)
        try:
            wire.send_header(transfer.layout)
            wire.send_pages(transfer.page_ids)
        finally:
            transfer.release()  # exactly once, exception-safe
        return True

    def adopt(self, table, transfer_id):
        transfer = table.claim(transfer_id)
        self.owned = transfer  # ownership escapes: not this fn's leak
        return transfer

    def traced(self, tracer, table, transfer_id, wire):
        span = tracer.start_span("kv_transfer.serve")
        transfer = table.claim(transfer_id)
        try:
            wire.send_pages(transfer.page_ids)
            span.end(ok=True)  # idempotent: first end wins
        finally:
            span.end(ok=False)
            transfer.release()


class Router:
    def dispatch(self, breaker, client, body):
        if not breaker.try_acquire():
            return None
        try:
            out = client.send(body)
            breaker.record_success()
            return out
        finally:
            breaker.release_probe()  # verdict settled on every path
