"""DJ3xx negatives: the rebind-in-the-same-statement discipline and
explicit donation declarations pass clean."""

import functools

import jax


def rebound(buf, x):
    step = jax.jit(lambda b, v: (b + v, v), donate_argnums=(0,))
    buf, out = step(buf, x)
    return buf.sum() + out


class Engine:
    def _build_step(self):
        return jax.jit(lambda kv, t: (kv + t, t), donate_argnums=(0,))

    def __init__(self):
        self.kv_cache = None

    def step(self, tokens):
        fn = self._build_step()
        args = [self.kv_cache, tokens]
        self.kv_cache, out = fn(*args)  # rebound through the star call
        return out


@functools.partial(jax.jit, donate_argnums=())
def gather(kv_cache, idx):
    return kv_cache[idx]  # read-only intent declared explicitly


@functools.partial(jax.jit, donate_argnums=(0,))
def scatter(kv_cache, idx, blocks):
    return kv_cache.at[idx].set(blocks)
