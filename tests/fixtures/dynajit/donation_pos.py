"""DJ3xx positives: use-after-donate, stale donated attribute,
undeclared donation on a KV-pool parameter."""

import jax


def use_after_donate(buf, x):
    step = jax.jit(lambda b, v: b + v, donate_argnums=(0,))
    out = step(buf, x)
    return buf.sum() + out  # DJ301: buf was retired by the call


class Engine:
    def _build_step(self):
        return jax.jit(lambda kv, t: (kv + t, t), donate_argnums=(0,))

    def __init__(self):
        self.kv_cache = None
        self._step = self._build_step()

    def step(self, tokens):
        fn = self._build_step()
        out = fn(self.kv_cache, tokens)  # DJ302: donated attr not rebound
        return out


def kernel_no_declaration(kv_cache, idx):
    return kv_cache[idx]


WRAPPED = jax.jit(kernel_no_declaration)  # DJ303: kv param, no donate kw
