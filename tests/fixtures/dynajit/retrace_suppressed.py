"""DJ1xx suppressed: justified per-call construction."""

import jax


def one_shot_tool(x):
    fn = jax.jit(lambda v: v * 3)  # dynajit: disable=DJ102 -- offline CLI tool, runs once per invocation
    return fn(x)
