"""DJ1xx negatives: the blessed construction idioms pass clean."""

import functools

import jax


@jax.jit
def decorated(x):
    return x + 1


@functools.partial(jax.jit, static_argnames=("n",))
def decorated_static(x, n: int):
    return x * n


MODULE_FN = jax.jit(lambda x: x - 1)


class Runner:
    def __init__(self):
        self._fn = jax.jit(lambda x: x)  # attr store in __init__
        self._fns = {}
        self._caps = {}

    def _build_step(self, bucket):
        return jax.jit(lambda x: x + bucket)  # returned from a builder

    def _bucket_for(self, n):
        return 1 << max(0, n - 1).bit_length()

    def step(self, x, n: int):
        bucket = self._bucket_for(n)  # pow2-bucketed key
        fn = self._fns.get(bucket)
        if fn is None:
            fn = self._build_step(bucket)
            self._fns[bucket] = fn
        return fn(x)

    def capped(self, x, k: int):
        fn = self._caps.get(k)
        if fn is None:
            fn = jax.jit(lambda v: v + k)
            self._caps[k] = fn  # bounded: eviction below
            while len(self._caps) > 4:
                self._caps.pop(next(iter(self._caps)))
        return fn(x)

    def flagged(self, x, want: bool):
        fn = self._fns.get(want)
        if fn is None:
            fn = self._build_step(1)
            self._fns[want] = fn  # bool-annotated key: domain of 2
        return fn(x)
