"""DJ3xx suppressed: a justified undeclared-donation site."""

import jax


def legacy_kernel(kv_cache, idx):
    return kv_cache[idx]


WRAPPED = jax.jit(legacy_kernel)  # dynajit: disable=DJ303 -- vendored reference kernel kept verbatim for diffing
