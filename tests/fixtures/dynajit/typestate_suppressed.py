"""DJ5xx suppressed: a justified non-finally release."""


class Puller:
    def serve(self, table, transfer_id, wire):
        transfer = table.claim(transfer_id)  # dynajit: disable=DJ501 -- wire.send_* cannot raise here (in-memory test double)
        wire.send_pages(transfer.page_ids)
        transfer.release()
        return True
