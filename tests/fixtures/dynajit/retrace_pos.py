"""DJ1xx positives: jit in a loop, per-call jit, unbounded cache key."""

import jax


def jit_in_loop(batches):
    outs = []
    for batch in batches:
        fn = jax.jit(lambda x: x + 1)  # DJ101: fresh callable per iter
        outs.append(fn(batch))
    return outs


def per_call_immediate(x):
    return jax.jit(lambda v: v * 2)(x)  # DJ102: compiled every call


def per_call_local(x):
    fn = jax.jit(lambda v: v * 3)  # DJ102: local never stored
    return fn(x)


class Runner:
    def __init__(self):
        self._fns = {}

    def step(self, x, k: int):
        fn = self._fns.get(k)
        if fn is None:
            fn = jax.jit(lambda v: v + k)
            self._fns[k] = fn  # DJ103: raw param key, no eviction
        return fn(x)
