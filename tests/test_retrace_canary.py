"""Retrace canary: the runtime half of dynajit's DJ1xx static pass.

The compile listener (engine/model_runner.py, jax.monitoring) counts
every XLA backend compile into dynamo_jit_compiles_total{fn}. This tier
drives a mocker-free decode loop — varying batch occupancy, sequence
lengths, speculation on and off — and pins the two properties the
checked-in jit-signature registry (tools/dynajit/signatures/) predicts:

  * warmup compiles EXACTLY one executable per (entry point, bounded
    cache key) combination exercised — no hidden variants;
  * steady state compiles NOTHING: occupancy, lengths, and sampling
    params are data, not cache keys.

A regression that adds a per-request value to a jit key (the DJ1xx
hazard class) fails the steady-state assertion here even if dynajit's
static view was evaded.
"""

import json
import pathlib

import numpy as np
import pytest

from dynamo_tpu.engine import ModelRunner, RunnerConfig
from dynamo_tpu.models import get_config
from dynamo_tpu.parallel import MeshConfig, make_mesh
from dynamo_tpu.runtime.metrics import REGISTRY

REGISTRY_PATH = (pathlib.Path(__file__).parent.parent / "tools" /
                 "dynajit" / "signatures" / "jit_surface.json")

# Entry-point labels the compile listener attributes serving compiles to.
SCOPES = ("decode", "decode_multi", "decode_spec", "prefill",
          "prefill_batch", "prefill_ring", "embed", "unscoped")


def _snapshot() -> dict:
    return {fn: REGISTRY.get_sample_value("dynamo_jit_compiles_total",
                                          {"fn": fn}) or 0.0
            for fn in SCOPES}


def _delta(before: dict, after: dict) -> dict:
    return {fn: after[fn] - before[fn] for fn in SCOPES
            if after[fn] != before[fn]}


def _runner():
    return ModelRunner(
        get_config("tiny-test"),
        RunnerConfig(page_size=4, num_pages=64, max_batch=4,
                     max_pages_per_seq=16, prefill_buckets=(8, 16, 32)),
        make_mesh(MeshConfig()),
        seed=0,
    )


class TestRetraceCanary:
    def test_registry_predicts_bounded_serving_surface(self):
        """Every call-form jit site in the runner's serving methods has
        a bounded disposition in the checked-in registry (a dict cache
        or an attribute — never per-call): the static prediction the
        runtime assertions below are checked against."""
        assert REGISTRY_PATH.exists(), (
            "jit-signature registry missing; run "
            "`python -m tools.dynajit --registry-update`")
        sites = json.loads(REGISTRY_PATH.read_text())["sites"]
        runner_sites = [
            s for s in sites
            if s["file"].endswith("engine/model_runner.py")
            and s["scope"].startswith("ModelRunner.")
            and s["scope"].split(".")[-1] not in ("__init__", "reshard")
            and s["form"] == "call"]
        assert runner_sites, "registry lost the runner's jit surface"
        for site in runner_sites:
            assert site["disposition"].startswith(("cached:", "attr:",
                                                   "returned")), site

    def test_steady_state_decode_compiles_are_bounded(self):
        pre = _snapshot()
        runner = _runner()
        if sum(_snapshot().values()) == sum(pre.values()):
            # Engine construction compiles param/KV init; observing
            # nothing means this jax does not emit the backend-compile
            # monitoring event (the counter is inert, not broken).
            pytest.skip("jax.monitoring compile events not observed")
        b, p = 4, 16
        base = _snapshot()

        def prefill(tokens):
            runner.prefill_chunk(
                np.asarray(tokens, np.int32), 0,
                np.arange(1, p + 1, dtype=np.int32) % runner.config.num_pages,
                len(tokens), (0.0, 1.0, 0, 0))

        def decode(active, kv_lens, seeds=0):
            runner.decode(
                np.zeros(b, np.int32), np.asarray(kv_lens, np.int32) - 1,
                np.tile(np.arange(1, p + 1, dtype=np.int32)
                        % runner.config.num_pages, (b, 1)),
                np.asarray(kv_lens, np.int32),
                np.asarray(active, bool), np.ones(b, np.float32),
                np.ones(b, np.float32), np.zeros(b, np.int32),
                np.full(b, seeds, np.uint32))

        def spec(kv_lens):
            runner.decode_spec(
                np.zeros(b, np.int32), np.ones((b, 2), np.int32),
                np.asarray(kv_lens, np.int32) - 1,
                np.tile(np.arange(1, p + 1, dtype=np.int32)
                        % runner.config.num_pages, (b, 1)),
                np.asarray(kv_lens, np.int32), np.ones(b, bool),
                np.ones(b, np.float32), np.ones(b, np.float32),
                np.zeros(b, np.int32), np.zeros(b, np.uint32))

        # -- warmup: touch each (entry, cache-key) combo once ----------
        prefill([1] * 5)        # bucket 8
        prefill([1] * 12)       # bucket 16
        decode([1, 1, 1, 1], [4, 4, 4, 4])
        spec([6, 6, 6, 6])
        warm = _delta(base, _snapshot())
        # Registry-predicted key space for the combos exercised:
        # decode -> attr:_decode_fn (1), prefill -> cached:_prefill_fns
        # keyed by bucket (2 buckets touched), decode_spec ->
        # cached:_decode_spec_fns keyed (t, want_logits) (1 combo).
        assert warm.get("decode") == 1, warm
        assert warm.get("prefill") == 2, warm
        assert warm.get("decode_spec") == 1, warm

        # -- steady state: occupancy/lengths/seeds are DATA ------------
        steady = _snapshot()
        prefill([2] * 7)                 # bucket 8 again
        prefill([3] * 15)                # bucket 16 again
        for step in range(6):
            active = [1, 1, 1, 1] if step % 2 == 0 else [1, 0, 1, 0]
            lens = [4 + step, 5 + step, 4, 6]
            decode(active, lens, seeds=step)
        spec([12, 13, 14, 15])
        assert _delta(steady, _snapshot()) == {}, (
            "steady-state decode recompiled: a per-request value leaked "
            "into a jit cache key (DJ1xx hazard) — "
            f"{_delta(steady, _snapshot())}")

    def test_prewarm_compiles_exactly_the_predicted_key_space(self):
        """The fast-start pre-warm pass (docs/elasticity.md): prewarm()
        compiles the registry-predicted steady-state surface — decode
        (one key), EVERY prefill bucket, the configured spec-verify
        combo — and NOTHING after it compiles again: a warm-cache
        arrival that replays these from the persistent compile cache
        serves its whole steady state without a single trace."""
        pre = _snapshot()
        runner = _runner()
        if sum(_snapshot().values()) == sum(pre.values()):
            pytest.skip("jax.monitoring compile events not observed")
        base = _snapshot()
        runner.prewarm(spec_widths=[2])
        warm = _delta(base, _snapshot())
        assert warm.get("decode") == 1, warm
        assert warm.get("prefill") == len(runner.config.prefill_buckets), \
            warm
        assert warm.get("decode_spec") == 1, warm

        # prewarm is idempotent — the warm-arrival shape
        again = _snapshot()
        runner.prewarm(spec_widths=[2])
        assert _delta(again, _snapshot()) == {}, _delta(again, _snapshot())

        # steady state after prewarm compiles NOTHING: every bucket,
        # varying occupancy/lengths/seeds, and the spec-verify path
        b, p = 4, 16
        steady = _snapshot()
        for n in (5, 12, 20):  # lands in buckets 8, 16, 32
            runner.prefill_chunk(
                np.full(n, 2, np.int32), 0,
                np.arange(1, p + 1, dtype=np.int32)
                % runner.config.num_pages,
                n, (0.0, 1.0, 0, 0))
        for step in range(4):
            kv = np.asarray([4 + step, 5, 6, 4 + step], np.int32)
            runner.decode(
                np.zeros(b, np.int32), kv - 1,
                np.tile(np.arange(1, p + 1, dtype=np.int32)
                        % runner.config.num_pages, (b, 1)),
                kv, np.asarray([1, step % 2, 1, 1], bool),
                np.ones(b, np.float32), np.ones(b, np.float32),
                np.zeros(b, np.int32), np.full(b, step, np.uint32))
        runner.decode_spec(
            np.zeros(b, np.int32), np.ones((b, 2), np.int32),
            np.full(b, 7, np.int32),
            np.tile(np.arange(1, p + 1, dtype=np.int32)
                    % runner.config.num_pages, (b, 1)),
            np.full(b, 8, np.int32), np.ones(b, bool),
            np.ones(b, np.float32), np.ones(b, np.float32),
            np.zeros(b, np.int32), np.zeros(b, np.uint32))
        assert _delta(steady, _snapshot()) == {}, (
            "post-prewarm steady state recompiled — the pre-warm pass "
            "missed part of the predicted key space: "
            f"{_delta(steady, _snapshot())}")
