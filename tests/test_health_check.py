"""Canary health-check tests (ref contract: lib/runtime/src/health_check.rs —
synthetic requests to idle endpoints after canary_wait_time; failures mark
unhealthy and eventually deregister)."""

import asyncio
import uuid

from dynamo_tpu.runtime import (
    DistributedRuntime,
    HealthCheckManager,
    PushRouter,
    RuntimeConfig,
)


def _cfg(cluster):
    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = cluster
    cfg.request_plane = "tcp"
    cfg.tcp_host = "127.0.0.1"
    cfg.event_plane = "mem"
    cfg.system_enabled = False
    return cfg


class TestHealthCheck:
    def test_canary_probes_idle_endpoint(self, run):
        async def body():
            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()
            seen = []

            async def handler(req, ctx):
                seen.append(req)
                yield {"ok": True}

            ep = rt.namespace("t").component("w").endpoint("generate")
            served = await ep.serve_endpoint(
                handler, health_check_payload={"canary": True})
            manager = HealthCheckManager(rt, canary_wait_time=0.0,
                                         canary_timeout=2.0)
            await manager.check_now()
            assert seen == [{"canary": True}]
            assert served.healthy()
            await rt.shutdown()

        run(body())

    def test_active_endpoint_not_probed(self, run):
        async def body():
            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()
            seen = []

            async def handler(req, ctx):
                seen.append(req)
                yield {"ok": True}

            ep = rt.namespace("t").component("w").endpoint("generate")
            await ep.serve_endpoint(
                handler, health_check_payload={"canary": True})
            client = ep.client()
            await client.wait_for_instances(1, timeout=5.0)
            router = PushRouter(client, mode="round_robin")
            out = [x async for x in router.generate({"real": 1})]
            assert out == [{"ok": True}]
            manager = HealthCheckManager(rt, canary_wait_time=60.0)
            await manager.check_now()
            assert seen == [{"real": 1}]  # no canary: traffic is recent
            await rt.shutdown()

        run(body())

    def test_failing_canary_marks_unhealthy_and_deregisters(self, run):
        async def body():
            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()

            async def handler(req, ctx):
                raise RuntimeError("wedged")
                yield  # pragma: no cover

            ep = rt.namespace("t").component("w").endpoint("generate")
            served = await ep.serve_endpoint(
                handler, health_check_payload={"canary": True})
            client = ep.client()
            await client.wait_for_instances(1, timeout=5.0)

            manager = HealthCheckManager(rt, canary_wait_time=0.0,
                                         canary_timeout=2.0, max_failures=2)
            await manager.check_now()
            assert not served.healthy()
            await manager.check_now()  # second failure -> deregister
            deadline = asyncio.get_running_loop().time() + 5.0
            while client.instance_ids():
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            await rt.shutdown()

        run(body())

    def test_recovered_endpoint_reregisters(self, run):
        """A deregistered endpoint whose canaries start passing again gets
        its discovery record re-advertised (saturation, not death)."""

        async def body():
            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()
            wedged = {"on": True}

            async def handler(req, ctx):
                if wedged["on"]:
                    raise RuntimeError("saturated")
                yield {"ok": True}

            ep = rt.namespace("t").component("w").endpoint("generate")
            served = await ep.serve_endpoint(
                handler, health_check_payload={"canary": True})
            client = ep.client()
            await client.wait_for_instances(1, timeout=5.0)
            manager = HealthCheckManager(rt, canary_wait_time=0.0,
                                         canary_timeout=2.0, max_failures=1)
            await manager.check_now()  # fails -> deregistered
            deadline = asyncio.get_running_loop().time() + 5.0
            while client.instance_ids():
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            wedged["on"] = False
            await manager.check_now()  # passes -> re-registered
            assert served.healthy()
            await client.wait_for_instances(1, timeout=5.0)
            await rt.shutdown()

        run(body())
