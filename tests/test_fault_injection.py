"""Fault-injection tier 2 (ref: tests/fault_tolerance/{etcd_ha,hardware}/):
scripted infrastructure faults with RECOVERY assertions, not just
survival.

  1. discovery outage: SIGKILL the etcd stub mid-serving, restart an
     EMPTY one on the same port — workers must re-grant leases and
     re-register (runtime._recover_lease), the frontend must rebuild its
     pipeline, and chat must flow again.
  2. network partition router->worker: black-hole one worker's request
     plane (SIGSTOP) — the router must mark it faulted and migrate the
     in-flight stream to the peer; after SIGCONT the worker serves again.
  3. router-replica restart with journal replay: a restarted KV-routed
     frontend converges from the durable journal and keeps serving
     (extends test_event_journal's e2e with mid-traffic restart).
  4. latency injection through the fault service's TCP delay proxy —
     a fault only expressible via the service API (no signal slows a
     link), healed live.

All faults are driven through the fault-injection SERVICE
(dynamo_tpu/faults — the reusable HTTP API the reference ships as
tests/fault_tolerance/hardware/fault_injection_service/), not raw
os.kill: the tests prove the service's agent semantics and the
runtime's recovery in one pass.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time
import uuid

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("DYNT_SKIP_CHAOS") == "1",
    reason="chaos tier disabled")

from tests.chaos_util import (  # noqa: E402
    REPO,
    chat as _chat,
    kill_all as _kill_all,
    spawn as _spawn,
    wait_models as _wait_models,
    wait_port as _wait_port,
)

import contextlib  # noqa: E402

from dynamo_tpu.faults import FaultClient, FaultInjectionService  # noqa: E402


@contextlib.asynccontextmanager
async def fault_service():
    svc = await FaultInjectionService().start()
    client = FaultClient(f"http://127.0.0.1:{svc.port}")
    try:
        yield client
    finally:
        await client.close()
        await svc.close()


class TestDiscoveryOutage:
    def test_etcd_outage_lease_regrant_and_reregister(self, run, tmp_path):
        """Kill the discovery backend mid-serving; restart it EMPTY on
        the same port. Worker + frontend must re-grant leases,
        re-register instances/cards, and serve again — the etcd-HA
        failover contract."""
        import aiohttp

        salt = uuid.uuid4().int
        etcd_port = 20100 + (salt % 300)
        fe_port = 20450 + (salt % 300)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "DYNT_DISCOVERY_BACKEND": "etcd",
            "DYNT_ETCD_ENDPOINTS": f"http://127.0.0.1:{etcd_port}",
            "DYNT_REQUEST_PLANE": "tcp",
            "DYNT_EVENT_PLANE": "zmq",
            "DYNT_LEASE_TTL_SECS": "2.0",
            "DYNT_SYSTEM_ENABLED": "false",
            "DYNT_LOG_LEVEL": "INFO",
        })
        logs = tmp_path / "logs"
        logs.mkdir()
        stub = _spawn("tests/etcd_stub_server.py", str(etcd_port),
                      env=env, log_path=logs / "etcd1.log", script=True)
        assert _wait_port(etcd_port), "etcd stub never bound"
        worker = _spawn("dynamo_tpu.mocker", "--model-name", "ha-model",
                        env=env, log_path=logs / "worker.log")
        fe = _spawn("dynamo_tpu.frontend", "--port", str(fe_port),
                    env=env, log_path=logs / "fe.log")
        procs = [stub, worker, fe]
        respawned: list[int] = []  # pids the fault service spawned
        try:
            async def body():
                base = f"http://127.0.0.1:{fe_port}"
                async with aiohttp.ClientSession() as session, \
                        fault_service() as faults:
                    assert await _wait_models(session, base, "ha-model"), (
                        (logs / "fe.log").read_text()[-2000:])
                    await _chat(session, base, "ha-model", "before")

                    # OUTAGE: the service's kill_respawn scenario — kill
                    # the discovery backend, hold past the lease TTL so
                    # every lease is gone, then respawn an EMPTY stub on
                    # the same port (one atomic server-side scenario).
                    await faults.register(
                        "etcd", stub.pid,
                        argv=[sys.executable, "-u",
                              "tests/etcd_stub_server.py", str(etcd_port)],
                        env=env, cwd=REPO, log=str(logs / "etcd2.log"))
                    out = await faults.run_scenario(
                        "kill_respawn", target="etcd", down_ms=4000)
                    assert [s["type"] for s in out["steps"]] == \
                        ["kill", "respawn"]
                    respawned.append(out["steps"][1]["detail"]["pid"])
                    assert await asyncio.to_thread(_wait_port, etcd_port)

                    # RECOVERY: the worker re-grants + re-registers; the
                    # frontend's watch re-lists and rebuilds the
                    # pipeline; chat flows again.
                    assert await _wait_models(session, base, "ha-model",
                                              timeout=60.0), (
                        "model never re-registered after outage:\n"
                        + (logs / "worker.log").read_text()[-2000:])
                    out = await _chat(session, base, "ha-model", "after")
                    assert out
                    # _recover_lease ran in the WORKER (subprocess stdout
                    # is block-buffered; poll for the flush).
                    deadline = time.monotonic() + 30
                    while time.monotonic() < deadline:
                        if "re-registered" in (logs / "worker.log"
                                               ).read_text():
                            break
                        await asyncio.sleep(0.5)
                    assert "re-registered" in (logs / "worker.log"
                                               ).read_text()

            run(body(), timeout=240.0)
        finally:
            _kill_all(procs)
            for pid in respawned:
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass


class TestNetworkPartition:
    def test_partitioned_worker_marked_and_stream_migrates(self, run,
                                                           tmp_path):
        """SIGSTOP one of two workers (a black-holed peer: connections
        hang, nothing errors) mid-stream. The router must fault-mark it
        and Migration must finish the stream on the peer; SIGCONT heals
        the partition and the worker serves again."""
        import aiohttp

        salt = uuid.uuid4().int
        fe_port = 20800 + (salt % 300)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "DYNT_DISCOVERY_BACKEND": "file",
            "DYNT_DISCOVERY_PATH": str(tmp_path / "disc"),
            "DYNT_REQUEST_PLANE": "tcp",
            "DYNT_EVENT_PLANE": "zmq",
            "DYNT_LEASE_TTL_SECS": "2.0",
            "DYNT_REQUEST_TIMEOUT_SECS": "8.0",
            "DYNT_STREAM_IDLE_TIMEOUT_SECS": "5.0",
            "DYNT_SYSTEM_ENABLED": "false",
            "DYNT_LOG_LEVEL": "INFO",
        })
        logs = tmp_path / "logs"
        logs.mkdir()
        w1 = _spawn("dynamo_tpu.mocker", "--model-name", "part-model",
                    "--speedup-ratio", "2.0", env=env,
                    log_path=logs / "w1.log")
        w2 = _spawn("dynamo_tpu.mocker", "--model-name", "part-model",
                    "--speedup-ratio", "2.0", env=env,
                    log_path=logs / "w2.log")
        fe = _spawn("dynamo_tpu.frontend", "--port", str(fe_port),
                    env=env, log_path=logs / "fe.log")
        procs = [w1, w2, fe]
        try:
            async def stream_tokens(session, base, kill_cb=None):
                got = 0
                async with session.post(
                        f"{base}/v1/chat/completions",
                        json={"model": "part-model",
                              "messages": [{"role": "user",
                                            "content": "partition test"}],
                              "max_tokens": 40, "stream": True},
                        timeout=120) as resp:
                    assert resp.status == 200
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            break
                        delta = json.loads(payload)["choices"][0]
                        if delta.get("delta", {}).get("content"):
                            got += 1
                            if got == 5 and kill_cb is not None:
                                kill_cb()
                        if delta.get("finish_reason") is not None:
                            return got, delta["finish_reason"]
                return got, None

            async def body():
                base = f"http://127.0.0.1:{fe_port}"
                async with aiohttp.ClientSession() as session, \
                        fault_service() as faults:
                    assert await _wait_models(session, base, "part-model")
                    await faults.register("w1", w1.pid)
                    # Two concurrent streams (round-robin-ish spread);
                    # black-hole w1 through the service once tokens flow.
                    frozen = {"done": False, "fault_id": None,
                              "task": None}

                    async def _pause():
                        fault = await faults.inject("pause", target="w1")
                        frozen["fault_id"] = fault["id"]

                    def freeze():
                        if not frozen["done"]:
                            frozen["done"] = True
                            frozen["task"] = \
                                asyncio.get_running_loop().create_task(
                                    _pause())

                    a, b = await asyncio.gather(
                        stream_tokens(session, base, kill_cb=freeze),
                        stream_tokens(session, base, kill_cb=freeze))
                    # Surface any pause failure with its root cause (a
                    # swallowed task exception would otherwise die later
                    # as an opaque fault_id assert).
                    assert frozen["task"] is not None
                    await frozen["task"]
                    # Migration must complete BOTH streams despite the
                    # black-holed worker (request timeout -> fault mark
                    # -> replay on the peer).
                    assert a == (40, "length"), a
                    assert b == (40, "length"), b
                    # New traffic keeps flowing while partitioned.
                    out = await _chat(session, base, "part-model",
                                      "during", max_tokens=6, timeout=90)
                    assert out
                    # Heal through the service: the pause fault's heal is
                    # SIGCONT; after the lease recovers it serves again
                    # (send a few requests — at least one must land on
                    # the thawed worker without error).
                    assert frozen["fault_id"] is not None
                    healed = await faults.heal(frozen["fault_id"])
                    assert healed["state"] == "healed"
                    await asyncio.sleep(3.0)
                    for i in range(4):
                        await _chat(session, base, "part-model",
                                    f"healed-{i}", max_tokens=4,
                                    timeout=90)

            run(body(), timeout=300.0)
        finally:
            if w1.poll() is None:
                try:
                    os.kill(w1.pid, signal.SIGCONT)
                except ProcessLookupError:
                    pass
            _kill_all(procs)


class TestRouterReplicaRestart:
    def test_kv_frontend_restarts_with_journal_replay(self, run, tmp_path):
        """A KV-routed frontend dies mid-traffic and a replacement comes
        up on the same port with the SAME durable journal: it must
        replay the KV index state and keep serving (JetStream-mode
        router-replica failover)."""
        import aiohttp

        salt = uuid.uuid4().int
        fe_port = 21150 + (salt % 300)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "DYNT_DISCOVERY_BACKEND": "file",
            "DYNT_DISCOVERY_PATH": str(tmp_path / "disc"),
            "DYNT_REQUEST_PLANE": "tcp",
            "DYNT_EVENT_PLANE": "journal",
            "DYNT_EVENT_JOURNAL_PATH": str(tmp_path / "journal"),
            "DYNT_LEASE_TTL_SECS": "2.0",
            "DYNT_SYSTEM_ENABLED": "false",
            "DYNT_LOG_LEVEL": "INFO",
        })
        logs = tmp_path / "logs"
        logs.mkdir()
        worker = _spawn("dynamo_tpu.mocker", "--model-name", "jr-model",
                        env=env, log_path=logs / "worker.log")
        fe = _spawn("dynamo_tpu.frontend", "--port", str(fe_port),
                    "--router-mode", "kv", env=env,
                    log_path=logs / "fe1.log")
        procs = [worker, fe]
        respawned: list[int] = []
        try:
            async def body():
                base = f"http://127.0.0.1:{fe_port}"
                async with aiohttp.ClientSession() as session, \
                        fault_service() as faults:
                    assert await _wait_models(session, base, "jr-model")
                    # Build KV state (prefix-cache events land in the
                    # journal).
                    shared = "journal replay prefix " * 3
                    for i in range(4):
                        await _chat(session, base, "jr-model",
                                    shared + str(i))
                    # Router replica dies hard mid-service, and the crash
                    # tears the journal tail (corrupt_file appends a
                    # garbage half-frame — exactly what a publisher dying
                    # mid-write leaves behind). Replay must skip the torn
                    # tail, not crash on it.
                    await faults.register(
                        "frontend", fe.pid,
                        argv=[sys.executable, "-u", "-m",
                              "dynamo_tpu.frontend", "--port",
                              str(fe_port), "--router-mode", "kv"],
                        env=env, cwd=REPO, log=str(logs / "fe2.log"))
                    await faults.inject("kill", target="frontend")
                    journal_logs = sorted(
                        (tmp_path / "journal").rglob("*.log"))
                    assert journal_logs, "no journal files written"
                    await faults.inject(
                        "corrupt_file", path=str(journal_logs[0]),
                        mode="append_garbage", bytes=48)
                    # ...replacement replays the journal on the same port.
                    out = await faults.inject("respawn",
                                              target="frontend")
                    respawned.append(out["detail"]["pid"])
                    assert await _wait_models(session, base, "jr-model",
                                              timeout=60.0)
                    out = await _chat(session, base, "jr-model",
                                      shared + "after")
                    assert out
                    # The replay actually happened: the new router's KV
                    # indexer applied journaled events before serving.
                    deadline = time.monotonic() + 20
                    while time.monotonic() < deadline:
                        text = (logs / "fe2.log").read_text()
                        if "journal replay:" in text:
                            break
                        await asyncio.sleep(0.5)
                    assert "journal replay:" in (logs / "fe2.log"
                                                 ).read_text()

            run(body(), timeout=240.0)
        finally:
            _kill_all(procs)
            for pid in respawned:
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass


class TestDelayInjection:
    def test_delay_proxy_fault_and_heal(self, run, tmp_path):
        """The service's TCP delay proxy — a fault no signal can
        express (VERDICT r4 item 7's 'one new scenario only expressible
        via the API'): traffic through the proxy gains the configured
        latency; healing the fault closes the listener."""
        import aiohttp

        salt = uuid.uuid4().int
        fe_port = 21500 + (salt % 300)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "DYNT_DISCOVERY_BACKEND": "file",
            "DYNT_DISCOVERY_PATH": str(tmp_path / "disc"),
            "DYNT_REQUEST_PLANE": "tcp",
            "DYNT_EVENT_PLANE": "mem",
            "DYNT_SYSTEM_ENABLED": "false",
        })
        logs = tmp_path / "logs"
        logs.mkdir()
        worker = _spawn("dynamo_tpu.mocker", "--model-name", "dl-model",
                        "--speedup-ratio", "100.0", env=env,
                        log_path=logs / "worker.log")
        fe = _spawn("dynamo_tpu.frontend", "--port", str(fe_port),
                    env=env, log_path=logs / "fe.log")
        procs = [worker, fe]
        try:
            async def body():
                base = f"http://127.0.0.1:{fe_port}"
                async with aiohttp.ClientSession() as session, \
                        fault_service() as faults:
                    assert await _wait_models(session, base, "dl-model")
                    await _chat(session, base, "dl-model", "warm")
                    t0 = time.monotonic()
                    await _chat(session, base, "dl-model", "direct")
                    direct_s = time.monotonic() - t0

                    fault = await faults.inject(
                        "delay", target_host="127.0.0.1",
                        target_port=fe_port, delay_ms=150.0)
                    proxy_base = ("http://127.0.0.1:"
                                  f"{fault['detail']['listen_port']}")
                    t0 = time.monotonic()
                    await _chat(session, proxy_base, "dl-model",
                                "delayed")
                    delayed_s = time.monotonic() - t0
                    # request + response each pay >=150ms
                    assert delayed_s >= direct_s + 0.25, (direct_s,
                                                          delayed_s)

                    healed = await faults.heal(fault["id"])
                    assert healed["state"] == "healed"
                    # listener gone: a fresh connection is refused
                    with pytest.raises(aiohttp.ClientConnectionError):
                        await _chat(session, proxy_base, "dl-model",
                                    "after-heal", timeout=5)
                    # the real endpoint is untouched
                    await _chat(session, base, "dl-model", "fine")

            run(body(), timeout=180.0)
        finally:
            _kill_all(procs)
