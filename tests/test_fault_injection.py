"""Fault-injection tier 2 (ref: tests/fault_tolerance/{etcd_ha,hardware}/):
scripted infrastructure faults with RECOVERY assertions, not just
survival.

  1. discovery outage: SIGKILL the etcd stub mid-serving, restart an
     EMPTY one on the same port — workers must re-grant leases and
     re-register (runtime._recover_lease), the frontend must rebuild its
     pipeline, and chat must flow again.
  2. network partition router->worker: black-hole one worker's request
     plane (SIGSTOP) — the router must mark it faulted and migrate the
     in-flight stream to the peer; after SIGCONT the worker serves again.
  3. router-replica restart with journal replay: a restarted KV-routed
     frontend converges from the durable journal and keeps serving
     (extends test_event_journal's e2e with mid-traffic restart).
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time
import uuid

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("DYNT_SKIP_CHAOS") == "1",
    reason="chaos tier disabled")

from tests.chaos_util import (  # noqa: E402
    REPO,
    chat as _chat,
    kill_all as _kill_all,
    spawn as _spawn,
    wait_models as _wait_models,
    wait_port as _wait_port,
)


class TestDiscoveryOutage:
    def test_etcd_outage_lease_regrant_and_reregister(self, run, tmp_path):
        """Kill the discovery backend mid-serving; restart it EMPTY on
        the same port. Worker + frontend must re-grant leases,
        re-register instances/cards, and serve again — the etcd-HA
        failover contract."""
        import aiohttp

        salt = uuid.uuid4().int
        etcd_port = 20100 + (salt % 300)
        fe_port = 20450 + (salt % 300)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "DYNT_DISCOVERY_BACKEND": "etcd",
            "DYNT_ETCD_ENDPOINTS": f"http://127.0.0.1:{etcd_port}",
            "DYNT_REQUEST_PLANE": "tcp",
            "DYNT_EVENT_PLANE": "zmq",
            "DYNT_LEASE_TTL_SECS": "2.0",
            "DYNT_SYSTEM_ENABLED": "false",
            "DYNT_LOG_LEVEL": "INFO",
        })
        logs = tmp_path / "logs"
        logs.mkdir()
        stub = _spawn("tests/etcd_stub_server.py", str(etcd_port),
                      env=env, log_path=logs / "etcd1.log", script=True)
        assert _wait_port(etcd_port), "etcd stub never bound"
        worker = _spawn("dynamo_tpu.mocker", "--model-name", "ha-model",
                        env=env, log_path=logs / "worker.log")
        fe = _spawn("dynamo_tpu.frontend", "--port", str(fe_port),
                    env=env, log_path=logs / "fe.log")
        procs = [stub, worker, fe]
        try:
            async def body():
                nonlocal stub
                base = f"http://127.0.0.1:{fe_port}"
                async with aiohttp.ClientSession() as session:
                    assert await _wait_models(session, base, "ha-model"), (
                        (logs / "fe.log").read_text()[-2000:])
                    await _chat(session, base, "ha-model", "before")

                    # OUTAGE: kill the discovery backend, wait past the
                    # lease TTL so every lease is gone, then restart an
                    # EMPTY stub on the same port.
                    os.kill(stub.pid, signal.SIGKILL)
                    stub.wait(timeout=10)
                    await asyncio.sleep(4.0)  # > 2s TTL: leases expire
                    stub = _spawn("tests/etcd_stub_server.py",
                                  str(etcd_port), env=env,
                                  log_path=logs / "etcd2.log", script=True)
                    procs.append(stub)
                    assert await asyncio.to_thread(_wait_port, etcd_port)

                    # RECOVERY: the worker re-grants + re-registers; the
                    # frontend's watch re-lists and rebuilds the
                    # pipeline; chat flows again.
                    assert await _wait_models(session, base, "ha-model",
                                              timeout=60.0), (
                        "model never re-registered after outage:\n"
                        + (logs / "worker.log").read_text()[-2000:])
                    out = await _chat(session, base, "ha-model", "after")
                    assert out
                    # _recover_lease ran in the WORKER (subprocess stdout
                    # is block-buffered; poll for the flush).
                    deadline = time.monotonic() + 30
                    while time.monotonic() < deadline:
                        if "re-registered" in (logs / "worker.log"
                                               ).read_text():
                            break
                        await asyncio.sleep(0.5)
                    assert "re-registered" in (logs / "worker.log"
                                               ).read_text()

            run(body(), timeout=240.0)
        finally:
            _kill_all(procs)


class TestNetworkPartition:
    def test_partitioned_worker_marked_and_stream_migrates(self, run,
                                                           tmp_path):
        """SIGSTOP one of two workers (a black-holed peer: connections
        hang, nothing errors) mid-stream. The router must fault-mark it
        and Migration must finish the stream on the peer; SIGCONT heals
        the partition and the worker serves again."""
        import aiohttp

        salt = uuid.uuid4().int
        fe_port = 20800 + (salt % 300)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "DYNT_DISCOVERY_BACKEND": "file",
            "DYNT_DISCOVERY_PATH": str(tmp_path / "disc"),
            "DYNT_REQUEST_PLANE": "tcp",
            "DYNT_EVENT_PLANE": "zmq",
            "DYNT_LEASE_TTL_SECS": "2.0",
            "DYNT_REQUEST_TIMEOUT_SECS": "8.0",
            "DYNT_STREAM_IDLE_TIMEOUT_SECS": "5.0",
            "DYNT_SYSTEM_ENABLED": "false",
            "DYNT_LOG_LEVEL": "INFO",
        })
        logs = tmp_path / "logs"
        logs.mkdir()
        w1 = _spawn("dynamo_tpu.mocker", "--model-name", "part-model",
                    "--speedup-ratio", "2.0", env=env,
                    log_path=logs / "w1.log")
        w2 = _spawn("dynamo_tpu.mocker", "--model-name", "part-model",
                    "--speedup-ratio", "2.0", env=env,
                    log_path=logs / "w2.log")
        fe = _spawn("dynamo_tpu.frontend", "--port", str(fe_port),
                    env=env, log_path=logs / "fe.log")
        procs = [w1, w2, fe]
        try:
            async def stream_tokens(session, base, kill_cb=None):
                got = 0
                async with session.post(
                        f"{base}/v1/chat/completions",
                        json={"model": "part-model",
                              "messages": [{"role": "user",
                                            "content": "partition test"}],
                              "max_tokens": 40, "stream": True},
                        timeout=120) as resp:
                    assert resp.status == 200
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            break
                        delta = json.loads(payload)["choices"][0]
                        if delta.get("delta", {}).get("content"):
                            got += 1
                            if got == 5 and kill_cb is not None:
                                kill_cb()
                        if delta.get("finish_reason") is not None:
                            return got, delta["finish_reason"]
                return got, None

            async def body():
                base = f"http://127.0.0.1:{fe_port}"
                async with aiohttp.ClientSession() as session:
                    assert await _wait_models(session, base, "part-model")
                    # Two concurrent streams (round-robin-ish spread);
                    # freeze w1 once tokens flow.
                    frozen = {"done": False}

                    def freeze():
                        if not frozen["done"]:
                            os.kill(w1.pid, signal.SIGSTOP)
                            frozen["done"] = True

                    a, b = await asyncio.gather(
                        stream_tokens(session, base, kill_cb=freeze),
                        stream_tokens(session, base, kill_cb=freeze))
                    # Migration must complete BOTH streams despite the
                    # black-holed worker (request timeout -> fault mark
                    # -> replay on the peer).
                    assert a == (40, "length"), a
                    assert b == (40, "length"), b
                    # New traffic keeps flowing while partitioned.
                    out = await _chat(session, base, "part-model",
                                      "during", max_tokens=6, timeout=90)
                    assert out
                    # Heal: the worker thaws; after its lease recovers it
                    # serves again (send a few requests — at least one
                    # must land on the thawed worker without error).
                    os.kill(w1.pid, signal.SIGCONT)
                    await asyncio.sleep(3.0)
                    for i in range(4):
                        await _chat(session, base, "part-model",
                                    f"healed-{i}", max_tokens=4,
                                    timeout=90)

            run(body(), timeout=300.0)
        finally:
            if w1.poll() is None:
                try:
                    os.kill(w1.pid, signal.SIGCONT)
                except ProcessLookupError:
                    pass
            _kill_all(procs)


class TestRouterReplicaRestart:
    def test_kv_frontend_restarts_with_journal_replay(self, run, tmp_path):
        """A KV-routed frontend dies mid-traffic and a replacement comes
        up on the same port with the SAME durable journal: it must
        replay the KV index state and keep serving (JetStream-mode
        router-replica failover)."""
        import aiohttp

        salt = uuid.uuid4().int
        fe_port = 21150 + (salt % 300)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "DYNT_DISCOVERY_BACKEND": "file",
            "DYNT_DISCOVERY_PATH": str(tmp_path / "disc"),
            "DYNT_REQUEST_PLANE": "tcp",
            "DYNT_EVENT_PLANE": "journal",
            "DYNT_EVENT_JOURNAL_PATH": str(tmp_path / "journal"),
            "DYNT_LEASE_TTL_SECS": "2.0",
            "DYNT_SYSTEM_ENABLED": "false",
            "DYNT_LOG_LEVEL": "INFO",
        })
        logs = tmp_path / "logs"
        logs.mkdir()
        worker = _spawn("dynamo_tpu.mocker", "--model-name", "jr-model",
                        env=env, log_path=logs / "worker.log")
        fe = _spawn("dynamo_tpu.frontend", "--port", str(fe_port),
                    "--router-mode", "kv", env=env,
                    log_path=logs / "fe1.log")
        procs = [worker, fe]
        try:
            async def body():
                base = f"http://127.0.0.1:{fe_port}"
                async with aiohttp.ClientSession() as session:
                    assert await _wait_models(session, base, "jr-model")
                    # Build KV state (prefix-cache events land in the
                    # journal).
                    shared = "journal replay prefix " * 3
                    for i in range(4):
                        await _chat(session, base, "jr-model",
                                    shared + str(i))
                    # Router replica dies hard mid-service...
                    os.kill(fe.pid, signal.SIGKILL)
                    fe.wait(timeout=10)
                    # ...replacement replays the journal on the same port.
                    fe2 = _spawn("dynamo_tpu.frontend", "--port",
                                 str(fe_port), "--router-mode", "kv",
                                 env=env, log_path=logs / "fe2.log")
                    procs.append(fe2)
                    assert await _wait_models(session, base, "jr-model",
                                              timeout=60.0)
                    out = await _chat(session, base, "jr-model",
                                      shared + "after")
                    assert out
                    # The replay actually happened: the new router's KV
                    # indexer applied journaled events before serving.
                    deadline = time.monotonic() + 20
                    while time.monotonic() < deadline:
                        text = (logs / "fe2.log").read_text()
                        if "journal replay:" in text:
                            break
                        await asyncio.sleep(0.5)
                    assert "journal replay:" in (logs / "fe2.log"
                                                 ).read_text()

            run(body(), timeout=240.0)
        finally:
            _kill_all(procs)
