"""Diffusion serving tests: DiT model + in-jit DDIM sampling, the
diffusion worker, and the /v1/images/generations + /v1/videos endpoints
(ref surface: sglang image/video diffusion handlers + openai.rs routes)."""

import asyncio
import base64
import io
import uuid

import numpy as np
import pytest

from dynamo_tpu.diffusion import DiffusionWorker
from dynamo_tpu.frontend import Frontend
from dynamo_tpu.models.diffusion import (
    DiffusionRunner,
    get_diffusion_config,
    text_condition,
)
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


class TestDiffusionModel:
    def test_generate_shapes_and_determinism(self):
        runner = DiffusionRunner(get_diffusion_config("tiny-diffusion-test"),
                                 seed=0)
        out1 = runner.generate("a red square", n=2, steps=4, seed=7)
        out2 = runner.generate("a red square", n=2, steps=4, seed=7)
        assert out1.shape == (1, 2, 16, 16, 3)
        np.testing.assert_array_equal(out1, out2)
        assert float(out1.min()) >= 0.0 and float(out1.max()) <= 1.0
        # different seed -> different image
        out3 = runner.generate("a red square", n=2, steps=4, seed=8)
        assert not np.allclose(out1, out3)
        # different prompt -> different conditioning -> different image
        out4 = runner.generate("a blue circle", n=2, steps=4, seed=7)
        assert not np.allclose(out1, out4)

    def test_multi_frame_video_path(self):
        runner = DiffusionRunner(get_diffusion_config("tiny-diffusion-test"))
        out = runner.generate("waves", n=1, steps=2, seed=1, n_frames=3)
        assert out.shape == (3, 1, 16, 16, 3)
        # frames differ but are correlated (temporal threading)
        assert not np.allclose(out[0], out[1])

    def test_text_condition_stable(self):
        a = text_condition("hello", 64)
        b = text_condition("hello", 64)
        c = text_condition("world", 64)
        np.testing.assert_array_equal(a, b)
        assert not np.allclose(a, c)
        assert abs(float(np.linalg.norm(a)) - 1.0) < 1e-5


def _cfg(cluster):
    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = cluster
    cfg.request_plane = "tcp"
    cfg.tcp_host = "127.0.0.1"
    cfg.event_plane = "mem"
    cfg.system_enabled = False
    cfg.lease_ttl_secs = 1.0
    return cfg


class TestDiffusionE2E:
    def test_images_and_videos_endpoints(self, run):
        async def body():
            import aiohttp
            from PIL import Image

            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()
            worker = DiffusionWorker(rt, "sd-tiny",
                                     preset="tiny-diffusion-test")
            await worker.start()
            frt = await DistributedRuntime(_cfg(cluster)).start()
            frontend = Frontend(frt, host="127.0.0.1", port=0)
            await frontend.start()
            for _ in range(100):
                if "sd-tiny" in frontend.manager.image_pools:
                    break
                await asyncio.sleep(0.05)
            base = f"http://127.0.0.1:{frontend.port}"
            async with aiohttp.ClientSession() as session:
                # model listed
                async with session.get(f"{base}/v1/models") as resp:
                    ids = [m["id"] for m in (await resp.json())["data"]]
                    assert "sd-tiny" in ids
                # images
                async with session.post(f"{base}/v1/images/generations",
                                        json={"model": "sd-tiny",
                                              "prompt": "a red square",
                                              "n": 2, "steps": 3}) as resp:
                    assert resp.status == 200, await resp.text()
                    data = (await resp.json())["data"]
                assert len(data) == 2
                img = Image.open(io.BytesIO(
                    base64.b64decode(data[0]["b64_json"])))
                assert img.size == (16, 16) and img.format == "PNG"
                # videos
                async with session.post(f"{base}/v1/videos",
                                        json={"model": "sd-tiny",
                                              "prompt": "waves",
                                              "seconds": 1, "fps": 3,
                                              "steps": 2}) as resp:
                    assert resp.status == 200, await resp.text()
                    vdata = (await resp.json())["data"]
                assert vdata[0]["format"] == "gif"
                assert vdata[0]["frames"] == 3
                gif = Image.open(io.BytesIO(
                    base64.b64decode(vdata[0]["b64_json"])))
                assert gif.format == "GIF" and gif.n_frames == 3
                # unknown model / missing prompt
                async with session.post(f"{base}/v1/images/generations",
                                        json={"model": "ghost",
                                              "prompt": "x"}) as resp:
                    assert resp.status == 404
                async with session.post(f"{base}/v1/images/generations",
                                        json={"model": "sd-tiny"}) as resp:
                    assert resp.status == 400
            await frontend.close()
            await frt.shutdown()
            await worker.close()
            await rt.shutdown()

        run(body(), timeout=240)


class TestClassifierFreeGuidance:
    """CFG + negative prompts (production diffusion sampling; ref: the
    reference's sglang diffusion runners expose guidance_scale)."""

    def test_guided_differs_and_stays_valid(self):
        from dynamo_tpu.models.diffusion import (
            DiffusionRunner,
            get_diffusion_config,
        )

        runner = DiffusionRunner(get_diffusion_config(
            "tiny-diffusion-test"), seed=0)
        base = runner.generate("a red square", n=1, steps=4, seed=3)
        guided = runner.generate("a red square", n=1, steps=4, seed=3,
                                 negative_prompt="blue", guidance_scale=4.0)
        assert guided.shape == base.shape
        assert np.isfinite(guided).all()
        assert (guided >= 0).all() and (guided <= 1).all()
        assert not np.allclose(guided, base)  # guidance moved the sample
        # scale 1.0 with no negative == the unguided path exactly
        same = runner.generate("a red square", n=1, steps=4, seed=3,
                               guidance_scale=1.0)
        np.testing.assert_array_equal(same, base)

    def test_worker_parses_guidance(self, run):
        import asyncio as aio

        from dynamo_tpu.diffusion import DiffusionWorker
        from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

        async def body():
            cfg = RuntimeConfig.from_env()
            cfg.discovery_backend = "mem"
            cfg.discovery_path = uuid.uuid4().hex
            cfg.request_plane = "tcp"
            cfg.tcp_host = "127.0.0.1"
            cfg.event_plane = "mem"
            cfg.system_enabled = False
            rt = await DistributedRuntime(cfg).start()
            w = DiffusionWorker(rt, "sd-tiny",
                                preset="tiny-diffusion-test")
            await w.start()
            try:
                frames = []
                async for f in w.generate_image({
                        "prompt": "x", "steps": 2,
                        "negative_prompt": "y",
                        "guidance_scale": 3.0}):
                    frames.append(f)
                assert frames and "error" not in frames[0]
                async for f in w.generate_image({
                        "prompt": "x", "steps": 2,
                        "guidance_scale": "loud"}):
                    assert "guidance_scale" in f.get("error", "")
                    break
            finally:
                await w.close()
                await rt.shutdown()

        run(body(), timeout=120.0)
