"""Capture bundles (observatory/capture.py): bundle layout and
manifest, per-rule rate limiting, the /debug/profile capture-lock
contention path, spool count/size bounds, and the disabled/error
outcomes — all with an injected fetch, no HTTP."""

import json
from pathlib import Path

from dynamo_tpu.observatory.capture import CaptureBundler, CaptureSpool
from dynamo_tpu.observatory.collector import ScrapeTarget
from dynamo_tpu.observatory.rollup import FleetRollup
from dynamo_tpu.runtime import metrics as rt_metrics

BUNDLE_FILES = ("manifest.json", "rollup.json", "alerts.json",
                "timelines.json", "steptrace.json")


def _counter(name, **labels):
    for metric in rt_metrics.REGISTRY.collect():
        if metric.name != name.removesuffix("_total"):
            continue
        for sample in metric.samples:
            if sample.name == name and all(
                    sample.labels.get(k) == v for k, v in labels.items()):
                return sample.value
    return 0.0


def _transition(rule="slo_burn_fast", pool="decode"):
    return {"rule": rule, "severity": "page", "transition": "firing",
            "epoch": 1, "detail": "burn 20x", "pool": pool,
            "capture": True}


def _fetch_json(target, path, timeout_s=5.0):
    if path.startswith("/debug/requests"):
        return {"inflight": [], "total_inflight": 0, "total_completed": 1,
                "completed": [{"request_id": f"{target.name}-r0"}]}
    return {"trace_dir": "/tmp/trace", "files": ["steptrace.pb"]}


TARGETS = [ScrapeTarget(name="d0", pool="decode"),
           ScrapeTarget(name="d1", pool="decode"),
           ScrapeTarget(name="p0", pool="prefill")]


def _bundler(tmp_path, fetch=_fetch_json, **kw):
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("max_bundles", 8)
    kw.setdefault("max_mb", 8)
    return CaptureBundler(spool_dir=str(tmp_path), fetch_json=fetch, **kw)


class TestBundleAssembly:
    def test_layout_manifest_and_pool_attribution(self, tmp_path):
        bundler = _bundler(tmp_path)
        path = bundler.maybe_capture(
            _transition(), FleetRollup(at=1.0),
            {"active": [], "log": []}, TARGETS, now=10.0)
        assert path is not None and path.name == "000000-slo_burn_fast"
        payloads = {}
        for name in BUNDLE_FILES:
            assert (path / name).is_file(), name
            payloads[name] = json.loads((path / name).read_text())
        manifest = payloads["manifest.json"]
        assert manifest["rule"] == "slo_burn_fast"
        assert manifest["pool"] == "decode"
        # timelines come from the IMPLICATED pool's targets only
        assert manifest["targets"] == ["d0", "d1"]
        assert sorted(manifest["files"]) == sorted(BUNDLE_FILES)
        assert set(payloads["timelines.json"]) == {"d0", "d1"}
        assert payloads["steptrace.json"]["outcome"] == "captured"
        assert manifest["steptrace_outcome"] == "captured"
        assert payloads["alerts.json"] == {"active": [], "log": []}

    def test_rate_limit_is_per_rule_with_cooldown(self, tmp_path):
        bundler = _bundler(tmp_path, cooldown_s=100.0)
        roll = FleetRollup(at=1.0)
        alerts = {"active": [], "log": []}
        before = _counter("dynamo_observatory_bundles_total",
                          outcome="rate_limited")
        assert bundler.maybe_capture(_transition(), roll, alerts,
                                     TARGETS, now=10.0) is not None
        # same rule inside the cooldown: suppressed
        assert bundler.maybe_capture(_transition(), roll, alerts,
                                     TARGETS, now=20.0) is None
        assert _counter("dynamo_observatory_bundles_total",
                        outcome="rate_limited") - before == 1.0
        # a DIFFERENT rule is not throttled by the first one
        other = bundler.maybe_capture(_transition(rule="host_bound_workers",
                                                  pool="prefill"),
                                      roll, alerts, TARGETS, now=21.0)
        assert other is not None and other.name.endswith(
            "host_bound_workers")
        # past the cooldown the original rule captures again, seq bumped
        again = bundler.maybe_capture(_transition(), roll, alerts,
                                      TARGETS, now=200.0)
        assert again is not None and again.name == "000002-slo_burn_fast"

    def test_disabled_without_spool_dir(self, tmp_path):
        bundler = CaptureBundler(spool_dir="", fetch_json=_fetch_json,
                                 cooldown_s=0.0)
        before = _counter("dynamo_observatory_bundles_total",
                          outcome="disabled")
        assert bundler.maybe_capture(_transition(), FleetRollup(at=1.0),
                                     {}, TARGETS, now=1.0) is None
        assert _counter("dynamo_observatory_bundles_total",
                        outcome="disabled") - before == 1.0

    def test_profile_lock_contention_is_recorded_not_fatal(self, tmp_path):
        """A human mid-/debug/profile holds the process capture lock:
        the bundle still lands, with the contention on record instead
        of a corrupted trace."""
        from dynamo_tpu.runtime.status import _PROFILE_LOCK

        bundler = _bundler(tmp_path)
        assert _PROFILE_LOCK.acquire(blocking=False)
        try:
            path = bundler.maybe_capture(
                _transition(), FleetRollup(at=1.0),
                {"active": [], "log": []}, TARGETS, now=10.0)
        finally:
            _PROFILE_LOCK.release()
        assert path is not None
        steptrace = json.loads((path / "steptrace.json").read_text())
        assert steptrace == {"outcome": "lock_contended"}
        manifest = json.loads((path / "manifest.json").read_text())
        assert manifest["steptrace_outcome"] == "lock_contended"
        # the lock is free again for the next capture
        assert _PROFILE_LOCK.acquire(blocking=False)
        _PROFILE_LOCK.release()

    def test_timeline_fetch_error_keeps_the_bundle(self, tmp_path):
        def flaky(target, path, timeout_s=5.0):
            if path.startswith("/debug/requests"):
                raise ConnectionError("target died mid-incident")
            return _fetch_json(target, path, timeout_s)

        bundler = _bundler(tmp_path, fetch=flaky)
        before = _counter("dynamo_observatory_bundles_total",
                          outcome="written")
        path = bundler.maybe_capture(
            _transition(), FleetRollup(at=1.0),
            {"active": [], "log": []}, TARGETS, now=10.0)
        assert path is not None
        timelines = json.loads((path / "timelines.json").read_text())
        assert "died mid-incident" in timelines["d0"]["error"]
        assert _counter("dynamo_observatory_bundles_total",
                        outcome="written") - before == 1.0

    def test_no_pool_match_falls_back_to_any_pooled_target(self, tmp_path):
        bundler = _bundler(tmp_path)
        path = bundler.maybe_capture(
            _transition(pool="gone"), FleetRollup(at=1.0),
            {"active": [], "log": []}, TARGETS, now=10.0)
        manifest = json.loads((path / "manifest.json").read_text())
        assert manifest["targets"]  # still captured something


def _mk_bundle(root: Path, seq: int, rule: str, payload_bytes: int = 64):
    path = root / f"{seq:06d}-{rule}"
    path.mkdir(parents=True)
    (path / "manifest.json").write_text("x" * payload_bytes)
    return path


class TestCaptureSpool:
    def test_count_bound_drops_oldest(self, tmp_path):
        spool = CaptureSpool(tmp_path, max_bundles=2, max_mb=100)
        for seq in range(4):
            _mk_bundle(tmp_path, seq, "r")
        spool.prune()
        assert [p.name for p in spool.bundles()] == [
            "000002-r", "000003-r"]

    def test_size_bound_keeps_the_newest_even_over_cap(self, tmp_path):
        spool = CaptureSpool(tmp_path, max_bundles=10, max_mb=0)
        for seq in range(3):
            _mk_bundle(tmp_path, seq, "r")
        spool.prune()
        # an incident artifact beats an empty spool
        assert [p.name for p in spool.bundles()] == ["000002-r"]

    def test_next_dir_is_monotonic_across_pruning(self, tmp_path):
        spool = CaptureSpool(tmp_path, max_bundles=1, max_mb=100)
        for seq in range(3):
            _mk_bundle(tmp_path, seq, "r")
        spool.prune()
        # pruning old bundles must never recycle their sequence numbers
        assert spool.next_dir("r").name == "000003-r"

    def test_empty_root_is_fine(self, tmp_path):
        spool = CaptureSpool(tmp_path / "missing", max_bundles=2,
                             max_mb=1)
        assert spool.bundles() == []
        spool.prune()
        assert spool.next_dir("r").name == "000000-r"
