"""Weight-only packed int4 (W4A16) — the second halving of the decode
weight stream (ops/q4_linear.py): pack/unpack layouts (v1 half-block +
v2 VPU-swizzled), the Pallas kernel variants vs the XLA reference
across the geometry grid, v1<->v2 repack bit-exactness, per-group
quantization error bounds, einsum-spec plumbing, and runner integration
including the transparent checkpoint repack (BASELINE.md: decode at 7B
is weight-streaming-bound; the reference reaches this lever via its
engines' AWQ/GPTQ w4a16 checkpoint modes)."""

import numpy as np
import pytest

from dynamo_tpu.models import get_config

_GUARD: dict = {}


def _kernel_guard():
    """Skip the kernel tiers where even interpret-mode Pallas cannot
    run. Unlike the sibling kernel tests' hasattr(CompilerParams) guard,
    this PROBES: ops/q4_linear carries a TPUCompilerParams compat shim,
    so the parity tier runs on the older jax tier-1 uses too."""
    if "err" not in _GUARD:
        try:
            import jax.numpy as jnp

            from dynamo_tpu.ops.q4_linear import (
                q4_matmul,
                quantize_weight_q4,
            )

            qw = quantize_weight_q4(jnp.zeros((128, 128)), 1)
            q4_matmul(jnp.zeros((1, 128)), qw["q4"], qw["qs4"],
                      qw["qz4"], interpret=True)
            _GUARD["err"] = None
        except Exception as exc:  # noqa: BLE001 — any failure = old env
            _GUARD["err"] = repr(exc)
    if _GUARD["err"]:
        pytest.skip("this jax cannot run interpret-mode Pallas "
                    f"({_GUARD['err']}); kernel tests run where the "
                    "env is current")


class TestQ4Pack:
    def test_pack_roundtrip(self):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import _pack_codes, _unpack_codes

        rng = np.random.default_rng(0)
        u = jnp.asarray(rng.integers(0, 16, (256, 128)), jnp.uint8)
        packed = _pack_codes(u, 128)
        assert packed.shape == (128, 128)
        np.testing.assert_array_equal(
            np.asarray(_unpack_codes(packed, 128)), np.asarray(u))

    def test_dequant_error_within_half_lsb(self):
        """Asymmetric per-group codes reconstruct within scale/2."""
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            dequantize_q4,
            quantize_weight_q4,
        )

        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.standard_normal((512, 256)), jnp.float32)
        qw = quantize_weight_q4(w, 1)
        deq = np.asarray(dequantize_q4(qw["q4"], qw["qs4"], qw["qz4"]))
        group = 512 // qw["qs4"].shape[0]
        s = np.repeat(np.asarray(qw["qs4"]), group, axis=0)
        assert np.max(np.abs(deq - np.asarray(w)) - s * 0.5) <= 1e-5

    def test_constant_and_one_sided_groups_reconstruct(self):
        """A constant group and an all-positive group must dequantize to
        ~their values: the f32 zero-point row is NOT clipped to the code
        range (clipping it shifted such groups toward 0)."""
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            dequantize_q4,
            quantize_weight_q4,
        )

        const = jnp.full((256, 128), 3.0, jnp.float32)
        qw = quantize_weight_q4(const, 1)
        deq = np.asarray(dequantize_q4(qw["q4"], qw["qs4"], qw["qz4"]))
        np.testing.assert_allclose(deq, 3.0, rtol=1e-5)

        rng = np.random.default_rng(7)
        pos = jnp.asarray(rng.uniform(2.0, 4.0, (256, 128)), jnp.float32)
        qw = quantize_weight_q4(pos, 1)
        deq = np.asarray(dequantize_q4(qw["q4"], qw["qs4"], qw["qz4"]))
        # within half an LSB of the true values (range 2 / 15 codes)
        assert np.max(np.abs(deq - np.asarray(pos))) <= 2.0 / 15.0

        # The kernel's rank-1 zero-point fold must survive the huge
        # zero-points these groups produce (z ~ -lo/eps for constants).
        _kernel_guard()
        from dynamo_tpu.ops.q4_linear import q4_matmul, q4_matmul_ref

        mixed = jnp.concatenate([const[:128], pos[:128]], axis=0)
        qm = quantize_weight_q4(mixed, 1)
        x = jnp.asarray(rng.standard_normal((4, 256)), jnp.float32)
        ref = q4_matmul_ref(x, qm["q4"], qm["qs4"], qm["qz4"])
        out = q4_matmul(x, qm["q4"], qm["qs4"], qm["qz4"],
                        interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=2e-3)

    def test_non_divisible_k_rejected(self):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import quantize_weight_q4

        with pytest.raises(ValueError, match="group"):
            quantize_weight_q4(jnp.zeros((101, 128)), 1)


class TestQ4Matmul:
    def _case(self, m, k, n, seed=0):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import quantize_weight_q4

        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
        return x, w, quantize_weight_q4(w, 1)

    @pytest.mark.parametrize("m,k,n", [(8, 512, 512), (3, 1024, 512),
                                       (33, 384, 1536), (16, 128, 128)])
    def test_kernel_matches_reference(self, m, k, n):
        _kernel_guard()
        from dynamo_tpu.ops.q4_linear import q4_matmul, q4_matmul_ref

        x, _, qw = self._case(m, k, n)
        ref = q4_matmul_ref(x, qw["q4"], qw["qs4"], qw["qz4"])
        out = q4_matmul(x, qw["q4"], qw["qs4"], qw["qz4"],
                        interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-3)

    def test_matmul_error_bounded(self):
        """Output error vs exact is within the textbook per-group
        bound (measured against output rms, as in the q8 tests)."""
        from dynamo_tpu.ops.q4_linear import q4_matmul_ref

        x, w, qw = self._case(4, 512, 512)
        exact = np.asarray(x @ w)
        quant = np.asarray(q4_matmul_ref(x, qw["q4"], qw["qs4"],
                                         qw["qz4"]))
        # 4-bit LSB on N(0,1) weights: per-weight err sigma ~= s/sqrt(12)
        # ~= 0.12, accumulated over K=512 against output rms sqrt(K) ->
        # relative sigma ~0.12, p99 ~2.6 sigma.
        rel = np.abs(quant - exact) / np.sqrt(np.mean(exact ** 2))
        assert np.sqrt(np.mean(rel ** 2)) < 0.16
        assert np.percentile(rel, 99) < 0.38

    def test_einsum_specs(self):
        """Every dense-projection spec reshapes correctly (head
        projections keep out axes; wo stores flat because pack blocks
        span heads)."""
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            dequantize_q4,
            q4_einsum,
            quantize_weight_q4,
        )

        rng = np.random.default_rng(2)
        b, t, h, qh, hd, mdim = 2, 3, 512, 8, 128, 1024
        x = jnp.asarray(rng.standard_normal((b, t, h)), jnp.float32)
        for spec, wshape, nc in [
            ("bth,hm->btm", (h, mdim), 1),
            ("bth,hqd->btqd", (h, qh, hd), 1),
            ("bth,hkd->btkd", (h, 4, hd), 1),
            ("bth,hv->btv", (h, 1024), 1),
        ]:
            w = jnp.asarray(rng.standard_normal(wshape), jnp.float32)
            qw = quantize_weight_q4(w, nc)
            out = q4_einsum(spec, x, qw["q4"], qw["qs4"], qw["qz4"])
            deq = dequantize_q4(qw["q4"], qw["qs4"], qw["qz4"])
            ref = jnp.einsum(spec, x,
                             deq.reshape(wshape).astype(jnp.float32))
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-4, atol=2e-4)
        xo = jnp.asarray(rng.standard_normal((b, t, qh, hd)), jnp.float32)
        wo = jnp.asarray(rng.standard_normal((qh, hd, h)), jnp.float32)
        qo = quantize_weight_q4(wo, 2)
        assert qo["q4"].shape == (qh * hd // 2, h)
        out = q4_einsum("btqd,qdh->bth", xo, qo["q4"], qo["qs4"],
                        qo["qz4"])
        deq = dequantize_q4(qo["q4"], qo["qs4"], qo["qz4"])
        ref = jnp.einsum("btqd,qdh->bth", xo,
                         deq.reshape(qh, hd, h).astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestQ4PackV2:
    """The VPU-swizzled v2 layout (global half-split, signed-biased
    nibbles, int8 storage): pack/unpack bijection, layout-version
    policy, and bit-exact v1<->v2 repacking (the checkpoint-migration
    contract — scale/zero rows are never touched)."""

    def test_pack_roundtrip_v2(self):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            _pack_codes_v2,
            _unpack_codes_v2,
        )

        rng = np.random.default_rng(0)
        u = jnp.asarray(rng.integers(0, 16, (512, 64)), jnp.uint8)
        packed = _pack_codes_v2(u)
        assert packed.dtype == jnp.int8 and packed.shape == (256, 64)
        np.testing.assert_array_equal(np.asarray(_unpack_codes_v2(packed)),
                                      np.asarray(u))

    def test_version_policy(self, monkeypatch):
        from dynamo_tpu.ops.q4_linear import (
            PACK_V1,
            PACK_V2,
            resolve_pack_version,
        )

        # auto: v2 wherever the global half-split is well-formed
        assert resolve_pack_version(512, 256) == PACK_V2
        assert resolve_pack_version(256, 256) == PACK_V1  # K == group
        assert resolve_pack_version(128, 128) == PACK_V1
        monkeypatch.setenv("DYNT_Q4_VARIANT", "v1")
        assert resolve_pack_version(512, 256) == PACK_V1
        monkeypatch.setenv("DYNT_Q4_VARIANT", "v2")
        assert resolve_pack_version(512, 256) == PACK_V2
        with pytest.raises(ValueError, match="v2"):
            resolve_pack_version(256, 256)
        monkeypatch.setenv("DYNT_Q4_VARIANT", "bogus")
        with pytest.raises(ValueError, match="DYNT_Q4_VARIANT"):
            resolve_pack_version(512, 256)

    def test_quantizer_emits_versions(self):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            pack_version,
            quantize_weight_q4,
        )

        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.standard_normal((512, 128)), jnp.float32)
        assert pack_version(quantize_weight_q4(w, 1)["q4"]) == 2  # auto
        assert pack_version(quantize_weight_q4(w, 1, version=1)["q4"]) == 1
        small = jnp.asarray(rng.standard_normal((128, 128)), jnp.float32)
        # small-K fallback: auto keeps v1 where the half-split is not
        # well-formed; forcing v2 raises instead of mis-packing
        assert pack_version(quantize_weight_q4(small, 1)["q4"]) == 1
        with pytest.raises(ValueError, match="v2"):
            quantize_weight_q4(small, 1, version=2)

    def test_dequant_bitwise_identical_across_layouts(self):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            dequantize_q4,
            quantize_weight_q4,
        )

        rng = np.random.default_rng(2)
        w = jnp.asarray(rng.standard_normal((1024, 128)), jnp.float32)
        q1 = quantize_weight_q4(w, 1, version=1)
        q2 = quantize_weight_q4(w, 1, version=2)
        np.testing.assert_array_equal(
            np.asarray(q1["qs4"]), np.asarray(q2["qs4"]))
        np.testing.assert_array_equal(
            np.asarray(q1["qz4"]), np.asarray(q2["qz4"]))
        np.testing.assert_array_equal(
            np.asarray(dequantize_q4(q1["q4"], q1["qs4"], q1["qz4"])),
            np.asarray(dequantize_q4(q2["q4"], q2["qs4"], q2["qz4"])))

    def test_repack_roundtrip_bit_exact(self):
        """quantize -> repack v1->v2 -> repack back: bit-exact, and the
        v2 leg matches a direct v2 quantize (the transform is the same
        nibble bijection either way). Includes constant and one-sided
        groups — the huge-zero-point edge the f32 rows carry."""
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            quantize_weight_q4,
            repack_q4_leaf,
        )

        rng = np.random.default_rng(3)
        w = jnp.concatenate([
            jnp.full((256, 64), 3.0, jnp.float32),  # constant groups
            jnp.asarray(rng.uniform(2.0, 4.0, (256, 64)), jnp.float32),
            jnp.asarray(rng.standard_normal((512, 64)), jnp.float32),
        ], axis=0)
        v1 = {k: np.asarray(v)
              for k, v in quantize_weight_q4(w, 1, version=1).items()}
        v2 = repack_q4_leaf(v1, 2)
        assert v2["q4"].dtype == np.int8
        direct = quantize_weight_q4(w, 1, version=2)
        np.testing.assert_array_equal(v2["q4"], np.asarray(direct["q4"]))
        assert v2["qs4"] is v1["qs4"] and v2["qz4"] is v1["qz4"]
        back = repack_q4_leaf(v2, 1)
        np.testing.assert_array_equal(back["q4"], v1["q4"])
        # no-op repacks return the same dict (device leaves never
        # round-trip through host for nothing)
        assert repack_q4_leaf(v1, 1) is v1
        assert repack_q4_leaf(v2, 2) is v2

    def test_repack_auto_keeps_small_k_on_v1(self, monkeypatch):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            quantize_weight_q4,
            repack_q4_leaf,
        )

        rng = np.random.default_rng(4)
        w = jnp.asarray(rng.standard_normal((128, 64)), jnp.float32)
        v1 = {k: np.asarray(v)
              for k, v in quantize_weight_q4(w, 1, version=1).items()}
        assert repack_q4_leaf(v1, None) is v1
        # forcing v2 on an incompatible K keeps the leaf at load time
        # (non-strict) — only the QUANTIZER refuses to mis-pack...
        monkeypatch.setenv("DYNT_Q4_VARIANT", "v2")
        assert repack_q4_leaf(v1, None) is v1
        # ...but a typo'd knob must raise, not silently skip the repack
        monkeypatch.setenv("DYNT_Q4_VARIANT", "v3")
        with pytest.raises(ValueError, match="DYNT_Q4_VARIANT"):
            repack_q4_leaf(v1, None)

    def test_repack_params_tree(self):
        """models.quantize.repack_params_q4: q4 dict leaves migrate,
        everything else (and already-current leaves) pass through as
        the same objects."""
        import jax.numpy as jnp

        from dynamo_tpu.models.quantize import repack_params_q4
        from dynamo_tpu.ops.q4_linear import (
            dequantize_q4,
            quantize_weight_q4,
        )

        rng = np.random.default_rng(5)
        w = jnp.asarray(rng.standard_normal((512, 128)), jnp.float32)
        leaf = {k: np.asarray(v)
                for k, v in quantize_weight_q4(w, 1, version=1).items()}
        norm = np.ones(128, np.float32)
        params = {"embed": np.zeros((8, 4), np.float32),
                  "layers": [{"wq": leaf, "attn_norm": norm}],
                  "lm_head": dict(leaf)}
        out = repack_params_q4(params)  # auto -> v2 for K=512
        assert out["layers"][0]["wq"]["q4"].dtype == np.int8
        assert out["lm_head"]["q4"].dtype == np.int8
        assert out["layers"][0]["attn_norm"] is norm
        assert out["embed"] is params["embed"]
        np.testing.assert_array_equal(
            np.asarray(dequantize_q4(out["layers"][0]["wq"]["q4"],
                                     out["layers"][0]["wq"]["qs4"],
                                     out["layers"][0]["wq"]["qz4"])),
            np.asarray(dequantize_q4(leaf["q4"], leaf["qs4"],
                                     leaf["qz4"])))
        again = repack_params_q4(out)
        assert again["layers"][0]["wq"] is out["layers"][0]["wq"]


class TestQ4VariantParity:
    """Interpret-mode parity for EVERY kernel variant vs q4_matmul_ref
    across the geometry grid: small-K fallback groups, gk boundaries,
    the M=1 decode row, the flat-wo multi-axis contraction, and the
    constant-group zero-point edge (dynajit DJ403 oracle coverage for
    the new kernel)."""

    def _case(self, m, k, n, version, seed=0):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import quantize_weight_q4

        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
        return x, quantize_weight_q4(w, 1, version=version)

    @pytest.mark.parametrize("version", [1, 2])
    @pytest.mark.parametrize("m,k,n", [
        (8, 512, 512),    # one k-step at group 256 (gk boundary)
        (1, 512, 512),    # M=1 decode row
        (3, 1024, 512),   # multiple k-steps
        (16, 1024, 128),  # lane-minimal N
        (33, 2048, 256),  # padded M, deep contraction
    ])
    def test_variant_matches_reference(self, version, m, k, n):
        _kernel_guard()
        from dynamo_tpu.ops.q4_linear import q4_matmul, q4_matmul_ref

        x, qw = self._case(m, k, n, version)
        ref = q4_matmul_ref(x, qw["q4"], qw["qs4"], qw["qz4"])
        out = q4_matmul(x, qw["q4"], qw["qs4"], qw["qz4"],
                        interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-3)

    @pytest.mark.parametrize("version,gk", [(1, 1), (1, 2), (1, 4),
                                            (2, 2), (2, 4)])
    def test_forced_gk(self, version, gk):
        _kernel_guard()
        from dynamo_tpu.ops.q4_linear import q4_matmul, q4_matmul_ref

        x, qw = self._case(5, 2048, 256, version)
        ref = q4_matmul_ref(x, qw["q4"], qw["qs4"], qw["qz4"])
        out = q4_matmul(x, qw["q4"], qw["qs4"], qw["qz4"], gk=gk,
                        interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-3)

    def test_small_k_fallback_group(self):
        """K below the preferred group: the group falls back to a
        divisor and auto stays on v1 — the fallback still matches."""
        _kernel_guard()
        from dynamo_tpu.ops.q4_linear import (
            pack_version,
            q4_matmul,
            q4_matmul_ref,
        )

        x, qw = self._case(4, 128, 128, None)
        assert pack_version(qw["q4"]) == 1
        ref = q4_matmul_ref(x, qw["q4"], qw["qs4"], qw["qz4"])
        out = q4_matmul(x, qw["q4"], qw["qs4"], qw["qz4"],
                        interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-3)

    def test_constant_group_zero_point_edge_v2(self):
        """The v2 rank-1 fold (zs = (z - 8) * s) must survive the huge
        zero-points constant/one-sided groups produce."""
        _kernel_guard()
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            q4_matmul,
            q4_matmul_ref,
            quantize_weight_q4,
        )

        rng = np.random.default_rng(7)
        mixed = jnp.concatenate([
            jnp.full((256, 128), 3.0, jnp.float32),
            jnp.asarray(rng.uniform(2.0, 4.0, (256, 128)), jnp.float32),
        ], axis=0)
        qm = quantize_weight_q4(mixed, 1, version=2)
        x = jnp.asarray(rng.standard_normal((4, 512)), jnp.float32)
        ref = q4_matmul_ref(x, qm["q4"], qm["qs4"], qm["qz4"])
        out = q4_matmul(x, qm["q4"], qm["qs4"], qm["qz4"],
                        interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=2e-3)

    def test_einsum_specs_v2_including_flat_wo(self):
        """q4_einsum carries the layout version (dtype-encoded) through
        every projection spec — including the flat multi-axis wo."""
        _kernel_guard()
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            dequantize_q4,
            q4_einsum,
            quantize_weight_q4,
        )

        rng = np.random.default_rng(8)
        b, t, h, qh, hd, mdim = 2, 3, 512, 8, 128, 1024
        x = jnp.asarray(rng.standard_normal((b, t, h)), jnp.float32)
        for spec, wshape, nc in [
            ("bth,hm->btm", (h, mdim), 1),
            ("bth,hqd->btqd", (h, qh, hd), 1),
            ("bth,hkd->btkd", (h, 4, hd), 1),
            ("bth,hv->btv", (h, 1024), 1),
        ]:
            w = jnp.asarray(rng.standard_normal(wshape), jnp.float32)
            qw = quantize_weight_q4(w, nc, version=2)
            assert qw["q4"].dtype == jnp.int8
            out = q4_einsum(spec, x, qw["q4"], qw["qs4"], qw["qz4"])
            deq = dequantize_q4(qw["q4"], qw["qs4"], qw["qz4"])
            ref = jnp.einsum(spec, x,
                             deq.reshape(wshape).astype(jnp.float32))
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-4, atol=2e-4)
        xo = jnp.asarray(rng.standard_normal((b, t, qh, hd)), jnp.float32)
        wo = jnp.asarray(rng.standard_normal((qh, hd, h)), jnp.float32)
        qo = quantize_weight_q4(wo, 2, version=2)
        assert qo["q4"].shape == (qh * hd // 2, h)
        out = q4_einsum("btqd,qdh->bth", xo, qo["q4"], qo["qs4"],
                        qo["qz4"])
        deq = dequantize_q4(qo["q4"], qo["qs4"], qo["qz4"])
        ref = jnp.einsum("btqd,qdh->bth", xo,
                         deq.reshape(qh, hd, h).astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_geometry_errors_are_value_errors(self):
        """Geometry validation raises explicit ValueError (survives
        python -O), matching the lane-divisibility error."""
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import q4_matmul, quantize_weight_q4

        rng = np.random.default_rng(9)
        w = jnp.asarray(rng.standard_normal((512, 256)), jnp.float32)
        qw = quantize_weight_q4(w, 1, version=2)
        x = jnp.asarray(rng.standard_normal((2, 512)), jnp.float32)
        with pytest.raises(ValueError, match="x columns"):
            q4_matmul(x[:, :256], qw["q4"], qw["qs4"], qw["qz4"],
                      interpret=True)
        with pytest.raises(ValueError, match="zero"):
            q4_matmul(x, qw["q4"], qw["qs4"], qw["qz4"][:1],
                      interpret=True)
        with pytest.raises(ValueError, match="even gk"):
            q4_matmul(x, qw["q4"], qw["qs4"], qw["qz4"], gk=1,
                      interpret=True)  # odd gk on the v2 layout
        with pytest.raises(ValueError, match="does not divide"):
            q4_matmul(x, qw["q4"], qw["qs4"], qw["qz4"], gk=8,
                      interpret=True)


class TestRunnerInt4Weights:
    def _runner(self, weight_dtype):
        from dynamo_tpu.engine.model_runner import ModelRunner, RunnerConfig
        from dynamo_tpu.parallel import MeshConfig, make_mesh

        return ModelRunner(
            get_config("tiny-test"),
            RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                         max_pages_per_seq=16, prefill_buckets=(16, 32),
                         weight_dtype=weight_dtype),
            make_mesh(MeshConfig()),
            seed=0,
        )

    def test_serving_loop_matches_dequantized_oracle(self):
        """The quantize->serve invariant: an int4 runner's greedy stream
        equals a bf16 runner serving the explicitly DEQUANTIZED weights
        (the two compute the same math; a plain bf16-vs-int4 comparison
        would only measure 4-bit noise on a random tiny model)."""
        import jax.numpy as jnp

        from dynamo_tpu.engine.model_runner import ModelRunner, RunnerConfig
        from dynamo_tpu.models import get_config as gc
        from dynamo_tpu.ops.q4_linear import dequantize_q4
        from dynamo_tpu.parallel import MeshConfig, make_mesh

        config = gc("tiny-test")
        r4 = self._runner("int4")

        def deq(leaf, orig_shape):
            w = dequantize_q4(leaf["q4"].reshape(leaf["q4"].shape[0], -1),
                              leaf["qs4"], leaf["qz4"])
            return np.asarray(w.reshape(orig_shape).astype(jnp.bfloat16))

        h, qh, kh, hd = (config.hidden, config.n_q_heads,
                         config.n_kv_heads, config.head_dim)
        m = config.mlp_hidden
        shapes = {"wq": (h, qh, hd), "wk": (h, kh, hd), "wv": (h, kh, hd),
                  "wo": (qh, hd, h), "w_gate": (h, m), "w_up": (h, m),
                  "w_down": (m, h)}
        params = {k: np.asarray(v) for k, v in r4.params.items()
                  if not isinstance(v, (dict, list))}
        params["layers"] = [
            {name: (deq(leaf, shapes[name]) if isinstance(leaf, dict)
                    else np.asarray(leaf))
             for name, leaf in layer.items()}
            for layer in r4.params["layers"]
        ]
        rd = ModelRunner(
            config,
            RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                         max_pages_per_seq=16, prefill_buckets=(16, 32)),
            make_mesh(MeshConfig()),
            params=params,
            seed=0,
        )

        rng = np.random.default_rng(2)
        prompt = rng.integers(1, 500, 20).astype(np.int32)
        table = np.zeros(16, np.int32)
        table[:8] = np.arange(1, 9)
        outs = {}
        for key, r in (("int4", r4), ("oracle", rd)):
            first = r.prefill_chunk(prompt, 0, table, len(prompt),
                                    (0.0, 1.0, 0, 0))
            toks = [first]
            tok = first
            for i in range(6):
                pos = len(prompt) + i
                nxt = r.decode(
                    np.array([tok], np.int32), np.array([pos], np.int32),
                    table[None, :], np.array([pos + 1], np.int32),
                    np.array([True]), np.zeros(1, np.float32),
                    np.ones(1, np.float32), np.zeros(1, np.int32),
                    np.zeros(1, np.uint32), np.array([i], np.int32))
                tok = int(nxt[0])
                toks.append(tok)
            outs[key] = toks
        # bf16 rounding of the dequantized weights vs the kernel's f32
        # dequant can flip a near-tie; demand near-total agreement.
        same = sum(a == b for a, b in zip(outs["int4"], outs["oracle"]))
        assert same >= len(outs["oracle"]) - 1, outs

    def test_quantized_leaf_structure(self):
        r = self._runner("int4")
        layer = r.params["layers"][0]
        for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
            assert isinstance(layer[name], dict), name
            # tiny-test contractions (64/128/256 rows) are below the v2
            # half-split floor, so auto keeps the uint8 v1 layout here.
            assert layer[name]["q4"].dtype == np.uint8
            assert layer[name]["qs4"].ndim == 2
        # wo flattens (pack blocks span heads); head projections keep
        # their out axes for the einsum reshape.
        assert layer["wo"].get("q4").ndim == 2
        assert layer["wq"]["q4"].ndim == 3
        assert not isinstance(layer["attn_norm"], dict)
        assert not isinstance(r.params["embed"], dict)

    def test_int4_rejects_non_dense_families(self):
        from dynamo_tpu.models.quantize import check_quantizable

        with pytest.raises(ValueError, match="int4"):
            check_quantizable(get_config("tiny-mla-test"), dtype="int4")
        with pytest.raises(ValueError, match="single-device"):
            check_quantizable(get_config("tiny-test"), tp=2,
                              dtype="int4")


class TestRunnerQ4Repack:
    """Checkpoint-migration contract at the runner level: a v1-packed
    quantized tree (old checkpoint / weight-service stream) loads
    through ModelRunner unchanged in MATH — transparently repacked to
    the DYNT_Q4_VARIANT target where well-formed, bit-identically kept
    where not — and serves the same greedy stream either way."""

    def _config(self):
        from dynamo_tpu.models.config import ModelConfig

        # Wide enough that every contraction (512 = hidden = qh*hd =
        # mlp) is v2-capable, tiny everywhere else.
        return ModelConfig(
            name="tiny-v2-test", vocab_size=512, hidden=512,
            n_layers=1, n_q_heads=4, n_kv_heads=2, head_dim=128,
            mlp_hidden=512, max_context=2048)

    def _runner(self, config, params=None):
        from dynamo_tpu.engine.model_runner import ModelRunner, RunnerConfig
        from dynamo_tpu.parallel import MeshConfig, make_mesh

        return ModelRunner(
            config,
            RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                         max_pages_per_seq=16, prefill_buckets=(16,),
                         weight_dtype="int4"),
            make_mesh(MeshConfig()),
            params=params,
            seed=0,
        )

    def _greedy(self, runner, prompt, steps=4):
        table = np.zeros(16, np.int32)
        table[:8] = np.arange(1, 9)
        tok = runner.prefill_chunk(prompt, 0, table, len(prompt),
                                   (0.0, 1.0, 0, 0))
        toks = [tok]
        for i in range(steps):
            pos = len(prompt) + i
            nxt = runner.decode(
                np.array([tok], np.int32), np.array([pos], np.int32),
                table[None, :], np.array([pos + 1], np.int32),
                np.array([True]), np.zeros(1, np.float32),
                np.ones(1, np.float32), np.zeros(1, np.int32),
                np.zeros(1, np.uint32), np.array([i], np.int32))
            tok = int(nxt[0])
            toks.append(tok)
        return toks

    def test_v1_tree_loads_via_transparent_repack(self, monkeypatch):
        from dynamo_tpu.ops.q4_linear import pack_version

        config = self._config()
        monkeypatch.setenv("DYNT_Q4_VARIANT", "v1")
        r1 = self._runner(config)
        v1_layer = r1.params["layers"][0]
        assert all(pack_version(v1_layer[n]["q4"]) == 1
                   for n in ("wq", "wo", "w_down"))
        host = {
            "embed": np.asarray(r1.params["embed"]),
            "final_norm": np.asarray(r1.params["final_norm"]),
            "layers": [{
                name: ({k: np.asarray(v) for k, v in leaf.items()}
                       if isinstance(leaf, dict) else np.asarray(leaf))
                for name, leaf in r1.params["layers"][0].items()
            }],
        }
        monkeypatch.delenv("DYNT_Q4_VARIANT", raising=False)
        r2 = self._runner(config, params=host)  # auto -> repack to v2
        v2_layer = r2.params["layers"][0]
        assert all(pack_version(v2_layer[n]["q4"]) == 2
                   for n in ("wq", "wo", "w_down"))
        rng = np.random.default_rng(6)
        prompt = rng.integers(1, 500, 12).astype(np.int32)
        assert self._greedy(r1, prompt) == self._greedy(r2, prompt)

    def test_v1_tree_loads_unchanged_when_pinned(self, monkeypatch):
        from dynamo_tpu.ops.q4_linear import pack_version

        config = self._config()
        monkeypatch.setenv("DYNT_Q4_VARIANT", "v1")
        r1 = self._runner(config)
        host = {
            "embed": np.asarray(r1.params["embed"]),
            "final_norm": np.asarray(r1.params["final_norm"]),
            "layers": [{
                name: ({k: np.asarray(v) for k, v in leaf.items()}
                       if isinstance(leaf, dict) else np.asarray(leaf))
                for name, leaf in r1.params["layers"][0].items()
            }],
        }
        r2 = self._runner(config, params=host)  # policy still v1
        for name in ("wq", "wo", "w_down"):
            assert pack_version(r2.params["layers"][0][name]["q4"]) == 1
            np.testing.assert_array_equal(
                np.asarray(r2.params["layers"][0][name]["q4"]),
                np.asarray(r1.params["layers"][0][name]["q4"]))
