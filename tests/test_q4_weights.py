"""Weight-only packed int4 (W4A16) — the second halving of the decode
weight stream (ops/q4_linear.py): pack/unpack layout, the Pallas kernel
vs the XLA reference, per-group quantization error bounds, einsum-spec
plumbing, and runner integration (BASELINE.md: decode at 7B is
weight-streaming-bound; the reference reaches this lever via its
engines' AWQ/GPTQ w4a16 checkpoint modes)."""

import numpy as np
import pytest

from dynamo_tpu.models import get_config


class TestQ4Pack:
    def test_pack_roundtrip(self):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import _pack_codes, _unpack_codes

        rng = np.random.default_rng(0)
        u = jnp.asarray(rng.integers(0, 16, (256, 128)), jnp.uint8)
        packed = _pack_codes(u, 128)
        assert packed.shape == (128, 128)
        np.testing.assert_array_equal(
            np.asarray(_unpack_codes(packed, 128)), np.asarray(u))

    def test_dequant_error_within_half_lsb(self):
        """Asymmetric per-group codes reconstruct within scale/2."""
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            dequantize_q4,
            quantize_weight_q4,
        )

        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.standard_normal((512, 256)), jnp.float32)
        qw = quantize_weight_q4(w, 1)
        deq = np.asarray(dequantize_q4(qw["q4"], qw["qs4"], qw["qz4"]))
        group = 512 // qw["qs4"].shape[0]
        s = np.repeat(np.asarray(qw["qs4"]), group, axis=0)
        assert np.max(np.abs(deq - np.asarray(w)) - s * 0.5) <= 1e-5

    def test_constant_and_one_sided_groups_reconstruct(self):
        """A constant group and an all-positive group must dequantize to
        ~their values: the f32 zero-point row is NOT clipped to the code
        range (clipping it shifted such groups toward 0)."""
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            dequantize_q4,
            quantize_weight_q4,
        )

        const = jnp.full((256, 128), 3.0, jnp.float32)
        qw = quantize_weight_q4(const, 1)
        deq = np.asarray(dequantize_q4(qw["q4"], qw["qs4"], qw["qz4"]))
        np.testing.assert_allclose(deq, 3.0, rtol=1e-5)

        rng = np.random.default_rng(7)
        pos = jnp.asarray(rng.uniform(2.0, 4.0, (256, 128)), jnp.float32)
        qw = quantize_weight_q4(pos, 1)
        deq = np.asarray(dequantize_q4(qw["q4"], qw["qs4"], qw["qz4"]))
        # within half an LSB of the true values (range 2 / 15 codes)
        assert np.max(np.abs(deq - np.asarray(pos))) <= 2.0 / 15.0

        # The kernel's rank-1 zero-point fold must survive the huge
        # zero-points these groups produce (z ~ -lo/eps for constants).
        from dynamo_tpu.ops.q4_linear import q4_matmul, q4_matmul_ref

        mixed = jnp.concatenate([const[:128], pos[:128]], axis=0)
        qm = quantize_weight_q4(mixed, 1)
        x = jnp.asarray(rng.standard_normal((4, 256)), jnp.float32)
        ref = q4_matmul_ref(x, qm["q4"], qm["qs4"], qm["qz4"])
        out = q4_matmul(x, qm["q4"], qm["qs4"], qm["qz4"],
                        interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=2e-3)

    def test_non_divisible_k_rejected(self):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import quantize_weight_q4

        with pytest.raises(ValueError, match="group"):
            quantize_weight_q4(jnp.zeros((101, 128)), 1)


class TestQ4Matmul:
    def _case(self, m, k, n, seed=0):
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import quantize_weight_q4

        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
        return x, w, quantize_weight_q4(w, 1)

    @pytest.mark.parametrize("m,k,n", [(8, 512, 512), (3, 1024, 512),
                                       (33, 384, 1536), (16, 128, 128)])
    def test_kernel_matches_reference(self, m, k, n):
        from dynamo_tpu.ops.q4_linear import q4_matmul, q4_matmul_ref

        x, _, qw = self._case(m, k, n)
        ref = q4_matmul_ref(x, qw["q4"], qw["qs4"], qw["qz4"])
        out = q4_matmul(x, qw["q4"], qw["qs4"], qw["qz4"],
                        interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-3)

    def test_matmul_error_bounded(self):
        """Output error vs exact is within the textbook per-group
        bound (measured against output rms, as in the q8 tests)."""
        from dynamo_tpu.ops.q4_linear import q4_matmul_ref

        x, w, qw = self._case(4, 512, 512)
        exact = np.asarray(x @ w)
        quant = np.asarray(q4_matmul_ref(x, qw["q4"], qw["qs4"],
                                         qw["qz4"]))
        # 4-bit LSB on N(0,1) weights: per-weight err sigma ~= s/sqrt(12)
        # ~= 0.12, accumulated over K=512 against output rms sqrt(K) ->
        # relative sigma ~0.12, p99 ~2.6 sigma.
        rel = np.abs(quant - exact) / np.sqrt(np.mean(exact ** 2))
        assert np.sqrt(np.mean(rel ** 2)) < 0.16
        assert np.percentile(rel, 99) < 0.38

    def test_einsum_specs(self):
        """Every dense-projection spec reshapes correctly (head
        projections keep out axes; wo stores flat because pack blocks
        span heads)."""
        import jax.numpy as jnp

        from dynamo_tpu.ops.q4_linear import (
            dequantize_q4,
            q4_einsum,
            quantize_weight_q4,
        )

        rng = np.random.default_rng(2)
        b, t, h, qh, hd, mdim = 2, 3, 512, 8, 128, 1024
        x = jnp.asarray(rng.standard_normal((b, t, h)), jnp.float32)
        for spec, wshape, nc in [
            ("bth,hm->btm", (h, mdim), 1),
            ("bth,hqd->btqd", (h, qh, hd), 1),
            ("bth,hkd->btkd", (h, 4, hd), 1),
            ("bth,hv->btv", (h, 1024), 1),
        ]:
            w = jnp.asarray(rng.standard_normal(wshape), jnp.float32)
            qw = quantize_weight_q4(w, nc)
            out = q4_einsum(spec, x, qw["q4"], qw["qs4"], qw["qz4"])
            deq = dequantize_q4(qw["q4"], qw["qs4"], qw["qz4"])
            ref = jnp.einsum(spec, x,
                             deq.reshape(wshape).astype(jnp.float32))
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-4, atol=2e-4)
        xo = jnp.asarray(rng.standard_normal((b, t, qh, hd)), jnp.float32)
        wo = jnp.asarray(rng.standard_normal((qh, hd, h)), jnp.float32)
        qo = quantize_weight_q4(wo, 2)
        assert qo["q4"].shape == (qh * hd // 2, h)
        out = q4_einsum("btqd,qdh->bth", xo, qo["q4"], qo["qs4"],
                        qo["qz4"])
        deq = dequantize_q4(qo["q4"], qo["qs4"], qo["qz4"])
        ref = jnp.einsum("btqd,qdh->bth", xo,
                         deq.reshape(qh, hd, h).astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestRunnerInt4Weights:
    def _runner(self, weight_dtype):
        from dynamo_tpu.engine.model_runner import ModelRunner, RunnerConfig
        from dynamo_tpu.parallel import MeshConfig, make_mesh

        return ModelRunner(
            get_config("tiny-test"),
            RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                         max_pages_per_seq=16, prefill_buckets=(16, 32),
                         weight_dtype=weight_dtype),
            make_mesh(MeshConfig()),
            seed=0,
        )

    def test_serving_loop_matches_dequantized_oracle(self):
        """The quantize->serve invariant: an int4 runner's greedy stream
        equals a bf16 runner serving the explicitly DEQUANTIZED weights
        (the two compute the same math; a plain bf16-vs-int4 comparison
        would only measure 4-bit noise on a random tiny model)."""
        import jax.numpy as jnp

        from dynamo_tpu.engine.model_runner import ModelRunner, RunnerConfig
        from dynamo_tpu.models import get_config as gc
        from dynamo_tpu.ops.q4_linear import dequantize_q4
        from dynamo_tpu.parallel import MeshConfig, make_mesh

        config = gc("tiny-test")
        r4 = self._runner("int4")

        def deq(leaf, orig_shape):
            w = dequantize_q4(leaf["q4"].reshape(leaf["q4"].shape[0], -1),
                              leaf["qs4"], leaf["qz4"])
            return np.asarray(w.reshape(orig_shape).astype(jnp.bfloat16))

        h, qh, kh, hd = (config.hidden, config.n_q_heads,
                         config.n_kv_heads, config.head_dim)
        m = config.mlp_hidden
        shapes = {"wq": (h, qh, hd), "wk": (h, kh, hd), "wv": (h, kh, hd),
                  "wo": (qh, hd, h), "w_gate": (h, m), "w_up": (h, m),
                  "w_down": (m, h)}
        params = {k: np.asarray(v) for k, v in r4.params.items()
                  if not isinstance(v, (dict, list))}
        params["layers"] = [
            {name: (deq(leaf, shapes[name]) if isinstance(leaf, dict)
                    else np.asarray(leaf))
             for name, leaf in layer.items()}
            for layer in r4.params["layers"]
        ]
        rd = ModelRunner(
            config,
            RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                         max_pages_per_seq=16, prefill_buckets=(16, 32)),
            make_mesh(MeshConfig()),
            params=params,
            seed=0,
        )

        rng = np.random.default_rng(2)
        prompt = rng.integers(1, 500, 20).astype(np.int32)
        table = np.zeros(16, np.int32)
        table[:8] = np.arange(1, 9)
        outs = {}
        for key, r in (("int4", r4), ("oracle", rd)):
            first = r.prefill_chunk(prompt, 0, table, len(prompt),
                                    (0.0, 1.0, 0, 0))
            toks = [first]
            tok = first
            for i in range(6):
                pos = len(prompt) + i
                nxt = r.decode(
                    np.array([tok], np.int32), np.array([pos], np.int32),
                    table[None, :], np.array([pos + 1], np.int32),
                    np.array([True]), np.zeros(1, np.float32),
                    np.ones(1, np.float32), np.zeros(1, np.int32),
                    np.zeros(1, np.uint32), np.array([i], np.int32))
                tok = int(nxt[0])
                toks.append(tok)
            outs[key] = toks
        # bf16 rounding of the dequantized weights vs the kernel's f32
        # dequant can flip a near-tie; demand near-total agreement.
        same = sum(a == b for a, b in zip(outs["int4"], outs["oracle"]))
        assert same >= len(outs["oracle"]) - 1, outs

    def test_quantized_leaf_structure(self):
        r = self._runner("int4")
        layer = r.params["layers"][0]
        for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
            assert isinstance(layer[name], dict), name
            assert layer[name]["q4"].dtype == np.uint8
            assert layer[name]["qs4"].ndim == 2
        # wo flattens (pack blocks span heads); head projections keep
        # their out axes for the einsum reshape.
        assert layer["wo"].get("q4").ndim == 2
        assert layer["wq"]["q4"].ndim == 3
        assert not isinstance(layer["attn_norm"], dict)
        assert not isinstance(r.params["embed"], dict)

    def test_int4_rejects_non_dense_families(self):
        from dynamo_tpu.models.quantize import check_quantizable

        with pytest.raises(ValueError, match="int4"):
            check_quantizable(get_config("tiny-mla-test"), dtype="int4")
        with pytest.raises(ValueError, match="single-device"):
            check_quantizable(get_config("tiny-test"), tp=2,
                              dtype="int4")
