"""Fast-start plane (docs/elasticity.md, ISSUE 17): striped peer weight
streaming — chunk-manifest integrity, resume-after-donor-death, donor
bandwidth budgeting —, the G4 object-store fallback, the persistent
compile-cache sync, and the 2-worker E2E striped arrival."""

import asyncio
import json
import os
import uuid

import numpy as np
import pytest

import jax

from dynamo_tpu.weights.striped import (
    BandwidthBudget,
    StripedAssembler,
    WeightManifest,
    chunk_digest,
    encode_chunk_frames,
    pull_striped,
)


def _flat(seed: int = 0, n: int = 3, size: int = 700):
    rng = np.random.default_rng(seed)
    return [(f"layer{i}/w", rng.standard_normal(size).astype(np.float32))
            for i in range(n)]


def _bufs(flat):
    return [np.ascontiguousarray(a).tobytes() for _, a in flat]


class TestManifest:
    def test_deterministic_across_replicas(self):
        m1 = WeightManifest.build(_flat(), "k:1", chunk_bytes=256)
        m2 = WeightManifest.build(_flat(), "k:1", chunk_bytes=256)
        assert m1.to_wire() == m2.to_wire()
        assert len(m1.chunks) > len(m1.params)  # multi-chunk params

    def test_wire_roundtrip(self):
        m = WeightManifest.build(_flat(), "k:rt", chunk_bytes=256)
        back = WeightManifest.from_wire(json.loads(json.dumps(
            {**m.to_wire(), "chunks": [c.to_wire() for c in m.chunks]})))
        assert back.weights_key == "k:rt"
        assert [c.to_wire() for c in back.chunks] == \
            [c.to_wire() for c in m.chunks]
        assert back.total_bytes == m.total_bytes

    def test_assembler_roundtrip_and_idempotence(self):
        flat = _flat()
        m = WeightManifest.build(flat, "k:a", chunk_bytes=256)
        asm = StripedAssembler(m)
        bufs = _bufs(flat)
        for frame in encode_chunk_frames(m, bufs, range(len(m.chunks))):
            assert asm.add(frame["cid"], frame["data"])
            assert asm.add(frame["cid"], frame["data"])  # repeat is fine
        assert asm.complete and not asm.missing
        out = asm.params()
        for path, arr in flat:
            np.testing.assert_array_equal(out[path], arr)

    def test_corrupt_chunk_rejected_never_assembled(self):
        flat = _flat()
        m = WeightManifest.build(flat, "k:c", chunk_bytes=256)
        asm = StripedAssembler(m)
        good = dict(
            (f["cid"], f["data"])
            for f in encode_chunk_frames(m, _bufs(flat),
                                         range(len(m.chunks))))
        evil = b"\x00" * m.chunks[0].size
        assert chunk_digest(evil) != m.chunks[0].digest
        assert asm.add(0, evil) is False
        assert 0 in asm.missing  # the bad bytes were NOT placed
        assert asm.add(0, good[0][:-1]) is False  # size mismatch
        for cid, data in good.items():
            asm.add(cid, data)
        assert asm.complete
        np.testing.assert_array_equal(asm.params()["layer0/w"], flat[0][1])

    def test_unknown_cid_yields_error_frame(self):
        flat = _flat(n=1)
        m = WeightManifest.build(flat, "k:e", chunk_bytes=256)
        frames = list(encode_chunk_frames(m, _bufs(flat), [0, 999]))
        assert frames[0]["cid"] == 0
        assert "unknown chunk id" in frames[-1]["error"]


class TestBandwidthBudget:
    def test_pr8_duty_cycle_formula(self):
        b = BandwidthBudget(0.25)
        assert b.defer_after(0.1) == pytest.approx(0.3)  # g*(1/f - 1)
        assert b.deferred_total == pytest.approx(0.3)

    def test_full_fraction_never_defers(self):
        assert BandwidthBudget(1.0).defer_after(5.0) == 0.0

    def test_frac_clamped(self):
        assert BandwidthBudget(0.0).frac == 0.01
        assert BandwidthBudget(7.0).frac == 1.0
        assert BandwidthBudget(0.5).defer_after(-1.0) == 0.0


def _fake_donors(manifest, bufs, *, corrupt=None, dies_after=None):
    """fetch_chunks fake: donor 'names' are strings. `corrupt` maps
    donor -> set of cids it serves bad bytes for; `dies_after` maps
    donor -> number of chunks it serves before raising."""
    corrupt = corrupt or {}
    dies_after = dies_after or {}

    async def fetch_chunks(donor, cids):
        served = 0
        for frame in encode_chunk_frames(manifest, bufs, cids):
            if donor in dies_after and served >= dies_after[donor]:
                raise ConnectionError(f"{donor} evicted")
            served += 1
            data = frame["data"]
            if frame["cid"] in corrupt.get(donor, ()):
                data = b"\xff" * len(data)
            yield frame["cid"], data

    return fetch_chunks


class TestStripedPull:
    def _manifest(self):
        flat = _flat(n=4, size=900)
        return flat, WeightManifest.build(flat, "k:p", chunk_bytes=256)

    def test_stripes_across_all_donors(self, run):
        flat, m = self._manifest()
        out = run(pull_striped(
            m, ["d0", "d1", "d2"], _fake_donors(m, _bufs(flat))))
        for path, arr in flat:
            np.testing.assert_array_equal(out[path], arr)

    def test_corrupting_donor_refetched_from_another_peer(self, run):
        flat, m = self._manifest()
        fetch = _fake_donors(m, _bufs(flat), corrupt={"bad": {0, 1, 2}})
        out = run(pull_striped(m, ["bad", "good"], fetch))
        assert out is not None
        for path, arr in flat:
            np.testing.assert_array_equal(out[path], arr)

    def test_all_donors_corrupt_bails_not_spins(self, run):
        flat, m = self._manifest()
        all_cids = set(range(len(m.chunks)))
        fetch = _fake_donors(m, _bufs(flat),
                             corrupt={"b1": all_cids, "b2": all_cids})
        assert run(pull_striped(m, ["b1", "b2"], fetch),
                   timeout=30.0) is None

    def test_donor_death_restripes_over_survivors(self, run):
        flat, m = self._manifest()
        fetch = _fake_donors(m, _bufs(flat), dies_after={"dying": 1})
        out = run(pull_striped(m, ["dying", "live"], fetch))
        assert out is not None
        for path, arr in flat:
            np.testing.assert_array_equal(out[path], arr)

    def test_every_donor_dead_returns_none(self, run):
        flat, m = self._manifest()
        fetch = _fake_donors(m, _bufs(flat),
                             dies_after={"d0": 0, "d1": 1})
        assert run(pull_striped(m, ["d0", "d1"], fetch)) is None


class TestObjectStoreFallback:
    def test_publish_fetch_roundtrip(self, tmp_path):
        from dynamo_tpu.weights.objstore import (
            fetch_weights_from_store,
            make_store_client,
            publish_weights_to_store,
        )

        flat = _flat()
        store = make_store_client(str(tmp_path))
        n = publish_weights_to_store(store, "m:os", flat)
        assert n >= len(flat)
        out = fetch_weights_from_store(store, "m:os")
        for path, arr in flat:
            np.testing.assert_array_equal(out[path], arr)

    def test_missing_key_and_corrupt_chunk_return_none(self, tmp_path):
        from dynamo_tpu.weights.objstore import (
            fetch_weights_from_store,
            make_store_client,
            publish_weights_to_store,
            weights_prefix,
        )

        store = make_store_client(str(tmp_path))
        assert fetch_weights_from_store(store, "m:none") is None
        flat = _flat(n=1)
        publish_weights_to_store(store, "m:corr", flat)
        prefix = weights_prefix("m:corr")
        chunks_dir = tmp_path / prefix / "chunks"
        victim = sorted(chunks_dir.iterdir())[0]
        victim.write_bytes(b"\x00" * victim.stat().st_size)
        assert fetch_weights_from_store(store, "m:corr") is None

    def test_wrong_key_under_prefix_not_served(self, tmp_path):
        from dynamo_tpu.weights.objstore import (
            fetch_weights_from_store,
            make_store_client,
            weights_prefix,
        )

        store = make_store_client(str(tmp_path))
        m = WeightManifest.build(_flat(n=1), "m:other", chunk_bytes=256)
        prefix = weights_prefix("m:mine")
        store.put_bytes(f"{prefix}/manifest.json",
                        json.dumps(m.to_wire()).encode())
        assert fetch_weights_from_store(store, "m:mine") is None


class TestCompileCacheSync:
    def test_up_down_roundtrip(self, tmp_path, monkeypatch):
        from dynamo_tpu.engine import compile_cache

        store_root = tmp_path / "store"
        local_a = tmp_path / "node-a"
        local_b = tmp_path / "node-b"
        local_a.mkdir()
        local_b.mkdir()
        (local_a / "xla_key1.bin").write_bytes(b"compiled-1")
        (local_a / "sub").mkdir()
        (local_a / "sub" / "xla_key2.bin").write_bytes(b"compiled-2")
        monkeypatch.setenv("DYNT_COMPILE_CACHE_STORE", str(store_root))
        monkeypatch.setenv("DYNT_COMPILE_CACHE_DIR", str(local_a))
        assert compile_cache.sync_up() == 2
        assert compile_cache.sync_up() == 0  # idempotent
        monkeypatch.setenv("DYNT_COMPILE_CACHE_DIR", str(local_b))
        assert compile_cache.sync_down() == 2
        assert (local_b / "xla_key1.bin").read_bytes() == b"compiled-1"
        assert (local_b / "sub" / "xla_key2.bin").read_bytes() == \
            b"compiled-2"
        assert compile_cache.sync_down() == 0  # nothing new

    def test_sync_is_noop_without_store_knob(self, tmp_path, monkeypatch):
        from dynamo_tpu.engine import compile_cache

        monkeypatch.delenv("DYNT_COMPILE_CACHE_STORE", raising=False)
        monkeypatch.setenv("DYNT_COMPILE_CACHE_DIR", str(tmp_path))
        assert compile_cache.sync_down() == 0
        assert compile_cache.sync_up() == 0

    def test_traversal_names_in_index_are_skipped(self, tmp_path,
                                                  monkeypatch):
        from dynamo_tpu.engine import compile_cache
        from dynamo_tpu.weights.objstore import make_store_client

        store_root = tmp_path / "store"
        local = tmp_path / "local"
        local.mkdir()
        store = make_store_client(str(store_root))
        store.put_bytes("compile-cache/index.json", json.dumps(
            {"entries": ["../../etc/passwd", "/abs/path", "ok.bin"]}
        ).encode())
        store.put_bytes("compile-cache/files/ok.bin", b"fine")
        monkeypatch.setenv("DYNT_COMPILE_CACHE_STORE", str(store_root))
        monkeypatch.setenv("DYNT_COMPILE_CACHE_DIR", str(local))
        assert compile_cache.sync_down() == 1
        assert (local / "ok.bin").read_bytes() == b"fine"
        assert not (tmp_path / "etc").exists()


class TestStripedArrivalE2E:
    def test_worker_pulls_striped_from_live_peer(self, run,
                                                 mem_runtime_config,
                                                 monkeypatch):
        """Arrival-ladder E2E: a cold worker stripe-pulls the weight
        tree from a live replica over the request plane, lands with
        weights_source == "peer_striped", identical parameters, and a
        completed cold-start ladder."""
        from dynamo_tpu.engine import RunnerConfig, TpuWorker
        from dynamo_tpu.runtime import DistributedRuntime

        monkeypatch.setenv("DYNT_WEIGHT_STRIPE", "1")

        async def body():
            cluster = uuid.uuid4().hex
            ns = uuid.uuid4().hex
            cfg = RunnerConfig(page_size=4, num_pages=64, max_batch=4,
                               max_pages_per_seq=16,
                               prefill_buckets=(8, 16))
            rt_a = await DistributedRuntime(
                mem_runtime_config(cluster)).start()
            worker_a = TpuWorker(rt_a, model_name="tiny-test",
                                 namespace=ns, runner_config=cfg,
                                 warmup=False)
            await worker_a.start()
            rt_b = await DistributedRuntime(
                mem_runtime_config(cluster)).start()
            worker_b = TpuWorker(rt_b, model_name="tiny-test",
                                 namespace=ns, runner_config=cfg,
                                 warmup=False, weights_from_peer=True)
            await worker_b.start()
            assert worker_b.weights_source == "peer_striped"
            np.testing.assert_array_equal(
                np.asarray(worker_a.runner.params["embed"]),
                np.asarray(worker_b.runner.params["embed"]))
            assert worker_b.coldstart is not None
            rep = worker_b.coldstart.report()
            assert (rep["phases"]["fetch"] or 0.0) > 0.0
            await worker_b.close()
            await worker_a.close()
            await rt_b.shutdown()
            await rt_a.shutdown()

        run(body(), timeout=180)
