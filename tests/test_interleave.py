"""Deterministic-interleaving race tests (the `interleave` tier).

Each regression test here drives a cross-domain race that dynarace
(tools/dynarace) flagged, through the runtime/interleave.py harness:
actors run the REAL production methods with one shared attribute
probed so every read/write is a domain-switch point, and a seeded
sweep hunts for the losing schedule. The tests fail on the pre-fix
code (bare read-modify-write) and pass on the locked fix — that pair
is the evidence dynarace suppressions and channel blessings cite.

Cited by name from the fixed code and the analyzer docs:
  * test_offload_dropped_counter_lost_update   (block_manager/offload.py)
  * test_distributed_stats_lost_update         (block_manager/distributed.py)
  * test_tracer_double_flusher_spawn           (runtime/otel.py)
  * test_double_drain_converges                (engine/drain.py, DR401 rider)
"""

import asyncio
import threading

import numpy as np
import pytest

from dynamo_tpu.runtime.interleave import (
    DeadlockError,
    Interleaver,
    checkpoint,
    explore,
    probe_attribute,
)

pytestmark = [pytest.mark.unit, pytest.mark.interleave]

# Short stall window: these schedules park actors inside critical
# sections on purpose, and every lock hand-off costs one stall wait.
STALL = 0.05
SEEDS = range(10)


# ---------------------------------------------------------------------------
# Harness self-tests
# ---------------------------------------------------------------------------


class _Counter:
    """Toy shared state: `unlocked_add` is the racy read-modify-write
    the probe decomposes; `locked_add` is the fixed shape."""

    def __init__(self) -> None:
        self.value = 0
        self._lock = threading.Lock()

    def unlocked_add(self) -> None:
        self.value += 1

    def locked_add(self) -> None:
        with self._lock:
            self.value += 1


class TestHarness:
    def test_checkpoint_is_noop_outside_scheduler(self):
        checkpoint("not running")  # must not raise or block

    def test_same_seed_replays_identical_schedule(self):
        def schedule(seed):
            c = _Counter()
            probe_attribute(c, "value")
            itl = Interleaver(seed=seed, stall_timeout=STALL)
            itl.add("a", c.unlocked_add)
            itl.add("b", c.unlocked_add)
            itl.run()
            return itl.history

        assert schedule(7) == schedule(7)

    def test_explore_finds_lost_update_in_unlocked_counter(self):
        """The harness MUST be able to lose an update on a bare `+=`,
        otherwise the regression tests below prove nothing."""

        def scenario(seed):
            c = _Counter()
            probe_attribute(c, "value")
            itl = Interleaver(seed=seed, stall_timeout=STALL)
            itl.add("a", c.unlocked_add)
            itl.add("b", c.unlocked_add)
            itl.run()
            assert c.value == 2

        with pytest.raises(AssertionError, match="seed="):
            explore(scenario, seeds=range(32))

    def test_locked_counter_survives_every_schedule(self):
        """The stall machinery keeps native locks honest: an actor
        parked inside the critical section blocks its peer, the peer
        is marked stalled, and the schedule still converges."""

        def scenario(seed):
            c = _Counter()
            probe_attribute(c, "value")
            itl = Interleaver(seed=seed, stall_timeout=STALL)
            itl.add("a", c.locked_add)
            itl.add("b", c.locked_add)
            itl.run()
            assert c.value == 2

        explore(scenario, seeds=range(32))

    def test_actor_exception_replays_to_caller(self):
        itl = Interleaver(seed=0, stall_timeout=STALL)
        itl.add("boom", lambda: (_ for _ in ()).throw(ValueError("x")))
        with pytest.raises(ValueError, match="x"):
            itl.run()

    def test_native_deadlock_is_reported(self):
        a, b = threading.Lock(), threading.Lock()

        def ab():
            with a:
                checkpoint("holding a")
                with b:
                    pass

        def ba():
            with b:
                checkpoint("holding b")
                with a:
                    pass

        # Sweep a few seeds: only schedules that interleave the two
        # lock acquisitions deadlock; the others complete fine.
        saw_deadlock = False
        for seed in range(8):
            itl = Interleaver(seed=seed, stall_timeout=STALL,
                              run_timeout=2.0)
            itl.add("ab", ab)
            itl.add("ba", ba)
            try:
                itl.run()
            except DeadlockError:
                saw_deadlock = True
                break
        assert saw_deadlock

    def test_seed_defaults_to_config_knob(self, monkeypatch):
        monkeypatch.setenv("DYNT_INTERLEAVE_SEED", "41")
        assert Interleaver(stall_timeout=STALL).seed == 41


# ---------------------------------------------------------------------------
# Regression: OffloadManager.dropped lost update (block_manager/offload.py)
# ---------------------------------------------------------------------------


def _make_offload_mgr(gather):
    from dynamo_tpu.block_manager.offload import OffloadManager

    return OffloadManager(
        lookup_pages=lambda hashes: [0] * len(hashes),
        gather=gather,
        run_in_step=None,  # inline: the actor thread IS the step thread
        sink=lambda h, block, parent: None,
        bw_frac=0.0,
        subbatch=1,
        queue_cap=64,
    )


def test_offload_dropped_counter_lost_update():
    """dynarace DR101: OffloadManager.dropped is written by the offload
    worker's batch-failure path and by the scheduler-thread overflow
    path. Pre-fix, the failure path incremented it without _cond, so
    two concurrent `dropped += lost` RMWs could lose one increment."""

    def scenario(seed):
        def boom(ids):
            raise RuntimeError("gather failed")

        mgr = _make_offload_mgr(boom)
        probe_attribute(mgr, "dropped")

        def lose_batch(name):
            # Real error path: gather raises inside _do_offload_batch,
            # the except arm counts the whole batch as dropped.
            try:
                mgr._do_offload_batch([(1, None)])
            except RuntimeError:
                pass

        itl = Interleaver(seed=seed, stall_timeout=STALL)
        itl.add("offload-a", lambda: lose_batch("a"))
        itl.add("offload-b", lambda: lose_batch("b"))
        itl.run()
        # Plain attribute read (not dropped_count()) so the assertion
        # also runs against the pre-fix code, failing on the race
        # itself rather than on the reader API added with the fix.
        assert mgr.dropped == 2, \
            f"lost update: dropped={mgr.dropped} (expected 2)"
        mgr.close()

    explore(scenario, seeds=SEEDS)


# ---------------------------------------------------------------------------
# Regression: DistributedKvbm.stats lost update (block_manager/distributed.py)
# ---------------------------------------------------------------------------


class _ShardRunnerStub:
    def kvbm_load_shards(self, hashes, pages):
        pass

    def kvbm_store_shards(self, ids, hashes):
        pass


def _make_dist_kvbm():
    from dynamo_tpu.block_manager.distributed import DistributedKvbm
    from dynamo_tpu.block_manager.manager import KvbmConfig

    kvbm = DistributedKvbm(KvbmConfig(host_blocks=16), _ShardRunnerStub())
    kvbm._index[101] = None
    kvbm._index[202] = None
    return kvbm


def test_distributed_stats_lost_update():
    """dynarace DR101: DistributedKvbm.stats is a dataclass shared by
    the scheduler's onboard_direct, the leader thread's offload loop,
    and loop-side usage(). Pre-fix, onboard_direct bumped the counters
    outside _lock: two onboards interleaving their `+=` RMWs lose an
    increment, and a usage() snapshot can see the pair half-applied."""

    def scenario(seed):
        kvbm = _make_dist_kvbm()
        probe_attribute(kvbm.stats, "onboarded_blocks")
        snapshots = []
        pages = np.asarray([0], np.int32)

        itl = Interleaver(seed=seed, stall_timeout=STALL)
        itl.add("sched-a", lambda: kvbm.onboard_direct([101], pages))
        itl.add("sched-b", lambda: kvbm.onboard_direct([202], pages))
        itl.add("loop", lambda: snapshots.append(kvbm.usage()))
        itl.run()

        assert kvbm.stats.onboarded_blocks == 2, \
            f"lost update: onboarded={kvbm.stats.onboarded_blocks}"
        assert kvbm.stats.onboard_hits_host == 2
        assert snapshots  # the locked reader ran against the writers

    explore(scenario, seeds=SEEDS)


# ---------------------------------------------------------------------------
# Regression: Tracer flusher double-spawn (runtime/otel.py)
# ---------------------------------------------------------------------------


def test_tracer_double_flusher_spawn():
    """dynarace DR101: Tracer._flusher check-then-spawn raced between
    any two recording domains (loop, scheduler, offload threads).
    Pre-fix both racers saw `_flusher is None` and each started a
    flush thread — one leaked, and both drained the same buffer."""
    from dynamo_tpu.runtime.otel import Span, Tracer

    def scenario(seed):
        tracer = Tracer("http://127.0.0.1:9")  # enabled, never reached
        spawned = []
        release = threading.Event()

        def fake_flush_loop():
            # Stand-in for the real _flush_loop: stays alive (so
            # is_alive() reflects a running flusher) without touching
            # the network, and exits when the scenario ends.
            spawned.append(threading.current_thread())
            release.wait(5.0)

        tracer._flush_loop = fake_flush_loop  # instance attr wins
        probe_attribute(tracer, "_flusher")

        def record(n):
            tracer.record(Span(name=n, trace_id="t" * 32, span_id=n * 8,
                               parent_span_id=None, start_ns=1, end_ns=2))

        itl = Interleaver(seed=seed, stall_timeout=STALL)
        itl.add("sched", lambda: record("a"))
        itl.add("offload", lambda: record("b"))
        itl.run()
        release.set()
        assert len(spawned) == 1, \
            f"double flusher spawn: {len(spawned)} threads started"

    explore(scenario, seeds=SEEDS)


# ---------------------------------------------------------------------------
# Rider (DR401 contract): drain converges under double delivery + cancel
# ---------------------------------------------------------------------------


class _LadderScheduler:
    """Minimal DrainCoordinator surface with a call ledger."""

    class _Stats:
        drain_bounced = 0

    def __init__(self):
        self.stats = self._Stats()
        self.calls = []
        self.draining = False

    def run_in_step(self, fn):
        import queue as thread_queue

        q = thread_queue.Queue()
        try:
            q.put((fn(), None))
        except Exception as exc:  # noqa: BLE001 — mirrors the real queue
            q.put((None, exc))
        return q

    def drain_sweep(self, register_handoff=None):
        self.draining = True
        self.calls.append("sweep")
        return {"handoff": [], "replay": [], "pending": []}

    def drain_expire(self, reason):
        self.calls.append("expire")
        return 0

    def queue_depth(self):
        return (0, 0)


class _LadderTransfers:
    def __len__(self):
        return 0

    def expire_all(self):
        return 0


class _LadderWorker:
    instance_id = 0xD12A2

    def __init__(self):
        self.scheduler = _LadderScheduler()
        self.transfers = _LadderTransfers()
        self.announces = 0
        self.announce_started = asyncio.Event()
        self.announce_release = asyncio.Event()

    async def announce_draining(self):
        self.announces += 1
        self.announce_started.set()
        # Hold the ladder mid-rung so callers can race/cancel around it.
        await self.announce_release.wait()

    def register_drain_handoff(self, seq, page_ids, computed):
        return None


def test_double_drain_converges(run):
    """DR401's contract (runtime/signals.py + engine/drain.py): the
    signal handler only resolves an event; once-semantics live in
    DrainCoordinator.drain(), where a double SIGTERM — including one
    whose awaiting task is CANCELLED mid-ladder — joins the one
    shielded ladder run instead of starting a second."""
    from dynamo_tpu.engine.drain import DrainCoordinator

    async def body():
        worker = _LadderWorker()
        coord = DrainCoordinator(worker, deadline_secs=5.0)

        first = asyncio.create_task(coord.drain("sigterm-1"))
        await worker.announce_started.wait()  # ladder is mid-rung

        # First deliverer dies (entrypoint task torn down): the shield
        # must keep the ladder itself running.
        first.cancel()
        with pytest.raises(asyncio.CancelledError):
            await first

        # Second SIGTERM joins the SAME run...
        second = asyncio.create_task(coord.drain("sigterm-2"))
        await asyncio.sleep(0)
        worker.announce_release.set()
        report = await second

        # ...so the ladder ran exactly once, to completion.
        assert worker.announces == 1
        assert worker.scheduler.calls.count("sweep") == 1
        assert report["completed"] is True
        assert coord.state == "drained"

    run(body(), timeout=30)
