"""dynaflow golden tests: every pass exercised by positive and negative
fixtures, schema-snapshot drift, suppression semantics, CLI contract,
and the repo-wide clean-lint invariant (dynalint + dynaflow over
dynamo_tpu/ — the same gate CI enforces, failing pytest locally)."""

import json
import pathlib
import subprocess
import sys

import tools.dynalint as dynalint
from tools.dynaflow import all_rules, run, update_schemas
from tools.dynaflow.passes_locks import LockOrderInversion, SlowCallUnderLock
from tools.dynaflow.passes_protocol import (
    Plane,
    WireKeyNeverRead,
    WireKeyNeverWritten,
    WireSchemaDrift,
    WireTagUnhandled,
)
from tools.dynaflow.passes_reach import (
    ProtocolFieldUnread,
    UnreachableAcceptedField,
)
from tools.dynaflow.passes_registry import (
    DeadConfigKnob,
    DuplicateMetricName,
    EnvDefaultTypeMismatch,
    UnboundedMetricLabel,
    UndocumentedMetric,
    UnregisteredEnvRead,
)
from tools.dynaflow.passes_spans import DuplicateSpanName, UndocumentedSpan
from tools.dynalint.core import collect_files

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "dynaflow"
REPO = pathlib.Path(__file__).parent.parent

# Fixture plane: one file, msg receivers, send() transmit, "t" tag.
FIXTURE_PLANE = (Plane("fixture", ("plane.py",), ("send",), ("msg",),
                       tag_key="t"),)


def flow(path, rules):
    findings, _ = run([str(FIXTURES / path)], rules=rules)
    return findings


def rules_of(findings):
    return sorted({f.rule for f in findings})


class TestRuleCatalogue:
    def test_thirteen_rules_registered(self):
        assert len(all_rules()) >= 13

    def test_ids_and_names_unique_and_described(self):
        rules = all_rules()
        assert len({r.id for r in rules}) == len(rules)
        assert len({r.name for r in rules}) == len(rules)
        assert all(r.description for r in rules)

    def test_disjoint_from_dynalint_ids(self):
        assert not ({r.id for r in all_rules()}
                    & {r.id for r in dynalint.all_rules()})


class TestProtocolConformance:
    RULES = [WireKeyNeverRead(FIXTURE_PLANE),
             WireKeyNeverWritten(FIXTURE_PLANE),
             WireTagUnhandled(FIXTURE_PLANE)]

    def test_positive(self):
        findings = flow("proto_pos", self.RULES)
        by_rule = {}
        for f in findings:
            by_rule.setdefault(f.rule, []).append(f.message)
        assert any("'dead'" in m for m in by_rule["DF101"])
        assert any("'gone'" in m for m in by_rule["DF102"])
        tags = " ".join(by_rule["DF103"])
        assert "'orphan'" in tags and "'ghost'" in tags

    def test_negative(self):
        assert flow("proto_neg", self.RULES) == []

    def test_schema_drift(self, tmp_path):
        files, _ = collect_files([str(FIXTURES / "proto_neg")])
        # no snapshot yet -> missing-snapshot finding
        rule = WireSchemaDrift(FIXTURE_PLANE, schema_dir=tmp_path)
        missing, _ = run([str(FIXTURES / "proto_neg")], rules=[rule])
        assert rules_of(missing) == ["DF104"]
        assert "no schema snapshot" in missing[0].message
        # blessed snapshot -> clean
        update_schemas(files, schema_dir=tmp_path, planes=FIXTURE_PLANE)
        clean, _ = run([str(FIXTURES / "proto_neg")], rules=[rule])
        assert clean == []
        # the tree drifts from the snapshot -> diffed finding
        drifted, _ = run([str(FIXTURES / "proto_pos")], rules=[rule])
        assert rules_of(drifted) == ["DF104"]
        assert "drifted" in drifted[0].message

    def test_schema_update_writes_json(self, tmp_path):
        files, _ = collect_files([str(FIXTURES / "proto_neg")])
        changed = update_schemas(files, schema_dir=tmp_path,
                                 planes=FIXTURE_PLANE)
        assert changed == ["fixture"]
        data = json.loads((tmp_path / "fixture.json").read_text())
        assert data["dispatch"] == ["end", "msg"]
        assert data["writes"]["msg"] == ["k", "t"]
        # second run is a no-op
        assert update_schemas(files, schema_dir=tmp_path,
                              planes=FIXTURE_PLANE) == []


class TestLockHazards:
    def test_slow_call_positive(self):
        findings = flow("locks_pos.py", [SlowCallUnderLock()])
        lines = [f.line for f in findings if f.rule == "DF201"]
        # direct slow await + the callee-traced one
        assert len(lines) == 2
        assert any("sleep" in f.message for f in findings)
        assert any("_helper" in f.message for f in findings)

    def test_lock_order_positive(self):
        findings = flow("locks_pos.py", [LockOrderInversion()])
        assert rules_of(findings) == ["DF202"]
        assert "OrderAB._a" in findings[0].message
        assert "OrderAB._b" in findings[0].message

    def test_negative(self):
        assert flow("locks_neg.py",
                    [SlowCallUnderLock(), LockOrderInversion()]) == []


class TestReachableConsumption:
    RULES = [UnreachableAcceptedField(), ProtocolFieldUnread()]

    def test_positive(self):
        findings = flow("reach_pos", self.RULES)
        assert ("DF301", "min_p") in [
            (f.rule, f.message.split(".")[1].split(" ")[0])
            for f in findings if f.rule == "DF301"]
        assert any(f.rule == "DF302" and "ghost_field" in f.message
                   for f in findings)
        # temperature IS read from the entry point: not flagged
        assert not any("temperature" in f.message for f in findings)

    def test_negative(self):
        assert flow("reach_neg", self.RULES) == []


class TestRegistryConformance:
    def test_env_positive(self):
        findings = flow("registry_pos",
                        [UnregisteredEnvRead(), EnvDefaultTypeMismatch(),
                         DeadConfigKnob()])
        msgs = {f.rule: f.message for f in findings}
        assert "DYNT_UNREGISTERED" in msgs["DF401"]
        assert "DYNT_BADTYPE" in msgs["DF402"]
        assert "DYNT_DEAD" in msgs["DF403"]

    def test_metrics_positive(self):
        findings = flow(
            "registry_pos",
            [DuplicateMetricName(),
             UndocumentedMetric(doc_path=FIXTURES / "metrics_doc.md")])
        assert any(f.rule == "DF404" and "dynamo_dup_total" in f.message
                   for f in findings)
        assert any(f.rule == "DF405" and "dynamo_secret_total" in f.message
                   for f in findings)

    def test_negative(self):
        findings = flow(
            "registry_neg",
            [UnregisteredEnvRead(), EnvDefaultTypeMismatch(),
             DeadConfigKnob(), DuplicateMetricName(),
             UndocumentedMetric(doc_path=FIXTURES / "metrics_doc.md")])
        assert findings == []


class TestBoundedLabels:
    def test_positive_all_three_call_shapes(self):
        findings = flow("labels_pos.py", [UnboundedMetricLabel()])
        assert all(f.rule == "DF406" for f in findings)
        # keyword tenant + **dict from/to + positional from/to
        assert len(findings) == 5
        msgs = " ".join(f.message for f in findings)
        assert "'tenant'" in msgs and "'from'" in msgs and "'to'" in msgs
        assert "bounded_label" in findings[0].message

    def test_negative_bounded_and_literal_sites(self):
        assert flow("labels_neg.py", [UnboundedMetricLabel()]) == []

    def test_suppression_on_flagged_line(self):
        assert flow("labels_suppressed.py", [UnboundedMetricLabel()]) == []


class TestSpanRegistry:
    def test_positive(self):
        findings = flow(
            "spans_pos",
            [UndocumentedSpan(doc_path=FIXTURES / "spans_doc.md"),
             DuplicateSpanName()])
        assert any(f.rule == "DF501" and "fixture.mystery" in f.message
                   for f in findings)
        assert any(f.rule == "DF502" and "fixture.documented" in f.message
                   for f in findings)

    def test_negative(self):
        findings = flow(
            "spans_neg",
            [UndocumentedSpan(doc_path=FIXTURES / "spans_doc.md"),
             DuplicateSpanName()])
        assert findings == []

    def test_conditional_names_both_checked(self):
        findings = flow(
            "spans_neg",
            [UndocumentedSpan(doc_path=FIXTURES / "metrics_doc.md")])
        # against the WRONG doc every literal name (incl. both IfExp
        # branches) is undocumented
        names = " ".join(f.message for f in findings)
        assert "fixture.chat" in names and "fixture.completions" in names


class TestSuppressions:
    def test_justified_suppression_silences(self):
        findings = flow("registry_suppressed", [DeadConfigKnob()])
        # DYNT_FUTURE suppressed; DYNT_TYPO's suppression names an
        # unknown rule: DF000 reported AND the DF403 still fires
        assert [f.rule for f in findings] == ["DF000", "DF403"]
        assert "DF999" in findings[0].message
        assert "DYNT_TYPO" in findings[1].message

    def test_dynalint_marker_does_not_suppress_dynaflow(self, tmp_path):
        root = tmp_path / "runtime"
        root.mkdir()
        src = (FIXTURES / "registry_suppressed" / "runtime"
               / "config.py").read_text()
        (root / "config.py").write_text(
            src.replace("# dynaflow: disable=DF403 -- reserved for the "
                        "next release",
                        "# dynalint: disable=DF403 -- wrong tool"))
        findings, _ = run([str(tmp_path)], rules=[DeadConfigKnob()])
        assert "DYNT_FUTURE" in " ".join(f.message for f in findings)


class TestCli:
    def test_json_output_and_exit_code(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.dynaflow",
             str(FIXTURES / "locks_pos.py"), "--format", "json"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 1
        data = json.loads(proc.stdout)
        assert data["files_checked"] == 1
        assert {f["rule"] for f in data["findings"]} == {"DF201", "DF202"}
        assert {r["id"] for r in data["rules"]} >= {
            "DF101", "DF102", "DF103", "DF104", "DF201", "DF202",
            "DF301", "DF302", "DF401", "DF402", "DF403", "DF404",
            "DF405"}

    def test_list_rules(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.dynaflow", "--list-rules"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0
        assert "DF101" in proc.stdout
        assert "wire-key-never-read" in proc.stdout

    def test_schema_update_on_current_tree_is_noop(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.dynaflow", "--schema-update"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0
        assert "already current" in proc.stdout


class TestRealTreeStaysClean:
    """The repo-wide clean-lint invariant: BOTH analyzers have zero
    unsuppressed findings on dynamo_tpu/. Regressions fail pytest
    locally, not just the CI lint job."""

    def test_dynaflow_clean(self):
        findings, files_checked = run([str(REPO / "dynamo_tpu")])
        assert files_checked > 100
        assert findings == [], "\n".join(
            f"{f.path}:{f.line}: {f.rule} {f.message}" for f in findings)

    def test_dynalint_clean(self):
        findings, files_checked = dynalint.run([str(REPO / "dynamo_tpu")])
        assert files_checked > 100
        assert findings == [], "\n".join(
            f"{f.path}:{f.line}: {f.rule} {f.message}" for f in findings)

    def test_schemas_current(self):
        """The checked-in snapshots match the tree (a drifted snapshot
        would already fail test_dynaflow_clean; this pins the four
        snapshot files exist)."""
        from tools.dynaflow import DEFAULT_PLANES, SCHEMA_DIR

        for plane in DEFAULT_PLANES:
            assert (SCHEMA_DIR / f"{plane.name}.json").exists(), plane.name
