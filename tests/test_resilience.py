"""Resilience plane tests (runtime/resilience.py + its request-plane,
router, and migration integration): deadlines propagate hop-to-hop and
bound every wait, retries draw on a token-bucket budget, breakers trip
and probe their way back.

Contract refs: "The Tail at Scale" end-to-end deadlines; Finagle
RetryBudget; the AWS decorrelated-jitter backoff scheme.
"""

import asyncio
import time
import uuid

import pytest

from dynamo_tpu.runtime import (
    DistributedRuntime,
    PushRouter,
    RuntimeConfig,
)
from dynamo_tpu.runtime.request_plane import RequestClient, TcpRequestServer
from dynamo_tpu.runtime.resilience import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryBudget,
    RetryPolicy,
)


class TestDeadline:
    def test_remaining_counts_down(self):
        d = Deadline(0.5)
        assert 0.4 < d.remaining() <= 0.5
        assert not d.expired()
        d2 = Deadline(-0.1)
        assert d2.expired()

    def test_wire_roundtrip_is_relative(self):
        d = Deadline(2.0)
        wire = d.to_wire()
        assert set(wire) == {"x-dynt-deadline-ms"}
        assert 1500 < wire["x-dynt-deadline-ms"] <= 2000
        d2 = Deadline.from_wire(wire)
        assert d2 is not None
        assert abs(d2.remaining() - d.remaining()) < 0.1

    def test_from_wire_tolerates_absent_and_garbage(self):
        assert Deadline.from_wire(None) is None
        assert Deadline.from_wire({}) is None
        assert Deadline.from_wire({"x-dynt-deadline-ms": "nope"}) is None
        d = Deadline.from_wire({"x-dynt-deadline-ms": "250"})
        assert d is not None and 0.2 < d.remaining() <= 0.25

    def test_bound_clamps_local_timeouts(self):
        d = Deadline(1.0)
        assert d.bound(10.0) <= 1.0
        assert d.bound(0.05) == 0.05
        assert d.bound(None) <= 1.0
        assert Deadline(-1.0).bound(10.0) == 0.0


class TestRetryPolicy:
    def test_decorrelated_jitter_bounds(self):
        policy = RetryPolicy(base_secs=0.01, cap_secs=0.5, max_attempts=4)
        prev = None
        for _ in range(100):
            prev = policy.next_delay(prev)
            assert 0.01 <= prev <= 0.5


class TestRetryBudget:
    def test_deposits_fund_retries(self):
        budget = RetryBudget(ratio=0.5, min_tokens=0.0, cap=10.0)
        assert not budget.try_spend()  # cold, no seed
        for _ in range(4):
            budget.deposit()  # 4 * 0.5 = 2 tokens
        assert budget.try_spend()
        assert budget.try_spend()
        assert not budget.try_spend()  # drained

    def test_seed_and_cap(self):
        budget = RetryBudget(ratio=1.0, min_tokens=2.0, cap=3.0)
        assert budget.try_spend() and budget.try_spend()
        assert not budget.try_spend()
        for _ in range(100):
            budget.deposit()
        assert budget.balance == 3.0  # capped


class TestCircuitBreaker:
    def test_open_after_threshold_and_single_probe_recovery(self):
        transitions = []
        b = CircuitBreaker(failure_threshold=2, reset_secs=0.05,
                           on_transition=transitions.append)
        assert b.try_acquire()
        b.record_failure()
        assert b.state == CLOSED  # 1 of 2
        b.record_failure()
        assert b.state == OPEN
        assert not b.can_attempt() and not b.try_acquire()
        time.sleep(0.06)
        assert b.can_attempt()
        assert b.try_acquire()  # the single half-open probe
        assert b.state == HALF_OPEN
        assert not b.try_acquire()  # second probe refused
        b.record_success(probe=True)
        assert b.state == CLOSED
        assert transitions == [OPEN, HALF_OPEN, CLOSED]

    def test_failed_probe_reopens(self):
        b = CircuitBreaker(failure_threshold=1, reset_secs=0.05)
        b.record_failure()
        assert b.state == OPEN
        time.sleep(0.06)
        assert b.try_acquire()
        b.record_failure(probe=True)
        assert b.state == OPEN
        assert not b.try_acquire()  # fresh reset window

    def test_release_probe_frees_the_slot(self):
        """A probe that ends with no health verdict (deadline ran out,
        application error, caller closed the stream) must return the
        half-open slot — a leaked slot locks the instance out forever."""
        b = CircuitBreaker(failure_threshold=1, reset_secs=0.01)
        b.record_failure()
        time.sleep(0.02)
        assert b.try_acquire()  # the probe goes out
        assert not b.try_acquire()
        b.release_probe()  # verdict-less exit by the probe owner
        assert b.state == HALF_OPEN
        assert b.can_attempt() and b.try_acquire()  # next probe admitted

    def test_reset_clears_state(self):
        b = CircuitBreaker(failure_threshold=1, reset_secs=60.0)
        b.record_failure()
        assert b.state == OPEN
        b.reset()
        assert b.state == CLOSED and b.try_acquire()


async def _tcp_server():
    server = TcpRequestServer("127.0.0.1", 0, advertise_host="127.0.0.1")
    await server.start()
    return server


@pytest.mark.parametrize("kind", ["tcp", "http"])
class TestRequestPlaneDeadline:
    """Wire-level contract: the server refuses expired budgets before
    dispatch, cancels overrunning handlers at the deadline, and the
    client surfaces DeadlineExceeded (never a bare timeout)."""

    async def _server(self, kind):
        if kind == "tcp":
            return await _tcp_server()
        from dynamo_tpu.runtime.request_plane import HttpRequestServer

        server = HttpRequestServer("127.0.0.1", 0, advertise_host="127.0.0.1")
        await server.start()
        return server

    def test_expired_deadline_refused_before_dispatch(self, run, kind):
        async def body():
            server = await self._server(kind)
            dispatched = []

            async def handler(req, ctx):
                dispatched.append(req)
                yield {"ok": True}

            server.registry.register("s/dl", handler)
            client = RequestClient()
            with pytest.raises(DeadlineExceeded):
                async for _ in client.call(server.address, "s/dl", {},
                                           {"x-dynt-deadline-ms": 0}):
                    pass
            assert dispatched == []  # never occupied the worker
            await client.close()
            await server.close()

        run(body())

    def test_handler_cancelled_at_deadline(self, run, kind):
        async def body():
            server = await self._server(kind)
            stopped = asyncio.Event()

            async def handler(req, ctx):
                try:
                    yield {"first": True}
                    await asyncio.sleep(30.0)  # would hold the slot 30s
                    yield {"never": True}
                except asyncio.CancelledError:
                    stopped.set()
                    raise

            server.registry.register("s/slow", handler)
            client = RequestClient()
            start = time.monotonic()
            got = []
            with pytest.raises(DeadlineExceeded):
                async for item in client.call(server.address, "s/slow", {},
                                              {"x-dynt-deadline-ms": 300}):
                    got.append(item)
            elapsed = time.monotonic() - start
            assert got == [{"first": True}]
            assert elapsed < 5.0, elapsed  # not the 30s handler sleep
            # the server-side watchdog cancelled the handler: the worker
            # slot is free well before the handler's own sleep ends
            await asyncio.wait_for(stopped.wait(), 2.0)
            await client.close()
            await server.close()

        run(body())

    def test_context_remaining_exposes_budget(self, run, kind):
        async def body():
            server = await self._server(kind)
            seen = {}

            async def handler(req, ctx):
                seen["remaining"] = ctx.remaining()
                seen["default"] = ctx.remaining(default=123.0)
                yield {"ok": True}

            server.registry.register("s/rem", handler)
            client = RequestClient()
            out = [x async for x in client.call(
                server.address, "s/rem", {}, {"x-dynt-deadline-ms": 5000})]
            assert out == [{"ok": True}]
            assert 0.0 < seen["remaining"] <= 5.0
            assert seen["default"] <= 5.0  # real deadline wins over default
            out = [x async for x in client.call(server.address, "s/rem", {})]
            assert out == [{"ok": True}]
            assert seen["remaining"] is None  # no deadline propagated
            assert seen["default"] == 123.0
            await client.close()
            await server.close()

        run(body())


class TestMemPlaneDeadline:
    def test_mem_plane_refuses_expired(self, run):
        from dynamo_tpu.runtime.request_plane import MemRequestPlane

        async def body():
            server = MemRequestPlane.create_server()

            async def handler(req, ctx):
                yield {"ok": True}

            server.registry.register("s/m", handler)
            with pytest.raises(DeadlineExceeded):
                async for _ in MemRequestPlane.call(
                        server.address, "s/m", {},
                        {"x-dynt-deadline-ms": 0}):
                    pass
            out = [x async for x in MemRequestPlane.call(
                server.address, "s/m", {}, {"x-dynt-deadline-ms": 5000})]
            assert out == [{"ok": True}]
            await server.close()

        run(body())


def _cfg(cluster):
    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = cluster
    cfg.request_plane = "tcp"
    cfg.tcp_host = "127.0.0.1"
    cfg.event_plane = "mem"
    cfg.system_enabled = False
    return cfg


async def _fake_instance(rt, ep, instance_id: int) -> None:
    """Advertise an instance whose wire subject has NO registered handler:
    dialing it fails with EndpointNotFound — a transport-class fault the
    router retries (unlike handler exceptions, which are application
    errors and must NOT trip breakers)."""
    await rt.put_leased(f"{ep.instance_prefix}{instance_id}", {
        "instance_id": instance_id,
        "address": rt.request_server.address,
        "subject": f"{ep.subject}/{instance_id}",
        "endpoint": ep.subject,
    })


class TestRouterResilience:
    def test_breaker_opens_and_recovers_via_probe(self, run):
        """A faulted instance trips its breaker; after reset_secs the single
        half-open probe re-admits it — the open->half_open->closed ladder."""

        async def body():
            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()

            async def healthy(req, ctx):
                yield {"ok": True}

            ep = rt.namespace("rz").component("w").endpoint("gen")
            await ep.serve_endpoint(healthy, instance_id=2)
            # instance 1 is advertised but its wire subject dangles: every
            # dial fails like a dead peer
            await _fake_instance(rt, ep, 1)
            client = ep.client()
            await client.wait_for_instances(2, timeout=5.0)
            from dynamo_tpu.runtime.resilience import BreakerBoard

            router = PushRouter(
                client, mode="round_robin",
                retry_policy=RetryPolicy(0.001, 0.005, 3),
                retry_budget=RetryBudget(ratio=1.0, min_tokens=10.0),
                breakers=BreakerBoard("rz/w/gen", failure_threshold=1,
                                      reset_secs=0.2),
            )
            # Drive until instance 1's failure trips its breaker; every
            # request still completes (retry lands on instance 2).
            for _ in range(4):
                out = [x async for x in router.generate({})]
                assert out == [{"ok": True}]
            breaker = router.breakers.get(1)
            assert breaker.state == OPEN
            assert router.available() == [2]
            # Heal: register the missing handler, wait out the reset
            # window — the next pick may probe instance 1.
            rt.request_server.registry.register(f"{ep.subject}/1", healthy)
            await asyncio.sleep(0.25)
            assert 1 in router.available()
            for _ in range(6):
                out = [x async for x in router.generate({})]
                assert out == [{"ok": True}]
            assert breaker.state == CLOSED
            await rt.shutdown()

        run(body(), timeout=30.0)

    def test_retry_budget_exhaustion_stops_storm(self, run):
        """With every instance dead and the budget drained, the router
        fails fast instead of multiplying retries."""
        from dynamo_tpu.runtime.request_plane import EndpointNotFound

        async def body():
            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()
            ep = rt.namespace("rz2").component("w").endpoint("gen")
            for iid in (1, 2, 3):
                await _fake_instance(rt, ep, iid)
            client = ep.client()
            await client.wait_for_instances(3, timeout=5.0)
            from dynamo_tpu.runtime.resilience import BreakerBoard

            budget = RetryBudget(ratio=0.1, min_tokens=1.0)
            router = PushRouter(
                client, mode="round_robin",
                retry_policy=RetryPolicy(0.001, 0.002, 10),
                retry_budget=budget,
                breakers=BreakerBoard("rz2/w/gen", failure_threshold=99,
                                      reset_secs=0.1),
            )
            with pytest.raises(EndpointNotFound):
                async for _ in router.generate({}):
                    pass
            # seed was 1 token: exactly one retry was admitted, then the
            # budget denied the rest (no storm against 3 dead workers)
            assert budget.balance < 1.0
            assert not budget.try_spend()
            await rt.shutdown()

        run(body(), timeout=30.0)

    def test_router_deadline_bounds_dispatch(self, run):
        """An expired deadline fails routing immediately; a live one is
        forwarded so the server can refuse late requests."""

        async def body():
            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()
            seen = []

            async def handler(req, ctx):
                seen.append(ctx.remaining())
                yield {"ok": True}

            ep = rt.namespace("rz3").component("w").endpoint("gen")
            await ep.serve_endpoint(handler, instance_id=1)
            client = ep.client()
            await client.wait_for_instances(1, timeout=5.0)
            router = PushRouter(client, mode="round_robin")
            out = [x async for x in router.generate(
                {}, deadline=Deadline(5.0))]
            assert out == [{"ok": True}]
            assert seen and 0.0 < seen[0] <= 5.0  # forwarded on the wire
            with pytest.raises(DeadlineExceeded):
                async for _ in router.generate({}, deadline=Deadline(-1.0)):
                    pass
            await rt.shutdown()

        run(body(), timeout=30.0)


class TestMigrationDeadline:
    def test_migration_stops_when_budget_spent(self, run):
        """Migration replay consumes the request's remaining budget: with
        the deadline expired it reports the overrun instead of replaying
        into a worker the client already abandoned."""
        from dynamo_tpu.llm.engine import Migration, TokenEngine
        from dynamo_tpu.llm.protocols import (
            EngineOutput,
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )
        from dynamo_tpu.runtime.request_plane import ConnectionLost

        class AlwaysBroken(TokenEngine):
            def __init__(self):
                self.attempts = 0

            async def generate(self, request):
                self.attempts += 1
                yield EngineOutput(token_ids=[self.attempts])
                raise ConnectionLost("gone")

        async def body():
            inner = AlwaysBroken()
            migration = Migration(inner, migration_limit=10_000,
                                  retry_policy=RetryPolicy(0.01, 0.02, 3))
            request = PreprocessedRequest(
                request_id="rz", token_ids=[1, 2],
                sampling=SamplingOptions(max_tokens=100),
                stop=StopConditions(),
                deadline=Deadline(0.05),
            )
            outs = [o async for o in migration.generate(request)]
            assert outs[-1].finish_reason == "error"
            assert "deadline exceeded" in outs[-1].error
            # far fewer than migration_limit attempts: the budget, not
            # the attempt cap, ended the replay loop
            assert inner.attempts < 100

        run(body(), timeout=30.0)
