"""dynalint golden tests: every rule exercised by a positive and a
negative fixture, suppression semantics, CLI output/exit codes, and the
gate that the real tree stays clean (the CI contract)."""

import json
import pathlib
import subprocess
import sys

from tools.dynalint import all_rules, run

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "dynalint"
REPO = pathlib.Path(__file__).parent.parent


def lint(*names):
    findings, _ = run([str(FIXTURES / n) for n in names])
    return findings


def hits(findings, rule):
    return [(f.path.rsplit("/", 1)[-1], f.line) for f in findings
            if f.rule == rule]


class TestRuleCatalogue:
    def test_at_least_eight_rules(self):
        assert len(all_rules()) >= 8

    def test_ids_and_names_unique(self):
        rules = all_rules()
        assert len({r.id for r in rules}) == len(rules)
        assert len({r.name for r in rules}) == len(rules)
        assert all(r.description for r in rules)


class TestFireAndForget:
    def test_positive(self):
        findings = lint("fire_and_forget_pos.py")
        assert hits(findings, "DL101") == [
            ("fire_and_forget_pos.py", 6),
            ("fire_and_forget_pos.py", 10),
            ("fire_and_forget_pos.py", 14),
            ("fire_and_forget_pos.py", 21),
        ]

    def test_negative(self):
        assert hits(lint("fire_and_forget_neg.py"), "DL101") == []

    def test_reintroduction_is_caught(self, tmp_path):
        """Acceptance probe: a scratch fire-and-forget create_task is
        flagged."""
        scratch = tmp_path / "scratch.py"
        scratch.write_text(
            "import asyncio\n\n\n"
            "async def oops():\n"
            "    asyncio.create_task(asyncio.sleep(1))\n")
        findings, _ = run([str(scratch)])
        assert ("DL101", 5) in [(f.rule, f.line) for f in findings]

    def test_hidden_ancestor_does_not_hide_the_tree(self, tmp_path):
        """A checkout under a dot-directory must still be linted — only
        hidden dirs BELOW the lint root are skipped."""
        root = tmp_path / ".work" / "repo"
        root.mkdir(parents=True)
        (root / "mod.py").write_text(
            "import asyncio\n\n\n"
            "async def oops():\n"
            "    asyncio.create_task(asyncio.sleep(1))\n")
        (root / ".hidden").mkdir()
        (root / ".hidden" / "skipme.py").write_text("import asyncio\n")
        findings, files_checked = run([str(root)])
        assert files_checked == 1
        assert ("DL101", 5) in [(f.rule, f.line) for f in findings]


class TestBlockingInAsync:
    def test_positive(self):
        findings = lint("blocking_async_pos.py")
        assert hits(findings, "DL102") == [
            ("blocking_async_pos.py", 9),
            ("blocking_async_pos.py", 10),
            ("blocking_async_pos.py", 11),
        ]

    def test_negative(self):
        assert hits(lint("blocking_async_neg.py"), "DL102") == []


class TestAsyncWithoutAwait:
    def test_positive(self):
        findings = lint("async_no_await_pos.py")
        assert hits(findings, "DL103") == [("async_no_await_pos.py", 4)]

    def test_negative_exemptions(self):
        assert hits(lint("async_no_await_neg.py"), "DL103") == []

    def test_duck_sibling_crosses_files(self):
        """An awaitless method is exempt when ANOTHER file implements the
        same name with a real await (interface conformity)."""
        solo = lint("async_no_await_pos.py")
        assert hits(solo, "DL103") != []
        paired_src = FIXTURES / "async_no_await_neg.py"
        both, _ = run([str(FIXTURES / "async_no_await_pos.py"),
                       str(paired_src)])
        assert hits(both, "DL103") != []  # no sibling named crunch_numbers


class TestHostSyncInLoop:
    def test_positive(self):
        findings = lint("engine/host_sync_pos.py")
        assert hits(findings, "DL201") == [
            ("host_sync_pos.py", 11),
            ("host_sync_pos.py", 14),
            ("host_sync_pos.py", 16),
            ("host_sync_pos.py", 17),
        ]

    def test_negative(self):
        assert hits(lint("engine/host_sync_neg.py"), "DL201") == []

    def test_scoped_to_hot_paths(self, tmp_path):
        """The same code outside engine/kv_router paths is not flagged —
        the rule is a hot-path rule, not a general numpy ban."""
        cold = tmp_path / "cold.py"
        cold.write_text(
            (FIXTURES / "engine" / "host_sync_pos.py").read_text())
        findings, _ = run([str(cold)])
        assert hits(findings, "DL201") == []


class TestJitScalarArg:
    def test_positive(self):
        findings = lint("jit_scalar_pos.py")
        assert hits(findings, "DL202") == [
            ("jit_scalar_pos.py", 10),
            ("jit_scalar_pos.py", 15),
            ("jit_scalar_pos.py", 20),
        ]

    def test_negative(self):
        assert hits(lint("jit_scalar_neg.py"), "DL202") == []


class TestUnserializableProtocolField:
    def test_positive(self):
        findings = lint("protocols_pos.py")
        assert hits(findings, "DL301") == [
            ("protocols_pos.py", 10),
            ("protocols_pos.py", 11),
        ]

    def test_negative(self):
        assert hits(lint("protocols_neg.py"), "DL301") == []


class TestUnconsumedSamplingField:
    def test_positive(self):
        findings, _ = run([str(FIXTURES / "proj_unconsumed")])
        assert [(f.rule, f.path.rsplit("/", 1)[-1], f.line)
                for f in findings] == [("DL302", "protocols.py", 9)]

    def test_negative(self):
        findings, _ = run([str(FIXTURES / "proj_consumed")])
        assert findings == []


class TestMetricNamePrefix:
    def test_positive(self):
        findings = lint("metrics_pos.py")
        assert hits(findings, "DL303") == [
            ("metrics_pos.py", 4),
            ("metrics_pos.py", 5),
        ]
        legacy = [f for f in findings if "dynt_queue_depth" in f.message]
        assert legacy and "dynamo_queue_depth" in legacy[0].message

    def test_negative(self):
        assert hits(lint("metrics_neg.py"), "DL303") == []
        assert hits(lint("metrics_nonprom.py"), "DL303") == []


class TestSuppressions:
    def test_semantics(self):
        findings = lint("suppressions.py")
        by_rule = {}
        for f in findings:
            by_rule.setdefault(f.rule, []).append(f.line)
        # line 8: justified DL101 suppression silences it
        assert 8 not in by_rule.get("DL101", [])
        # line 12: suppressing the WRONG rule does not silence DL101
        assert 12 in by_rule["DL101"]
        # line 16: suppression by rule name works too
        assert 16 not in by_rule.get("DL101", [])
        # line 20: unknown rule in the suppression is itself reported,
        # and the original finding still fires
        assert 20 in by_rule["DL000"]
        assert 20 in by_rule["DL102"]

    def test_unknown_rule_message_names_catalogue(self):
        findings = lint("suppressions.py")
        bad = [f for f in findings if f.rule == "DL000"]
        assert len(bad) == 1
        assert "DL999" in bad[0].message and "DL101" in bad[0].message


class TestCli:
    def test_json_output_and_exit_code(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.dynalint",
             str(FIXTURES / "metrics_pos.py"), "--format", "json"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 1
        data = json.loads(proc.stdout)
        assert data["files_checked"] == 1
        assert [f["rule"] for f in data["findings"]] == ["DL303", "DL303"]
        assert {r["id"] for r in data["rules"]} >= {
            "DL101", "DL102", "DL103", "DL201", "DL202",
            "DL301", "DL302", "DL303"}

    def test_clean_file_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.dynalint",
             str(FIXTURES / "metrics_neg.py")],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0

    def test_list_rules(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.dynalint", "--list-rules"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0
        assert "DL101" in proc.stdout and "fire-and-forget-task" \
            in proc.stdout


class TestRealTreeStaysClean:
    def test_dynamo_tpu_lints_clean(self):
        """The CI contract: the shipped tree has zero findings (true
        findings fixed, false positives suppressed with justification)."""
        findings, files_checked = run([str(REPO / "dynamo_tpu")])
        assert files_checked > 100
        assert findings == [], "\n".join(
            f"{f.path}:{f.line}: {f.rule} {f.message}" for f in findings)
