"""Deadline-aware admission tests (runtime/admission.py + the three
admission edges): drain-rate EWMA convergence, empty-queue / stalled-
drain / cold-start edge cases, per-pool isolation, and refusal semantics
at the frontend, the router admission queue, and the prefill router
(docs/fault-tolerance.md shed-early rung)."""

import asyncio
import math
import uuid

import pytest

from dynamo_tpu.kv_router import KvRouterConfig, KvScheduler, WorkerWithDpRank
from dynamo_tpu.kv_router.queue import QueuedRequest, SchedulerQueue
from dynamo_tpu.runtime.admission import (
    AdmissionRefused,
    DrainRateEwma,
    QueueWaitEstimator,
    check_admission,
)
from dynamo_tpu.runtime.resilience import Deadline


def _deadline(secs: float) -> Deadline:
    return Deadline(secs)


class TestDrainRateEwma:
    def test_cold_rate_is_none(self):
        assert DrainRateEwma().rate(now=10.0) is None

    def test_converges_to_steady_rate(self):
        ewma = DrainRateEwma(halflife_s=2.0)
        # 4 drains/sec for 30 seconds of virtual time.
        t = 0.0
        while t < 30.0:
            ewma.observe(1, now=t)
            t += 0.25
        rate = ewma.rate(now=t)
        assert rate == pytest.approx(4.0, rel=0.15)

    def test_batch_observations_equal_singles(self):
        a, b = DrainRateEwma(halflife_s=2.0), DrainRateEwma(halflife_s=2.0)
        for i in range(1, 41):
            a.observe(2, now=i * 0.5)
        for i in range(1, 41):
            b.observe(2.0, now=i * 0.5)
        assert a.rate(now=20.0) == pytest.approx(b.rate(now=20.0))

    def test_stall_decays_rate(self):
        ewma = DrainRateEwma(halflife_s=2.0)
        for i in range(20):
            ewma.observe(1, now=float(i))
        healthy = ewma.rate(now=19.0)
        assert healthy > 0.5
        # Within the half-life grace window the rate holds...
        assert ewma.rate(now=20.5) == pytest.approx(healthy)
        # ...then decays toward zero: 10 half-lives of silence.
        stalled = ewma.rate(now=19.0 + 2.0 + 20.0)
        assert stalled < healthy / 500


class TestQueueWaitEstimator:
    def _warmed(self, pool="p", halflife=2.0, rate=5.0,
                until=20.0) -> QueueWaitEstimator:
        est = QueueWaitEstimator(pool=pool, halflife_s=halflife)
        t = 0.0
        while t < until:
            est.observe_drained(1, now=t)
            t += 1.0 / rate
        return est

    def test_empty_queue_estimates_zero_and_admits(self):
        est = self._warmed()
        assert est.estimate_wait_ms(now=20.0) == 0.0
        # Even a nearly-spent budget is admitted against an empty queue.
        decision = est.check(_deadline(0.001), now=20.0)
        assert decision.admit

    def test_cold_start_admits_despite_depth(self):
        est = QueueWaitEstimator(pool="cold")
        est.update_worker(1, 50, now=0.0)
        # No drain ever observed: no evidence of a stall -> admit.
        assert est.estimate_wait_ms(now=1.0) == 0.0
        assert est.check(_deadline(0.5), now=1.0).admit

    def test_wait_tracks_depth_over_rate(self):
        est = self._warmed(rate=5.0)
        est.update_worker(1, 10, now=20.0)
        est.update_worker(2, 10, now=20.0)
        # 20 queued at ~5/s -> ~4s estimated wait.
        assert est.estimate_wait_ms(now=20.0) == pytest.approx(4000,
                                                               rel=0.3)
        assert not est.check(_deadline(1.0), now=20.0).admit
        assert est.check(_deadline(30.0), now=20.0).admit

    def test_stalled_drain_refuses_with_capped_retry_after(self):
        est = self._warmed(rate=5.0)
        est.update_worker(1, 10, now=120.0)  # backlog, drain long dead
        wait = est.estimate_wait_ms(now=120.0)
        assert math.isinf(wait)
        decision = est.check(_deadline(60.0), now=120.0)
        assert not decision.admit
        # Stalled pool advertises the DYNT_RETRY_AFTER_MAX_SECS cap.
        assert decision.retry_after_s == 30.0

    def test_retry_after_floor_and_cap(self):
        est = self._warmed(rate=5.0)
        assert est.retry_after_s(10.0) == 1.0  # floor
        assert est.retry_after_s(10_000.0) == 10.0
        assert est.retry_after_s(10_000_000.0) == 30.0  # cap

    def test_per_pool_isolation(self):
        drowning = self._warmed(pool="prefill", rate=1.0)
        drowning.update_worker(1, 100, now=20.0)
        healthy = self._warmed(pool="decode", rate=10.0)
        healthy.update_worker(1, 1, now=20.0)
        assert not drowning.check(_deadline(5.0), now=20.0).admit
        assert healthy.check(_deadline(5.0), now=20.0).admit

    def test_dead_worker_depth_expires(self):
        est = self._warmed(rate=5.0, until=100.0)
        est.update_worker(1, 40, now=100.0)
        assert est.depth(now=100.0) == 40
        # TTL (30s) passes with no fresh report: the dead worker's
        # backlog stops counting.
        assert est.depth(now=140.0) == 0

    def test_no_deadline_always_admits(self):
        est = self._warmed(rate=1.0)
        est.update_worker(1, 1000, now=20.0)
        assert est.check(None, now=20.0).admit

    def test_vanished_pool_set_depth_expires(self):
        # An edge that owns its queue stops reporting (pool vanished
        # from discovery): its frozen backlog must stop estimating an
        # unbounded wait against a ghost.
        est = self._warmed(rate=5.0, until=100.0)
        est.set_depth(50, now=100.0)
        assert est.depth(now=110.0) == 50
        assert est.estimate_wait_ms(now=110.0) > 0
        # worker_ttl_s (30s) with no fresh set_depth: decays to empty.
        assert est.depth(now=131.0) == 0
        assert est.estimate_wait_ms(now=131.0) == 0.0
        # ...and the estimator is reusable when the pool comes back.
        est.set_depth(3, now=200.0)
        assert est.depth(now=201.0) == 3

    def test_fresh_set_depth_keeps_counting(self):
        est = self._warmed(rate=5.0, until=100.0)
        est.set_depth(50, now=100.0)
        est.set_depth(40, now=125.0)  # still reporting
        assert est.depth(now=140.0) == 40

    def test_forget_worker_drops_backlog_immediately(self):
        est = self._warmed(rate=5.0, until=100.0)
        est.update_worker(1, 30, now=100.0)
        est.update_worker(2, 10, now=100.0)
        assert est.depth(now=101.0) == 40
        # Positive discovery delete: no TTL wait.
        est.forget_worker(1)
        assert est.depth(now=101.0) == 10


class TestCheckAdmission:
    def _stalled(self) -> QueueWaitEstimator:
        """A stalled pool anchored to the REAL clock (check_admission
        reads time.monotonic()): drain learned long ago, fresh backlog."""
        import time

        base = time.monotonic()
        est = QueueWaitEstimator(pool=f"t-{uuid.uuid4().hex[:6]}",
                                 halflife_s=1.0)
        for i in range(10):
            est.observe_drained(1, now=base - 500.0 + i)
        est.update_worker(1, 50, now=base)
        return est

    def test_refusal_raises_and_counts(self):
        from dynamo_tpu.runtime.metrics import REQUESTS_SHED

        est = self._stalled()
        before = REQUESTS_SHED.labels(reason="queue")._value.get()
        with pytest.raises(AdmissionRefused) as exc_info:
            check_admission(est, _deadline(5.0))
        assert exc_info.value.retry_after_s > 0
        assert exc_info.value.pool == est.pool
        after = REQUESTS_SHED.labels(reason="queue")._value.get()
        assert after == before + 1

    def test_disabled_admits_unconditionally(self, monkeypatch):
        monkeypatch.setenv("DYNT_ADMISSION_ENABLE", "0")
        decision = check_admission(self._stalled(), _deadline(5.0))
        assert decision.admit

    def test_healthy_pool_admits(self):
        est = QueueWaitEstimator(pool="healthy", halflife_s=2.0)
        now = 0.0
        while now < 20.0:
            est.observe_drained(1, now=now)
            now += 0.1
        est.update_worker(1, 1, now=20.0)
        assert check_admission(est, _deadline(10.0)).admit


BS = 16
W0 = WorkerWithDpRank(1)


class TestRouterQueueEdge:
    """Deadline-aware refusal at the router admission queue: a request
    about to PARK is checked against the heap's drain estimate."""

    def _queue(self) -> SchedulerQueue:
        sched = KvScheduler(KvRouterConfig(block_size=BS))
        return SchedulerQueue(sched, threshold_frac=0.5,
                              max_batched_tokens=lambda w: 100)

    def test_park_with_surviving_budget_still_parks(self, run):
        async def body():
            q = self._queue()
            await q.schedule(QueuedRequest(
                candidates=[W0], block_hashes=[], isl_tokens=96,
                request_id="warm"))
            task = asyncio.create_task(q.schedule(QueuedRequest(
                candidates=[W0], block_hashes=[], isl_tokens=8,
                request_id="r1", deadline=Deadline(60.0))))
            await asyncio.sleep(0.05)
            assert q.pending_count == 1
            q.scheduler.free("warm")
            q.update()
            result = await asyncio.wait_for(task, 2.0)
            assert result.worker == W0

        run(body())

    def test_park_with_doomed_budget_refused(self, run):
        async def body():
            q = self._queue()
            # Teach the estimator a slow-but-known drain, then stall it.
            for i in range(10):
                q.wait_estimator.observe_drained(1, now=float(i))
            q.wait_estimator.drain._last = -1000.0  # long-dead drain
            await q.schedule(QueuedRequest(
                candidates=[W0], block_hashes=[], isl_tokens=96,
                request_id="warm"))
            # Busy worker + non-empty backlog ahead: the next arrival
            # would park behind a stalled drain -> refused, not parked.
            parked = asyncio.create_task(q.schedule(QueuedRequest(
                candidates=[W0], block_hashes=[], isl_tokens=8,
                request_id="r1")))  # no deadline: parks fine
            await asyncio.sleep(0.05)
            assert q.pending_count == 1
            with pytest.raises(AdmissionRefused):
                await q.schedule(QueuedRequest(
                    candidates=[W0], block_hashes=[], isl_tokens=8,
                    request_id="r2", deadline=Deadline(2.0)))
            # The refused request never booked load or parked.
            assert q.pending_count == 1
            parked.cancel()
            try:
                await parked
            except asyncio.CancelledError:
                pass

        run(body())

    def test_drains_feed_rate(self, run):
        async def body():
            q = self._queue()
            await q.schedule(QueuedRequest(
                candidates=[W0], block_hashes=[], isl_tokens=96,
                request_id="warm"))
            task = asyncio.create_task(q.schedule(QueuedRequest(
                candidates=[W0], block_hashes=[], isl_tokens=8,
                request_id="r1")))
            await asyncio.sleep(0.05)
            assert q.wait_estimator.drain.rate() is None  # cold
            q.scheduler.free("warm")
            q.update()
            await asyncio.wait_for(task, 2.0)
            assert q.wait_estimator.drain.rate() is not None

        run(body())


class TestPrefillRouterEdge:
    def _pool(self):
        from dynamo_tpu.llm.prefill_router import PrefillPool

        pool = PrefillPool(router=None)  # router untouched on refusal
        pool.instances = {7}
        return pool

    def _request(self, deadline_secs=2.0):
        from dynamo_tpu.llm.protocols import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )

        req = PreprocessedRequest(
            request_id="pf-req", token_ids=[1, 2, 3, 4],
            sampling=SamplingOptions(max_tokens=4), stop=StopConditions())
        req.deadline = Deadline(deadline_secs)
        return req

    def test_doomed_budget_refused_before_prefill_leg(self, run):
        from dynamo_tpu.llm.prefill_router import PrefillRouterEngine

        import time

        base = time.monotonic()
        pool = self._pool()
        for i in range(10):
            pool.wait_estimator.observe_drained(1, now=base - 500.0 + i)
        pool.wait_estimator.update_worker(7, 30, now=base)

        class Inner:
            async def generate(self, request):
                raise AssertionError("refusal must precede any dispatch")
                yield  # pragma: no cover

        engine = PrefillRouterEngine(Inner(), pool_lookup=lambda: pool)

        async def body():
            with pytest.raises(AdmissionRefused):
                async for _ in engine.generate(self._request()):
                    pass

        run(body())

    def test_inactive_pool_skips_admission(self, run):
        from dynamo_tpu.llm.prefill_router import (
            PrefillPool,
            PrefillRouterEngine,
        )
        from dynamo_tpu.llm.protocols import EngineOutput

        pool = PrefillPool(router=None)  # no instances -> aggregated

        class Inner:
            async def generate(self, request):
                yield EngineOutput(token_ids=[1], finish_reason="stop")

        engine = PrefillRouterEngine(Inner(), pool_lookup=lambda: pool)

        async def body():
            outs = [o async for o in engine.generate(self._request(0.001))]
            assert outs[-1].finish_reason == "stop"

        run(body())


class TestFrontendEdge:
    """End-to-end over the real frontend + a mocker worker: a request
    whose x-dynt-deadline-ms budget cannot survive the (forced) queue
    estimate is shed 503 with an estimator-derived Retry-After."""

    def _cfg(self, cluster):
        from dynamo_tpu.runtime import RuntimeConfig

        cfg = RuntimeConfig.from_env()
        cfg.discovery_backend = "mem"
        cfg.discovery_path = cluster
        cfg.request_plane = "tcp"
        cfg.tcp_host = "127.0.0.1"
        cfg.event_plane = "mem"
        cfg.system_enabled = False
        return cfg

    def test_frontend_sheds_doomed_budget_with_retry_after(self, run):
        import aiohttp

        from dynamo_tpu.frontend import Frontend
        from dynamo_tpu.mocker import MockerConfig, MockerWorker
        from dynamo_tpu.runtime import DistributedRuntime

        async def body():
            cluster = uuid.uuid4().hex
            wrt = await DistributedRuntime(self._cfg(cluster)).start()
            worker = MockerWorker(
                wrt, model_name="adm-model",
                config=MockerConfig(speedup_ratio=200.0, num_blocks=256),
                load_publish_interval=0.2)
            await worker.start()
            frt = await DistributedRuntime(self._cfg(cluster)).start()
            frontend = Frontend(frt, host="127.0.0.1", port=0)
            await frontend.start()
            try:
                for _ in range(100):
                    if frontend.manager.get("adm-model") is not None:
                        break
                    await asyncio.sleep(0.05)
                entry = frontend.manager.get("adm-model")
                assert entry is not None
                base = f"http://127.0.0.1:{frontend.port}"
                payload = {"model": "adm-model", "max_tokens": 2,
                           "messages": [{"role": "user",
                                         "content": "hello"}]}
                async with aiohttp.ClientSession() as session:
                    # Healthy path first (also warms the pipeline).
                    async with session.post(
                            base + "/v1/chat/completions", json=payload,
                            headers={"x-dynt-deadline-ms": "30000"}) as r:
                        assert r.status == 200, await r.text()
                    # Force a measured-slow, deep queue into the entry's
                    # estimator: ~1 drain per 2s, 30 queued -> ~60s wait.
                    est = entry.wait_estimator
                    for i in range(10):
                        est.observe_drained(1, now=float(i) * 2.0)
                    import time as _time

                    est.drain._last = _time.monotonic()
                    est.update_worker(next(iter(entry.instances)), 30)
                    async with session.post(
                            base + "/v1/chat/completions", json=payload,
                            headers={"x-dynt-deadline-ms": "2000"}) as r:
                        assert r.status == 503, await r.text()
                        retry_after = int(r.headers["Retry-After"])
                        # Estimated drain (~60s) capped at
                        # DYNT_RETRY_AFTER_MAX_SECS=30.
                        assert retry_after == 30
                        body_json = await r.json()
                        assert "queue wait" in \
                            body_json["error"]["message"]
                    # A patient client (or none of the above) still gets
                    # served: shedding is per-budget, not a breaker.
                    async with session.post(
                            base + "/v1/chat/completions", json=payload,
                            headers={"x-dynt-deadline-ms": "300000"}) as r:
                        assert r.status == 200, await r.text()
            finally:
                await frontend.close()
                await frt.shutdown()
                await worker.close()
                await wrt.shutdown()

        run(body(), timeout=90)


class TestSloObserverDrain:
    def test_first_token_observes_drain(self):
        from dynamo_tpu.llm.http_service import _SloObserver
        from dynamo_tpu.llm.protocols import (
            EngineOutput,
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )

        est = QueueWaitEstimator(pool="obs")
        req = PreprocessedRequest(
            request_id="obs-req", token_ids=[1],
            sampling=SamplingOptions(max_tokens=2), stop=StopConditions())
        obs = _SloObserver(req, 0.0, 0.0, wait_estimator=est)
        assert est.drain._last is None
        obs.on_output(EngineOutput(token_ids=[5]))
        first = est.drain._last
        assert first is not None
        # Later chunks are NOT drains — only entering service is.
        obs.on_output(EngineOutput(token_ids=[6]))
        assert est.drain._last == first


class TestTenantLedger:
    """Weighted fair-share quota admission (docs/multi-tenancy.md):
    sliding-window token-rate accounting; under contention an
    over-share tenant is refused FIRST (shed reason="quota")."""

    def _ledger(self, capacity=1000.0, window=10.0, weights=None):
        from dynamo_tpu.runtime.admission import TenantLedger

        return TenantLedger(capacity_tps=capacity, window_s=window,
                            weights=weights or {}, default_weight=1.0)

    def test_disabled_capacity_always_admits(self):
        ledger = self._ledger(capacity=0.0)
        for _ in range(100):
            assert ledger.check("flood", 10_000, contended=True).admit

    def test_untagged_tenant_never_quota_checked(self):
        ledger = self._ledger(capacity=10.0)
        assert ledger.check("", 10_000, contended=True).admit

    def test_window_rate_accounting(self):
        ledger = self._ledger(capacity=1000.0, window=10.0)
        now = 100.0
        ledger.observe("a", 500, now=now)
        ledger.observe("a", 500, now=now + 1)
        assert ledger.rate("a", now=now + 1) == 100.0  # 1000 tok / 10 s
        # Events age out of the window.
        assert ledger.rate("a", now=now + 10.5) == 50.0
        assert ledger.rate("a", now=now + 20.0) == 0.0

    def test_uncontended_under_capacity_admits(self):
        ledger = self._ledger(capacity=1000.0, window=10.0)
        now = 0.0
        ledger.observe("a", 4000, now=now)  # 400 tok/s
        assert ledger.check("a", 1000, contended=False, now=now).admit

    def test_over_share_refused_under_contention(self):
        ledger = self._ledger(capacity=1000.0, window=10.0)
        now = 0.0
        # Two active tenants, equal weights: 500 tok/s weighted share
        # each; the victim's real 400 tok/s demand leaves the flood only
        # 600 tok/s of work-conserving headroom.
        ledger.observe("flood", 8000, now=now)   # 800 tok/s
        ledger.observe("victim", 4000, now=now)  # 400 tok/s
        flood = ledger.check("flood", 500, contended=True, now=now)
        victim = ledger.check("victim", 500, contended=True, now=now)
        assert not flood.admit
        assert "fair share" in flood.reason
        assert flood.retry_after_s >= 1.0
        assert victim.admit

    def test_weights_shift_the_share(self):
        ledger = self._ledger(capacity=1000.0, window=10.0,
                              weights={"gold": 3.0, "bronze": 1.0})
        now = 0.0
        ledger.observe("gold", 7000, now=now)    # 700 tok/s < 750 share
        ledger.observe("bronze", 3000, now=now)  # 300 tok/s > 250 share
        assert ledger.check("gold", 100, contended=True, now=now).admit
        assert not ledger.check("bronze", 100, contended=True,
                                now=now).admit

    def test_work_conserving_idle_capacity_usable(self):
        """A lone flooding tenant may use capacity the others are not
        using — the quota arbitrates contention, it does not idle
        chips."""
        ledger = self._ledger(capacity=1000.0, window=10.0)
        now = 0.0
        ledger.observe("flood", 8000, now=now)  # 800 tok/s, alone
        assert ledger.check("flood", 1000, contended=True, now=now).admit
        # A second tenant's demand squeezes the share back down.
        ledger.observe("other", 6000, now=now)  # 600 tok/s
        assert not ledger.check("flood", 1000, contended=True,
                                now=now).admit

    def test_check_tenant_admission_counts_and_raises(self):
        import time as _time

        from dynamo_tpu.runtime.admission import (
            AdmissionRefused,
            check_tenant_admission,
        )
        from dynamo_tpu.runtime.metrics import REQUESTS_SHED, TENANT_SHED

        ledger = self._ledger(capacity=100.0, window=10.0)
        now = _time.monotonic()
        ledger.observe("flood", 2000, now=now)
        ledger.observe("peer", 500, now=now)
        before = TENANT_SHED.labels(tenant="flood",
                                    reason="quota")._value.get()
        before_q = REQUESTS_SHED.labels(reason="quota")._value.get()
        with pytest.raises(AdmissionRefused) as exc_info:
            check_tenant_admission(ledger, "flood", 100, contended=True)
        assert exc_info.value.reason == "quota"
        assert TENANT_SHED.labels(tenant="flood",
                                  reason="quota")._value.get() \
            == before + 1
        assert REQUESTS_SHED.labels(reason="quota")._value.get() \
            == before_q + 1

    def test_observe_only_on_entry_edge(self):
        from dynamo_tpu.runtime.admission import check_tenant_admission

        ledger = self._ledger(capacity=10_000.0, window=10.0)
        check_tenant_admission(ledger, "a", 100, observe=False)
        assert ledger.rate("a") == 0.0
        check_tenant_admission(ledger, "a", 100, observe=True)
        assert ledger.rate("a") > 0.0

    def test_parse_weights_spec(self):
        from dynamo_tpu.runtime.admission import parse_tenant_weights

        assert parse_tenant_weights("a=4,b=1.5") == {"a": 4.0, "b": 1.5}
        # Malformed entries are skipped, not fatal.
        assert parse_tenant_weights("a=4,junk,c=-1,=2,d=x") == {"a": 4.0}
        assert parse_tenant_weights("") == {}

    def test_singleton_reset(self):
        from dynamo_tpu.runtime.admission import (
            get_tenant_ledger,
            reset_tenant_ledger,
        )

        first = get_tenant_ledger()
        assert get_tenant_ledger() is first
        reset_tenant_ledger()
        assert get_tenant_ledger() is not first
        reset_tenant_ledger()
