"""Frontend E2E: OpenAI HTTP <-> discovery <-> mocker workers, in-process
(ref contract: section 3.1 startup + request flow; router E2E pattern from
tests/router/test_router_e2e_with_mockers.py)."""

import asyncio
import json
import uuid

import aiohttp
import pytest

from dynamo_tpu.frontend import Frontend
from dynamo_tpu.mocker import MockerConfig, MockerWorker
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


def _cfg(cluster):
    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = cluster
    cfg.request_plane = "tcp"
    cfg.tcp_host = "127.0.0.1"
    cfg.event_plane = "mem"
    cfg.system_enabled = False
    cfg.lease_ttl_secs = 1.0
    return cfg


async def _setup(cluster, n_workers=1, router_mode="round_robin",
                 model="mock-model"):
    workers = []
    for _ in range(n_workers):
        rt = await DistributedRuntime(_cfg(cluster)).start()
        worker = MockerWorker(
            rt, model_name=model,
            config=MockerConfig(speedup_ratio=500.0, num_blocks=256),
            load_publish_interval=0.2,
        )
        await worker.start()
        workers.append((rt, worker))
    frt = await DistributedRuntime(_cfg(cluster)).start()
    frontend = Frontend(frt, host="127.0.0.1", port=0, router_mode=router_mode)
    await frontend.start()
    # Wait for model registration.
    for _ in range(100):
        if frontend.manager.get(model) is not None:
            break
        await asyncio.sleep(0.05)
    return frontend, frt, workers


async def _teardown(frontend, frt, workers):
    await frontend.close()
    await frt.shutdown()
    for rt, worker in workers:
        await worker.close()
        await rt.shutdown()


class TestFrontendE2E:
    def test_models_and_nonstreaming_chat(self, run):
        async def body():
            frontend, frt, workers = await _setup(uuid.uuid4().hex)
            base = f"http://127.0.0.1:{frontend.port}"
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/v1/models") as resp:
                    models = await resp.json()
                    assert models["data"][0]["id"] == "mock-model"
                payload = {
                    "model": "mock-model",
                    "messages": [{"role": "user", "content": "hello"}],
                    "max_tokens": 8,
                }
                async with session.post(f"{base}/v1/chat/completions",
                                        json=payload) as resp:
                    assert resp.status == 200
                    data = await resp.json()
                    assert data["object"] == "chat.completion"
                    assert data["usage"]["completion_tokens"] == 8
                    assert len(data["choices"][0]["message"]["content"]) > 0
            await _teardown(frontend, frt, workers)

        run(body(), timeout=90)

    def test_streaming_sse(self, run):
        async def body():
            frontend, frt, workers = await _setup(uuid.uuid4().hex)
            base = f"http://127.0.0.1:{frontend.port}"
            payload = {
                "model": "mock-model",
                "messages": [{"role": "user", "content": "hello"}],
                "max_tokens": 6,
                "stream": True,
                "stream_options": {"include_usage": True},
            }
            chunks = []
            async with aiohttp.ClientSession() as session:
                async with session.post(f"{base}/v1/chat/completions",
                                        json=payload) as resp:
                    assert resp.status == 200
                    assert resp.headers["Content-Type"].startswith("text/event-stream")
                    async for line in resp.content:
                        line = line.decode().strip()
                        if line.startswith("data: "):
                            chunks.append(line[len("data: "):])
            assert chunks[-1] == "[DONE]"
            parsed = [json.loads(c) for c in chunks[:-1]]
            finishes = [p["choices"][0]["finish_reason"]
                        for p in parsed if p.get("choices")]
            assert "length" in finishes
            usage = [p for p in parsed if p.get("usage")]
            assert usage and usage[-1]["usage"]["completion_tokens"] == 6
            await _teardown(frontend, frt, workers)

        run(body(), timeout=90)

    def test_completions_endpoint(self, run):
        async def body():
            frontend, frt, workers = await _setup(uuid.uuid4().hex)
            base = f"http://127.0.0.1:{frontend.port}"
            async with aiohttp.ClientSession() as session:
                async with session.post(
                    f"{base}/v1/completions",
                    json={"model": "mock-model", "prompt": "abc",
                          "max_tokens": 4},
                ) as resp:
                    assert resp.status == 200
                    data = await resp.json()
                    assert data["object"] == "text_completion"
                    assert len(data["choices"][0]["text"]) > 0
            await _teardown(frontend, frt, workers)

        run(body(), timeout=90)

    def test_unknown_model_404_and_bad_request_400(self, run):
        async def body():
            frontend, frt, workers = await _setup(uuid.uuid4().hex)
            base = f"http://127.0.0.1:{frontend.port}"
            async with aiohttp.ClientSession() as session:
                async with session.post(
                    f"{base}/v1/chat/completions",
                    json={"model": "nope", "messages": [
                        {"role": "user", "content": "x"}]},
                ) as resp:
                    assert resp.status == 404
                async with session.post(
                    f"{base}/v1/chat/completions",
                    json={"model": "mock-model"},
                ) as resp:
                    assert resp.status == 400
            await _teardown(frontend, frt, workers)

        run(body(), timeout=90)

    def test_kv_router_mode_e2e(self, run):
        async def body():
            frontend, frt, workers = await _setup(
                uuid.uuid4().hex, n_workers=2, router_mode="kv")
            model = frontend.manager.get("mock-model")
            assert model is not None and model.scheduler is not None
            base = f"http://127.0.0.1:{frontend.port}"
            prompt = "shared prefix " * 40  # several blocks
            async with aiohttp.ClientSession() as session:
                for i in range(4):
                    async with session.post(
                        f"{base}/v1/completions",
                        json={"model": "mock-model", "prompt": prompt,
                              "max_tokens": 4},
                    ) as resp:
                        assert resp.status == 200
                        await resp.json()
                    await asyncio.sleep(0.1)
            # KV events flowed: the router's index knows some blocks.
            assert model.scheduler.indexer.total_nodes() > 0
            # All requests after the first should hit the same worker
            # (cached prefix dominates the cost model).
            await _teardown(frontend, frt, workers)

        run(body(), timeout=90)

    def test_admin_and_docs_routes(self, run):
        """The reference's operational route set (busy_threshold.rs,
        clear_kv_blocks.rs, /openapi.json + /docs from service_v2.rs):
        get-or-set per-model thresholds, whole-fleet KV cache clear with
        per-worker outcomes, and the generated API docs."""
        async def body():
            frontend, frt, workers = await _setup(uuid.uuid4().hex)
            base = f"http://127.0.0.1:{frontend.port}"
            async with aiohttp.ClientSession() as session:
                # get-or-set busy thresholds
                async with session.get(f"{base}/busy_threshold") as resp:
                    assert (await resp.json())["thresholds"] == []
                async with session.post(
                    f"{base}/busy_threshold",
                    json={"model": "mock-model",
                          "active_decode_blocks_threshold": 0.9},
                ) as resp:
                    data = await resp.json()
                    assert data["active_decode_blocks_threshold"] == 0.9
                async with session.post(
                    f"{base}/busy_threshold", json={"model": "mock-model"},
                ) as resp:  # get via threshold-less POST
                    data = await resp.json()
                    assert data["active_decode_blocks_threshold"] == 0.9
                async with session.get(f"{base}/busy_threshold") as resp:
                    data = await resp.json()
                    assert data["thresholds"] == [
                        {"model": "mock-model",
                         "active_decode_blocks_threshold": 0.9}]
                async with session.post(
                    f"{base}/busy_threshold", json={"model": "nope"},
                ) as resp:
                    assert resp.status == 404
                async with session.post(
                    f"{base}/busy_threshold",
                    json={"model": "mock-model",
                          "active_decode_blocks_threshold": 7},
                ) as resp:
                    assert resp.status == 400

                # seed the prefix cache, then clear it fleet-wide
                payload = {
                    "model": "mock-model",
                    "messages": [{"role": "user", "content": "x" * 200}],
                    "max_tokens": 4,
                }
                async with session.post(f"{base}/v1/chat/completions",
                                        json=payload) as resp:
                    assert resp.status == 200
                async with session.post(f"{base}/clear_kv_blocks") as resp:
                    data = await resp.json()
                    assert data["failed_workers"] == []
                    assert len(data["cleared_workers"]) == 1
                    assert data["cleared_workers"][0]["status"] == "cleared"
                    assert data["cleared_workers"][0]["response"][
                        "cleared"] >= 1

                # generated docs
                async with session.get(f"{base}/openapi.json") as resp:
                    spec = await resp.json()
                    assert spec["openapi"].startswith("3.")
                    assert "/v1/chat/completions" in spec["paths"]
                    assert "/clear_kv_blocks" in spec["paths"]
                async with session.get(f"{base}/docs") as resp:
                    assert resp.status == 200
                    assert "/openapi.json" in await resp.text()
            await _teardown(frontend, frt, workers)

        run(body(), timeout=90)

    def test_worker_death_model_unlisted(self, run):
        async def body():
            cluster = uuid.uuid4().hex
            frontend, frt, workers = await _setup(cluster)
            base = f"http://127.0.0.1:{frontend.port}"
            rt, worker = workers[0]
            await worker.close()
            await rt.shutdown()
            for _ in range(100):
                if frontend.manager.get("mock-model") is None:
                    break
                await asyncio.sleep(0.05)
            assert frontend.manager.get("mock-model") is None
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/v1/models") as resp:
                    assert (await resp.json())["data"] == []
            await frontend.close()
            await frt.shutdown()

        run(body(), timeout=90)
