"""Fleet observatory unit tier (dynamo_tpu/observatory/): histogram
quantile merges against a single-process oracle, burn-rate math on the
injectable rollup clock (firing thresholds, hysteresis, window_scale
compression), the threshold rule catalogue, collector breaker behavior,
discovery-card target building, the bounded label registry,
/debug/requests filtering + pagination, and log-record correlation."""

import json
import logging
import math
import random
import threading
import time

import pytest

from dynamo_tpu.observatory.alerts import (
    AlertEngine,
    BurnRateRule,
    default_rules,
)
from dynamo_tpu.observatory.collector import (
    FleetCollector,
    ScrapeTarget,
    Snapshot,
    targets_from_cards,
)
from dynamo_tpu.observatory.rollup import (
    FleetRollup,
    PoolRollup,
    build_rollup,
    merged_buckets,
    quantile_from_buckets,
)
from dynamo_tpu.runtime import metrics as rt_metrics
from dynamo_tpu.runtime.metric_labels import (
    OVERFLOW,
    LabelRegistry,
    bounded_label,
    reset_label_registry,
)

TTFT = "dynamo_time_to_first_token_seconds"
_LES = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, math.inf)


def _counter(name, **labels):
    for metric in rt_metrics.REGISTRY.collect():
        if metric.name != name.removesuffix("_total"):
            continue
        for sample in metric.samples:
            if sample.name == name and all(
                    sample.labels.get(k) == v for k, v in labels.items()):
                return sample.value
    return 0.0


def hist_buckets(samples):
    """Observe `samples` into one cumulative histogram over _LES."""
    return [(le, float(sum(1 for s in samples if s <= le)))
            for le in _LES]


def ttft_families(samples):
    fams = {}
    for le, count in hist_buckets(samples):
        text = "+Inf" if math.isinf(le) else f"{le:g}"
        fams[(TTFT + "_bucket", (("le", text),))] = count
    return fams


def snap(name, pool, families, at=0.0):
    return Snapshot(target=ScrapeTarget(name=name, pool=pool), at=at,
                    families=families)


class TestQuantileMerge:
    def test_merge_matches_single_process_oracle(self):
        """Merging per-process histograms must equal observing the
        union of all samples into ONE histogram — the property that
        makes the fleet quantile honest."""
        rng = random.Random(7)
        shards = [[rng.lognormvariate(-1.5, 0.8) for _ in range(200)]
                  for _ in range(4)]
        snaps = [snap(f"w{i}", "decode", ttft_families(s))
                 for i, s in enumerate(shards)]
        union = [x for shard in shards for x in shard]
        for q in (0.5, 0.9, 0.95, 0.99):
            merged = quantile_from_buckets(
                merged_buckets(snaps, TTFT), q)
            oracle = quantile_from_buckets(hist_buckets(union), q)
            assert merged == pytest.approx(oracle), q

    def test_pool_filter_restricts_the_merge(self):
        snaps = [snap("d0", "decode", ttft_families([0.04] * 10)),
                 snap("p0", "prefill", ttft_families([4.9] * 10))]
        decode_p95 = quantile_from_buckets(
            merged_buckets(snaps, TTFT, pool="decode"), 0.95)
        prefill_p95 = quantile_from_buckets(
            merged_buckets(snaps, TTFT, pool="prefill"), 0.95)
        assert decode_p95 <= 0.05 < prefill_p95

    def test_inf_rank_clamps_to_last_finite_bound(self):
        buckets = hist_buckets([10.0, 11.0, 12.0])  # all past 5.0
        assert quantile_from_buckets(buckets, 0.5) == 5.0

    def test_empty_and_zero_histograms_are_nan(self):
        assert math.isnan(quantile_from_buckets([], 0.5))
        assert math.isnan(quantile_from_buckets(
            [(le, 0.0) for le in _LES], 0.5))


def roll_at(at, good, total):
    roll = FleetRollup(at=at)
    roll.slo_good = good
    roll.slo_total = total
    return roll


class TestBurnRate:
    """One rule, hand-checkable numbers: slo_target 0.9 (10% budget),
    threshold 4.5x, 50.5s/10s windows, 4.5s clear hold — fractional
    constants chosen so no comparison (threshold, clear floor, hold,
    window base selection) lands exactly on a tick boundary; a tie
    there would make the transition tick an artifact of FP rounding,
    not of the math. Traffic is 10 requests per tick, all-good or
    all-bad."""

    def _rule(self):
        return BurnRateRule("slo_burn", severity="page", slo_target=0.9,
                            threshold=4.5, long_s=50.5, short_s=10.0,
                            clear_hold_s=4.5)

    def _drive(self, scale, warm, bad, tail):
        """healthy(warm) -> 100% errors(bad) -> healthy(tail); returns
        [(tick, transition)] with ticks de-scaled for comparison."""
        engine = AlertEngine([self._rule()], window_scale=scale,
                             log_cap=32)
        good = total = 0.0
        out = []
        for tick in range(warm + bad + tail):
            failed = warm <= tick < warm + bad
            good += 0.0 if failed else 10.0
            total += 10.0
            for tr in engine.evaluate(roll_at(tick * scale, good, total)):
                out.append((tick, tr["transition"], tr["epoch"]))
        return engine, out

    def test_windowed_burn_math(self):
        engine = AlertEngine([self._rule()], log_cap=8)
        engine.evaluate(roll_at(0.0, 100.0, 100.0))
        engine.evaluate(roll_at(10.0, 100.0, 200.0))
        # last 10s: 100 requests, all errors -> err 1.0 / budget 0.1
        assert engine.burn_rate(10.0, 10.0, 0.9) == pytest.approx(10.0)
        # empty window (no finished requests) burns nothing
        assert engine.burn_rate(10.0, 200.0, 0.9) == 0.0

    def test_lifecycle_fires_resolves_with_hysteresis(self):
        engine, out = self._drive(1.0, warm=20, bad=25, tail=75)
        assert [t for _, t, _ in out] == ["firing", "resolved"]
        fired, resolved = out[0][0], out[1][0]
        # The short window saturates early (burn 10x by tick 30) but
        # the page waits for the long window's significance: 16 bad
        # ticks of the 35 in the window -> burn 4.57x > 4.5x.
        assert fired == 35
        # Errors stop at tick 44; resolution waits for the long burn to
        # drop under threshold*resolve_ratio (2.25x, first true at tick
        # 84) AND hold there for clear_hold_s — not the first clean
        # tick.
        assert resolved == 89
        assert engine.active() == []

    def test_short_spike_without_long_significance_stays_quiet(self):
        """A 15-tick blip saturates the short window (burn 10x) but
        never gives the long window >45% errors: no page, ever."""
        engine, out = self._drive(1.0, warm=20, bad=15, tail=40)
        assert out == []
        assert engine.active() == []

    def test_window_scale_compresses_without_changing_the_math(self):
        _, reference = self._drive(1.0, warm=20, bad=25, tail=75)
        _, compressed = self._drive(1.0 / 30.0, warm=20, bad=25, tail=75)
        assert compressed == reference

    def test_refire_opens_a_new_epoch(self):
        engine = AlertEngine([self._rule()], log_cap=32)
        good = total = 0.0
        epochs = []
        for tick in range(240):
            # two outages with a long quiet gap between them
            failed = 20 <= tick < 45 or 140 <= tick < 165
            good += 0.0 if failed else 10.0
            total += 10.0
            for tr in engine.evaluate(roll_at(float(tick), good, total)):
                epochs.append((tr["transition"], tr["epoch"]))
        assert epochs == [("firing", 1), ("resolved", 1),
                          ("firing", 2), ("resolved", 2)]


class TestThresholdRules:
    def _engine(self):
        return AlertEngine(default_rules(), log_cap=16)

    def _fired(self, engine, roll):
        return {t["rule"]: t for t in engine.evaluate(roll)
                if t["transition"] == "firing"}

    def test_host_bound_names_the_worst_pool(self):
        engine = self._engine()
        roll = FleetRollup(at=1.0)
        roll.pools["prefill"] = PoolRollup(pool="prefill", host_bound=2)
        roll.pools["decode"] = PoolRollup(pool="decode", host_bound=1)
        fired = self._fired(engine, roll)
        assert fired["host_bound_workers"]["pool"] == "prefill"
        assert "3 host-bound" in fired["host_bound_workers"]["detail"]

    def test_breaker_storm_threshold_is_three(self):
        engine = self._engine()
        roll = FleetRollup(at=1.0)
        roll.breakers_open = 2
        assert "breaker_storm" not in self._fired(engine, roll)
        roll = FleetRollup(at=2.0)
        roll.breakers_open = 3
        assert "breaker_storm" in self._fired(engine, roll)

    def test_journal_corruption_is_delta_based(self):
        engine = self._engine()
        steady = FleetRollup(at=1.0)
        steady.journal_bad_frames = 7.0
        # first sight of a nonzero cumulative counter fires (prev=None
        # bases at zero) ...
        assert "journal_corruption" in self._fired(engine, steady)
        # ... and a FLAT counter afterwards resolves: corruption is an
        # event, not a standing condition.
        flat = FleetRollup(at=2.0)
        flat.journal_bad_frames = 7.0
        transitions = engine.evaluate(flat)
        assert [(t["rule"], t["transition"]) for t in transitions] == [
            ("journal_corruption", "resolved")]

    def test_protocol_violations_fire_on_new_counts(self):
        engine = self._engine()
        first = FleetRollup(at=1.0)
        assert engine.evaluate(first) == []
        bad = FleetRollup(at=2.0)
        bad.protocol_violations = 1.0
        assert "protocol_violations" in self._fired(engine, bad)

    def test_federation_lag_past_contract(self):
        engine = self._engine()
        roll = FleetRollup(at=1.0)
        roll.federation_max_lag_s = 1e9
        fired = self._fired(engine, roll)
        assert "federation_lag" in fired
        assert "contract" in fired["federation_lag"]["detail"]


EXPO = ("dynamo_slo_good_total 5.0\n"
        "dynamo_slo_requests_total 10.0\n")


class TestFleetCollector:
    def _collector(self, fetch, **kw):
        kw.setdefault("timeout_ms", 1000.0)
        kw.setdefault("breaker_reset_secs", 60.0)
        return FleetCollector(fetch=fetch, **kw)

    def test_breaker_opens_after_failures_and_skips(self):
        calls = []
        dead = set()

        def fetch(target, deadline):
            calls.append(target.name)
            if target.name in dead:
                raise ConnectionError("down")
            return EXPO

        col = self._collector(fetch)
        col.add_target(ScrapeTarget(name="a", pool="p"))
        col.add_target(ScrapeTarget(name="b", pool="p"))
        before_skip = _counter("dynamo_fleet_scrapes_total",
                               outcome="skipped")
        fresh = col.poll(1.0)
        assert set(fresh) == {"a", "b"}
        assert col.snapshots["a"].value("dynamo_slo_good_total") == 5.0

        dead.add("b")
        col.poll(2.0)
        col.poll(3.0)  # second failure -> breaker opens
        assert col._breakers["b"].state == "open"
        fresh = col.poll(4.0)  # open breaker: skipped, not fetched
        assert set(fresh) == {"a"}
        assert calls.count("b") == 3  # 1 ok + 2 failures, then gated
        assert _counter("dynamo_fleet_scrapes_total",
                        outcome="skipped") - before_skip == 1.0
        # the stale snapshot stays available for the rollup
        assert "b" in col.snapshots
        assert _counter("dynamo_fleet_targets", health="ok") == 1.0
        assert _counter("dynamo_fleet_targets", health="broken") == 1.0

    def test_deadline_expiry_counts_as_error(self):
        def slow_fetch(target, deadline):
            time.sleep(0.01)
            return EXPO

        col = self._collector(slow_fetch, timeout_ms=1.0)
        col.add_target(ScrapeTarget(name="slow"))
        before = _counter("dynamo_fleet_scrapes_total", outcome="error")
        assert col.poll(1.0) == {}
        assert _counter("dynamo_fleet_scrapes_total",
                        outcome="error") - before == 1.0
        assert "slow" not in col.snapshots

    def test_dead_target_shows_broken_despite_stale_snapshot(self):
        # Regression: the rollup used to recount self.snapshots, whose
        # stale entries (kept for fold continuity) hid a dead target
        # forever — targets_broken stayed 0 after a worker died.
        from dynamo_tpu.observatory.service import Observatory

        dead = set()

        def fetch(target, deadline):
            if target.name in dead:
                raise ConnectionError("down")
            return EXPO

        obs = Observatory(
            targets=[ScrapeTarget(name="a", pool="p"),
                     ScrapeTarget(name="b", pool="p")],
            fetch=fetch, scrape_timeout_ms=1000.0,
            breaker_reset_secs=60.0)
        roll = obs.tick(1.0)
        assert (roll.targets_ok, roll.targets_broken) == (2, 0)

        dead.add("b")
        obs.tick(2.0)
        roll = obs.tick(3.0)  # second failure -> breaker opens
        assert obs.collector._breakers["b"].state == "open"
        assert (roll.targets_ok, roll.targets_broken) == (1, 1)
        assert (obs.collector.last_ok, obs.collector.last_broken) == (1, 1)
        # the stale snapshot still feeds the fold, only the health
        # split reflects the death
        assert "b" in obs.collector.snapshots

    def test_set_targets_reconciles_and_clears_state(self):
        col = self._collector(lambda t, d: EXPO)
        col.add_target(ScrapeTarget(name="a"))
        col.add_target(ScrapeTarget(name="b"))
        col.poll(1.0)
        col.set_targets([ScrapeTarget(name="a"), ScrapeTarget(name="c")])
        assert sorted(t.name for t in col.targets()) == ["a", "c"]
        assert "b" not in col.snapshots
        assert "b" not in col._breakers


class TestTargetsFromCards:
    def test_cards_build_deduped_pooled_targets(self):
        cards = [
            {"instance_id": 7, "subject": "ns.prefill.generate",
             "system_url": "http://h:1"},
            {"instance_id": 8, "subject": "ns.decode.generate",
             "metadata": {"system_url": "http://h:2", "cell": "c1"}},
            # same process (same status server) -> one target
            {"instance_id": 9, "subject": "ns.decode.generate",
             "system_url": "http://h:1"},
            # no status server advertised -> not scrapeable
            {"instance_id": 10, "subject": "ns.x.y"},
        ]
        targets = targets_from_cards(cards)
        assert [(t.name, t.url, t.pool, t.cell) for t in targets] == [
            ("7", "http://h:1", "prefill", ""),
            ("8", "http://h:2", "decode", "c1"),
        ]

    def test_metadata_pool_overrides_subject(self):
        (target,) = targets_from_cards(
            [{"instance_id": 1, "subject": "ns.decode.generate",
              "system_url": "http://h:9",
              "metadata": {"pool": "decode-spot"}}])
        assert target.pool == "decode-spot"

    def test_live_slash_subjects_pool_by_component(self):
        # the shape runtime/component.py actually publishes
        (target,) = targets_from_cards(
            [{"instance_id": 4870798920945837939,
              "subject": "dynamo/mocker/generate/4870798920945837939",
              "system_url": "http://127.0.0.1:35965"}])
        assert target.pool == "mocker"
        assert target.name == "4870798920945837939"


class TestRollupFields:
    def test_build_rollup_folds_the_planes(self):
        fam_a = dict(ttft_families([0.04] * 20))
        fam_a.update({
            ("dynamo_slo_good_total", ()): 90.0,
            ("dynamo_slo_requests_total", ()): 100.0,
            ("dynamo_mfu", ()): 0.5,
            ("dynamo_host_bound", ()): 1.0,
            ("dynamo_circuit_breaker_state",
             (("endpoint", "e"), ("instance", "0"))): 1.0,
            ("dynamo_journal_bad_frames_total", ()): 2.0,
            ("dynamo_kv_usage_ratio", ()): 0.7,
            ("dynamo_federation_lag_seconds", ()): 1.5,
        })
        fam_b = dict(ttft_families([2.0] * 20))
        fam_b.update({
            ("dynamo_slo_good_total", ()): 40.0,
            ("dynamo_slo_requests_total", ()): 100.0,
            ("dynamo_mfu", ()): 0.3,
            ("dynamo_kv_usage_ratio", ()): 0.9,
        })
        roll = build_rollup([snap("d0", "decode", fam_a),
                             snap("p0", "prefill", fam_b)], at=5.0)
        assert roll.at == 5.0 and roll.targets_ok == 2
        assert roll.goodput_ratio == pytest.approx(0.65)
        assert roll.pools["decode"].mfu == pytest.approx(0.5)
        assert roll.pools["decode"].host_bound == 1
        assert roll.breakers_open == 1
        assert roll.journal_bad_frames == 2.0
        assert roll.kv_usage_max == pytest.approx(0.9)
        assert roll.federation_max_lag_s == pytest.approx(1.5)
        # prefill's merged TTFT p95 dominates -> it is the worst pool
        assert roll.pools["prefill"].ttft_p95_s > \
            roll.pools["decode"].ttft_p95_s
        assert roll.worst_pool() == "prefill"
        json.dumps(roll.to_json())  # the /fleet pane must serialize

    def test_worst_pool_nan_sorts_last(self):
        roll = FleetRollup(at=1.0)
        roll.pools["idle"] = PoolRollup(pool="idle")  # ttft nan
        roll.pools["busy"] = PoolRollup(pool="busy", ttft_p95_s=0.2)
        assert roll.worst_pool() == "busy"


class TestLabelRegistry:
    def test_first_k_wins_admission_is_sticky(self):
        reg = LabelRegistry(cap=2)
        assert reg.admit("tenant", "a") == "a"
        assert reg.admit("tenant", "b") == "b"
        assert reg.admit("tenant", "c") == OVERFLOW
        assert reg.admit("tenant", "a") == "a"  # admitted stays admitted
        assert reg.admit("tenant", "c") == OVERFLOW
        assert reg.overflowed("tenant") == 2
        assert reg.admitted("tenant") == {"a", "b"}
        # namespaces bound independently
        assert reg.admit("cell", "c") == "c"

    def test_empty_value_passes_through(self):
        reg = LabelRegistry(cap=1)
        assert reg.admit("tenant", "") == ""
        assert reg.admitted("tenant") == set()

    def test_bounded_label_env_cap_and_overflow_counter(self, monkeypatch):
        monkeypatch.setenv("DYNT_METRIC_MAX_LABELS", "1")
        reset_label_registry()
        try:
            before = _counter("dynamo_metric_label_overflow_total",
                              namespace="tenant")
            assert bounded_label("tenant", "t0") == "t0"
            assert bounded_label("tenant", "t1") == OVERFLOW
            assert _counter("dynamo_metric_label_overflow_total",
                            namespace="tenant") - before == 1.0
        finally:
            reset_label_registry()

    def test_concurrent_admission_never_exceeds_cap(self):
        reg = LabelRegistry(cap=8)

        def worker(start):
            for i in range(100):
                reg.admit("ns", f"v{(start + i) % 40}")

        threads = [threading.Thread(target=worker, args=(j * 7,))
                   for j in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(reg.admitted("ns")) == 8


class TestDebugRequestsFiltering:
    def test_filters_pagination_and_totals(self, run):
        import aiohttp

        from dynamo_tpu.runtime.flight_recorder import (
            get_recorder,
            reset_recorder,
        )
        from dynamo_tpu.runtime.status import SystemStatusServer

        reset_recorder()
        rec = get_recorder()
        for i in range(4):
            rec.start(f"ok-{i}", model="m1")
            rec.finish(f"ok-{i}", "ok")
        for i in range(3):
            rec.start(f"err-{i}", model="m2")
            rec.finish(f"err-{i}", "error")
        rec.start("live-0", model="m1")

        async def body():
            server = SystemStatusServer(port=0, host="127.0.0.1")
            await server.start()
            base = f"http://127.0.0.1:{server.port}/debug/requests"
            out = {}
            try:
                async with aiohttp.ClientSession() as session:
                    for name, qs in (("err", "?status=error"),
                                     ("page",
                                      "?status=error&limit=2&offset=1"),
                                     ("model", "?model=m1"),
                                     ("bad", "?limit=x")):
                        async with session.get(base + qs) as resp:
                            out[name] = (resp.status, await resp.json())
            finally:
                await server.close()
            return out

        out = run(body())
        reset_recorder()
        status, err = out["err"]
        assert status == 200
        assert err["total_completed"] == 3 and err["total_inflight"] == 0
        assert [t["request_id"] for t in err["completed"]] == [
            "err-2", "err-1", "err-0"]  # newest first
        _, page = out["page"]
        assert page["total_completed"] == 3  # pre-pagination total
        assert [t["request_id"] for t in page["completed"]] == [
            "err-1", "err-0"]
        _, by_model = out["model"]
        assert by_model["total_inflight"] == 1
        assert by_model["total_completed"] == 4
        status, bad = out["bad"]
        assert status == 400 and "integers" in bad["error"]


class TestLogCorrelation:
    def _record(self):
        return logging.LogRecord("dynamo_tpu.observatory", logging.WARNING,
                                 __file__, 1, "capture bundle written: %s",
                                 ("/tmp/b/000000-slo_burn_fast",), None)

    def test_jsonl_formatter_carries_correlation_fields(self):
        from dynamo_tpu.runtime.logging import (
            _JsonlFormatter,
            current_request_id,
            current_trace_id,
            set_log_cell,
        )

        tok_r = current_request_id.set("req-1")
        tok_t = current_trace_id.set("ab" * 16)
        set_log_cell("cell-9")
        try:
            entry = json.loads(_JsonlFormatter().format(self._record()))
        finally:
            current_request_id.reset(tok_r)
            current_trace_id.reset(tok_t)
            set_log_cell("")
        assert entry["request_id"] == "req-1"
        assert entry["trace_id"] == "ab" * 16
        assert entry["cell"] == "cell-9"
        assert "000000-slo_burn_fast" in entry["message"]

    def test_readable_formatter_shows_cell_and_request(self):
        from dynamo_tpu.runtime.logging import (
            _ReadableFormatter,
            current_request_id,
            set_log_cell,
        )

        tok = current_request_id.set("req-12345678-extra")
        set_log_cell("cell-9")
        try:
            line = _ReadableFormatter().format(self._record())
        finally:
            current_request_id.reset(tok)
            set_log_cell("")
        assert "(cell-9)" in line and "[req-1234" in line

    def test_log_json_knob_selects_jsonl(self, monkeypatch):
        import dynamo_tpu.runtime.logging as dlog
        from dynamo_tpu.runtime.config import env

        monkeypatch.setenv("DYNT_LOG_JSON", "1")
        dlog.configure_logging(level="WARNING")
        root = logging.getLogger("dynamo_tpu")
        try:
            assert isinstance(root.handlers[0].formatter,
                              dlog._JsonlFormatter)
        finally:
            monkeypatch.delenv("DYNT_LOG_JSON")
            dlog.configure_logging(level=str(env("DYNT_LOG_LEVEL")))
        assert isinstance(root.handlers[0].formatter,
                          dlog._ReadableFormatter)
