"""Native C++ extension: build, hash parity with the Python fallback, and
radix-tree contract equivalence (csrc/native.cpp)."""

import random

import os

import pytest
import xxhash

from dynamo_tpu.native import get_native
from dynamo_tpu.tokens import (
    INITIAL_SEED,
    TokenBlockSequence,
    compute_block_hashes,
    hash_block,
)


@pytest.fixture(scope="module")
def native():
    mod = get_native()
    if mod is None:
        pytest.skip("native extension not built")
    return mod


class TestHashParity:
    def test_xxh64_matches_reference_library(self, native):
        rng = random.Random(0)
        for n in (0, 1, 7, 8, 31, 32, 33, 100, 4096):
            data = bytes(rng.randrange(256) for _ in range(n))
            seed = rng.getrandbits(64)
            assert native.hash_bytes(data, seed) == xxhash.xxh64_intdigest(
                data, seed=seed
            )

    def test_chained_block_hashes_match_python_fallback(self, native):
        rng = random.Random(1)
        tokens = [rng.randrange(1 << 20) for _ in range(70)]
        got = native.compute_block_hashes(tokens, 16, INITIAL_SEED)
        seed = INITIAL_SEED
        want = []
        for s in range(0, len(tokens) - 15, 16):
            seed = hash_block(tokens[s : s + 16], seed)
            want.append(seed)
        assert got == want
        # public API routes through native and agrees too
        assert compute_block_hashes(tokens, 16) == want

    def test_incremental_matches_batch(self, native):
        tokens = list(range(100))
        seq = TokenBlockSequence(block_size=16)
        out = []
        for t in tokens:  # worst case: one token at a time
            out.extend(seq.extend([t]))
        assert out == compute_block_hashes(tokens, 16)

    def test_buffer_input(self, native):
        import numpy as np

        tokens = np.arange(64, dtype=np.uint32)
        assert native.compute_block_hashes(
            tokens.tobytes(), 16, 5
        ) == native.compute_block_hashes(list(tokens), 16, 5)


class TestNativeRadixEquivalence:
    """Random event streams must produce identical scores in both backends."""

    def test_random_event_stream(self, native):
        from dynamo_tpu.kv_router import (
            KvCacheRemoved,
            KvCacheStored,
            NativeRadixTree,
            RadixTree,
            RouterEvent,
        )

        rng = random.Random(42)
        py, nat = RadixTree(), NativeRadixTree(native)
        live: list[int] = []
        eid = {w: 0 for w in (1, 2, 3)}
        for _ in range(400):
            w = rng.choice((1, 2, 3))
            eid[w] += 1
            if live and rng.random() < 0.3:
                victims = rng.sample(live, min(len(live), rng.randrange(1, 4)))
                ev = RouterEvent(
                    worker_id=w, event_id=eid[w],
                    removed=KvCacheRemoved(block_hashes=victims),
                )
            else:
                parent = rng.choice(live) if live and rng.random() < 0.5 else None
                chain = [rng.randrange(1, 1 << 48) for _ in range(rng.randrange(1, 5))]
                live.extend(chain)
                ev = RouterEvent(
                    worker_id=w, event_id=eid[w],
                    stored=KvCacheStored(block_hashes=chain, parent_hash=parent),
                )
            assert py.apply_event(ev) == nat.apply_event(ev)
            probe = rng.sample(live, min(len(live), 8)) if live else []
            a, b = py.find_matches(probe), nat.find_matches(probe)
            assert a.scores == b.scores
            assert a.tree_sizes == b.tree_sizes
        assert py.total_nodes() == nat.total_nodes()

    def test_dump_load_roundtrip(self, native):
        from dynamo_tpu.kv_router import (
            KvCacheStored,
            NativeRadixTree,
            RouterEvent,
            WorkerWithDpRank,
        )

        tree = NativeRadixTree(native)
        w = WorkerWithDpRank(7)
        tree.apply_event(
            RouterEvent(worker_id=7, event_id=1,
                        stored=KvCacheStored(block_hashes=[1, 2, 3]))
        )
        tree.apply_event(
            RouterEvent(worker_id=7, event_id=2,
                        stored=KvCacheStored(block_hashes=[9], parent_hash=2))
        )
        dump = tree.dump_worker(w)
        fresh = NativeRadixTree(native)
        fresh.load_worker(w, dump, last_event_id=2)
        assert fresh.find_matches([1, 2, 3]).scores == {w: 3}
        assert fresh.find_matches([1, 2, 9]).scores == {w: 3}
        assert fresh.worker_block_counts() == {w: 4}


class TestSanitizers:
    """ASan/UBSan + TSan over the native radix core (ref SURVEY section
    5.2: the reference gets safety from Rust ownership; our C++ earns it
    with sanitizers). Skipped when g++ is unavailable."""

    @pytest.mark.parametrize("flags", ["address,undefined", "thread"])
    def test_stress_clean_under_sanitizer(self, flags, tmp_path):
        import shutil
        import subprocess
        import sys

        if shutil.which("g++") is None:
            pytest.skip("g++ not available")
        src = os.path.join(os.path.dirname(__file__), "..", "csrc",
                           "sanitize_stress.cpp")
        csrc = os.path.dirname(src)
        binary = str(tmp_path / f"stress_{flags.split(',')[0]}")
        build = subprocess.run(
            ["g++", "-std=c++17", "-O1", "-g", f"-fsanitize={flags}",
             f"-I{csrc}", src, "-o", binary],
            capture_output=True, text=True, timeout=300)
        assert build.returncode == 0, build.stderr
        run_proc = subprocess.run([binary], capture_output=True, text=True,
                                  timeout=300)
        assert run_proc.returncode == 0, (run_proc.stdout + run_proc.stderr)
        assert "all ok" in run_proc.stdout
