"""Logprobs: sampler correctness, engine plumbing, OpenAI API surface,
analysis tooling (ref surface: OpenAI logprobs params + lib/llm/src/perf/
logprobs.rs)."""

import asyncio
import json
import math
import uuid

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.sampler import TOP_LOGPROBS_K, sample_with_logprobs
from dynamo_tpu.frontend import Frontend
from dynamo_tpu.mocker import MockerConfig, MockerWorker
from dynamo_tpu.perf.logprobs import (
    RequestLogprobs,
    aggregate,
    from_recording,
    from_response,
)
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


class TestSamplerLogprobs:
    def test_greedy_token_logprob_and_topk(self):
        logits = jnp.asarray([[0.0, 1.0, 3.0, 2.0],
                              [5.0, 0.0, 0.0, 0.0]], jnp.float32)
        b = logits.shape[0]
        tokens, lp, top_ids, top_lps = sample_with_logprobs(
            logits, jnp.zeros(b), jnp.ones(b), jnp.zeros(b, jnp.int32),
            jnp.zeros(b, jnp.uint32), jnp.int32(0))
        tokens = np.asarray(tokens)
        assert list(tokens) == [2, 0]  # greedy
        # sampled logprob == log softmax at the token
        ref = np.asarray(jnp.log(jnp.exp(logits)
                                 / jnp.sum(jnp.exp(logits), axis=-1,
                                           keepdims=True)))
        np.testing.assert_allclose(np.asarray(lp),
                                   [ref[0, 2], ref[1, 0]], rtol=1e-5)
        # top alternatives sorted descending, K wide
        assert np.asarray(top_ids).shape == (2, min(TOP_LOGPROBS_K, 4))
        assert np.asarray(top_ids)[0, 0] == 2
        tl = np.asarray(top_lps)
        assert all(tl[0, i] >= tl[0, i + 1] for i in range(3))

    def test_logprob_reflects_raw_distribution_not_temperature(self):
        logits = jnp.asarray([[0.0, 2.0]], jnp.float32)
        _, lp_cold, _, _ = sample_with_logprobs(
            logits, jnp.asarray([0.0]), jnp.ones(1),
            jnp.zeros(1, jnp.int32), jnp.zeros(1, jnp.uint32), jnp.int32(0))
        _, lp_hot, _, _ = sample_with_logprobs(
            logits, jnp.asarray([0.0001]), jnp.ones(1),
            jnp.zeros(1, jnp.int32), jnp.zeros(1, jnp.uint32), jnp.int32(0))
        # same token, same RAW logprob regardless of temperature
        np.testing.assert_allclose(np.asarray(lp_cold), np.asarray(lp_hot),
                                   rtol=1e-5)


class TestAnalysis:
    def test_request_stats_and_spans(self):
        r = RequestLogprobs("r1", [-0.1, -4.0, -5.0, -0.2, -3.5])
        assert r.low_confidence_spans(-3.0) == [(1, 3), (4, 5)]
        assert abs(r.perplexity() - math.exp(-r.mean())) < 1e-9
        s = r.summary()
        assert s["low_confidence_tokens"] == 3
        assert s["min_logprob"] == -5.0

    def test_from_recording_and_aggregate(self, tmp_path):
        path = tmp_path / "rec.jsonl"
        events = [
            {"ts": 1, "event": "request", "request_id": "a",
             "data": {"kind": "chat", "body": {}}},
            {"ts": 2, "event": "output", "request_id": "a",
             "data": {"t": [5], "lp": [-0.5]}},
            {"ts": 3, "event": "output", "request_id": "a",
             "data": {"t": [6], "lp": [-1.5], "f": "stop"}},
            {"ts": 4, "event": "output", "request_id": "b",
             "data": {"t": [7], "lp": [-4.0]}},
            {"ts": 5, "event": "output", "request_id": "c",
             "data": {"t": [7]}},  # no logprobs requested
        ]
        path.write_text("\n".join(json.dumps(e) for e in events))
        requests = from_recording(str(path))
        assert [r.request_id for r in requests] == ["a", "b"]
        agg = aggregate(requests)
        assert agg["requests"] == 2 and agg["tokens"] == 3
        assert agg["low_confidence_fraction"] == round(1 / 3, 4)

    def test_from_response_shapes(self):
        chat = {"id": "x", "choices": [{"logprobs": {"content": [
            {"token": "a", "logprob": -0.3},
            {"token": "b", "logprob": -0.7},
        ]}}]}
        r = from_response(chat)
        assert r.logprobs == [-0.3, -0.7]
        comp = {"id": "y", "choices": [{"logprobs": {
            "tokens": ["a"], "token_logprobs": [-0.9],
            "top_logprobs": [None]}}]}
        assert from_response(comp).logprobs == [-0.9]
        assert from_response({"id": "z", "choices": [{}]}) is None


def _cfg(cluster):
    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = cluster
    cfg.request_plane = "tcp"
    cfg.tcp_host = "127.0.0.1"
    cfg.event_plane = "mem"
    cfg.system_enabled = False
    cfg.lease_ttl_secs = 1.0
    return cfg


class TestLogprobsE2E:
    def test_chat_logprobs_through_real_engine(self, run):
        """Real TpuWorker: logprobs + top_logprobs come back in the chat
        response, self-consistent (sampled token appears in alternatives
        for greedy sampling, logprob <= 0)."""
        import aiohttp

        from dynamo_tpu.engine import RunnerConfig, TpuWorker

        async def body():
            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()
            worker = TpuWorker(
                rt, model_name="tiny-test",
                runner_config=RunnerConfig(
                    page_size=4, num_pages=64, max_batch=4,
                    max_pages_per_seq=16, prefill_buckets=(8, 16, 32)),
                warmup=False,
            )
            await worker.start()
            frt = await DistributedRuntime(_cfg(cluster)).start()
            frontend = Frontend(frt, host="127.0.0.1", port=0)
            await frontend.start()
            for _ in range(100):
                if frontend.manager.get("tiny-test") is not None:
                    break
                await asyncio.sleep(0.05)
            payload = {
                "model": "tiny-test",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4,
                "temperature": 0,
                "logprobs": True,
                "top_logprobs": 3,
            }
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        f"http://127.0.0.1:{frontend.port}"
                        "/v1/chat/completions", json=payload) as resp:
                    assert resp.status == 200
                    data = await resp.json()
            block = data["choices"][0]["logprobs"]
            entries = block["content"]
            assert len(entries) == 4
            for e in entries:
                assert e["logprob"] <= 0.0
                assert len(e["top_logprobs"]) == 3
                # greedy: the sampled token must be the top alternative
                assert abs(e["top_logprobs"][0]["logprob"]
                           - e["logprob"]) < 1e-4
            # no logprobs -> no block
            payload2 = {**payload}
            del payload2["logprobs"], payload2["top_logprobs"]
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        f"http://127.0.0.1:{frontend.port}"
                        "/v1/chat/completions", json=payload2) as resp:
                    data2 = await resp.json()
            assert "logprobs" not in data2["choices"][0]
            await frontend.close()
            await frt.shutdown()
            await worker.close()
            await rt.shutdown()

        run(body(), timeout=180)

    def test_completions_int_logprobs_param(self, run):
        """completions-style `logprobs: 3` (int) requests alternatives."""
        import aiohttp

        from dynamo_tpu.engine import RunnerConfig, TpuWorker

        async def body():
            cluster = uuid.uuid4().hex
            rt = await DistributedRuntime(_cfg(cluster)).start()
            worker = TpuWorker(
                rt, model_name="tiny-test",
                runner_config=RunnerConfig(
                    page_size=4, num_pages=64, max_batch=4,
                    max_pages_per_seq=16, prefill_buckets=(8, 16, 32)),
                warmup=False,
            )
            await worker.start()
            frt = await DistributedRuntime(_cfg(cluster)).start()
            frontend = Frontend(frt, host="127.0.0.1", port=0)
            await frontend.start()
            for _ in range(100):
                if frontend.manager.get("tiny-test") is not None:
                    break
                await asyncio.sleep(0.05)
            payload = {"model": "tiny-test", "prompt": "hello",
                       "max_tokens": 3, "temperature": 0, "logprobs": 2}
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        f"http://127.0.0.1:{frontend.port}/v1/completions",
                        json=payload) as resp:
                    assert resp.status == 200
                    data = await resp.json()
            block = data["choices"][0]["logprobs"]
            assert len(block["tokens"]) == 3
            assert len(block["token_logprobs"]) == 3
            assert all(len(t) == 2 for t in block["top_logprobs"])
            await frontend.close()
            await frt.shutdown()
            await worker.close()
            await rt.shutdown()

        run(body(), timeout=180)


class TestSamplerTruncationGate:
    """The full-vocab sort is gated behind a runtime cond — truncation
    must still bite when requested."""

    def test_topk_one_equals_greedy(self):
        from dynamo_tpu.engine.sampler import sample_with_logprobs

        logits = jnp.asarray(np.random.default_rng(0).standard_normal(
            (4, 64)), jnp.float32)
        greedy = np.argmax(np.asarray(logits), -1)
        toks, _, _, _ = sample_with_logprobs(
            logits, jnp.full(4, 1.0), jnp.ones(4),
            jnp.full(4, 1, jnp.int32),  # top_k=1 -> must pick argmax
            jnp.arange(4, dtype=jnp.uint32), jnp.int32(3))
        np.testing.assert_array_equal(np.asarray(toks), greedy)

    def test_tiny_top_p_equals_greedy(self):
        from dynamo_tpu.engine.sampler import sample

        logits = jnp.asarray(np.random.default_rng(1).standard_normal(
            (4, 64)) * 5, jnp.float32)
        greedy = np.argmax(np.asarray(logits), -1)
        toks = sample(logits, jnp.full(4, 1.0), jnp.full(4, 1e-6),
                      jnp.zeros(4, jnp.int32),
                      jnp.arange(4, dtype=jnp.uint32), jnp.int32(0))
        np.testing.assert_array_equal(np.asarray(toks), greedy)

    def test_mixed_batch_truncated_and_plain(self):
        """One slot truncating forces the masked branch for the batch;
        plain slots must be unaffected (mask is a no-op for them)."""
        from dynamo_tpu.engine.sampler import sample

        logits = jnp.asarray(np.random.default_rng(2).standard_normal(
            (2, 64)), jnp.float32)
        toks_mixed = sample(
            logits, jnp.asarray([1.0, 1.0]), jnp.asarray([1.0, 1e-6]),
            jnp.asarray([0, 0], jnp.int32),
            jnp.asarray([7, 8], jnp.uint32), jnp.int32(5))
        toks_plain = sample(
            logits, jnp.asarray([1.0, 1.0]), jnp.asarray([1.0, 1.0]),
            jnp.asarray([0, 0], jnp.int32),
            jnp.asarray([7, 8], jnp.uint32), jnp.int32(5))
        # slot 0 (no truncation) samples identically either way
        assert int(toks_mixed[0]) == int(toks_plain[0])
        # slot 1 with top_p->0 is argmax
        assert int(toks_mixed[1]) == int(np.argmax(np.asarray(logits)[1]))
