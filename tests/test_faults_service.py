"""Fast tier-1 coverage for the fault-injection service's agent paths
that only the (expensive) chaos tier exercised before: the kill_respawn
scenario, the delay proxy's latency + heal lifecycle, and the proxy's
half-close semantics (one leg's EOF must not kill the other; one leg's
FAILURE must kill both). Loopback only — the targets are throwaway
`sleep` subprocesses and in-process echo servers, not mockers."""

import asyncio
import contextlib
import os
import signal
import sys
import time

import pytest

from dynamo_tpu.faults import FaultClient, FaultInjectionService
from dynamo_tpu.faults.service import _DelayProxy


@contextlib.asynccontextmanager
async def fault_service():
    svc = await FaultInjectionService().start()
    client = FaultClient(f"http://127.0.0.1:{svc.port}")
    try:
        yield client
    finally:
        await client.close()
        await svc.close()


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


class TestKillRespawn:
    def test_kill_respawn_scenario_relaunches_target(self, run):
        import subprocess

        argv = [sys.executable, "-c", "import time; time.sleep(60)"]
        proc = subprocess.Popen(argv)
        respawned = []
        try:
            async def body():
                async with fault_service() as faults:
                    await faults.register("sleeper", proc.pid, argv=argv)
                    out = await faults.run_scenario(
                        "kill_respawn", target="sleeper", down_ms=100)
                    assert [s["type"] for s in out["steps"]] == \
                        ["kill", "respawn"]
                    new_pid = out["steps"][1]["detail"]["pid"]
                    respawned.append(new_pid)
                    assert new_pid != proc.pid
                    assert _alive(new_pid)
                    # the original target is really gone
                    proc.wait(timeout=10)
                    assert proc.returncode == -signal.SIGKILL

            run(body(), timeout=30.0)
        finally:
            if proc.poll() is None:
                proc.kill()
            for pid in respawned:
                with contextlib.suppress(ProcessLookupError):
                    os.kill(pid, signal.SIGKILL)

    def test_respawn_without_argv_is_rejected(self, run):
        import subprocess

        proc = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"])
        try:
            async def body():
                async with fault_service() as faults:
                    await faults.register("noargv", proc.pid)  # no argv
                    with pytest.raises(RuntimeError, match="argv"):
                        await faults.run_scenario("kill_respawn",
                                                  target="noargv",
                                                  down_ms=50)

            run(body(), timeout=30.0)
        finally:
            if proc.poll() is None:
                proc.kill()


async def _echo_server():
    """Loopback echo server; returns (server, port)."""

    async def handle(reader, writer):
        while True:
            data = await reader.read(4096)
            if not data:
                break
            writer.write(data)
            await writer.drain()
        with contextlib.suppress(OSError, RuntimeError):
            if writer.can_write_eof():
                writer.write_eof()
        writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", 0)
    return server, server.sockets[0].getsockname()[1]


class TestEvictScenario:
    """The GCE spot-preemption shape (docs/fault-tolerance.md departure
    ladder): SIGTERM notice -> deadline hold -> SIGKILL only if the
    target did not drain and exit inside the notice."""

    def test_graceful_exit_inside_notice_skips_sigkill(self, run):
        import subprocess

        # A well-behaved drainer: exits promptly on SIGTERM.
        proc = subprocess.Popen([
            sys.executable, "-c",
            "import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))\n"
            "time.sleep(60)"])
        try:
            time.sleep(0.2)  # let the handler install

            async def body():
                async with fault_service() as faults:
                    await faults.register("drainer", proc.pid)
                    out = await faults.run_scenario(
                        "evict", target="drainer", deadline_ms=5000)
                    kinds = [s["type"] for s in out["steps"]]
                    assert kinds == ["sigterm", "evict"]  # no kill step
                    assert out["steps"][-1]["detail"]["graceful"] is True
                    proc.wait(timeout=10)
                    assert not _alive(proc.pid)

            run(body(), timeout=30)
        finally:
            if _alive(proc.pid):
                proc.kill()

    def test_respawn_after_replaces_evicted_capacity(self, run):
        """Spot fleets REPLACE evicted workers: with respawn_after_ms
        the scenario relaunches the target from its registered argv
        after the modeled reprovision delay — the process-level path
        the chaos-spot gate times (docs/elasticity.md)."""
        import subprocess

        argv = [sys.executable, "-c",
                "import signal, sys, time\n"
                "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))\n"
                "time.sleep(60)"]
        proc = subprocess.Popen(argv)
        respawned = []
        try:
            time.sleep(0.2)

            async def body():
                async with fault_service() as faults:
                    await faults.register("spot", proc.pid, argv=argv)
                    t0 = time.monotonic()
                    out = await faults.run_scenario(
                        "evict", target="spot", deadline_ms=5000,
                        respawn_after_ms=150)
                    kinds = [s["type"] for s in out["steps"]]
                    assert kinds == ["sigterm", "evict", "respawn"]
                    assert time.monotonic() - t0 >= 0.15
                    new_pid = out["steps"][-1]["detail"]["pid"]
                    respawned.append(new_pid)
                    assert new_pid != proc.pid and _alive(new_pid)

            run(body(), timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
            for pid in respawned:
                with contextlib.suppress(ProcessLookupError):
                    os.kill(pid, signal.SIGKILL)

    def test_sigterm_ignorer_gets_sigkill_at_deadline(self, run):
        import subprocess

        proc = subprocess.Popen([
            sys.executable, "-c",
            "import signal, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "time.sleep(60)"])
        try:
            time.sleep(0.2)

            async def body():
                async with fault_service() as faults:
                    await faults.register("stubborn", proc.pid)
                    out = await faults.run_scenario(
                        "evict", target="stubborn", deadline_ms=300)
                    kinds = [s["type"] for s in out["steps"]]
                    assert kinds == ["sigterm", "kill", "evict"]
                    assert out["steps"][-1]["detail"]["graceful"] is False
                    proc.wait(timeout=10)

            run(body(), timeout=30)
        finally:
            if _alive(proc.pid):
                proc.kill()


class TestDelayHeal:
    def test_delay_adds_latency_and_heal_closes_listener(self, run):
        async def body():
            server, port = await _echo_server()
            try:
                async with fault_service() as faults:
                    fault = await faults.inject(
                        "delay", target_host="127.0.0.1", target_port=port,
                        delay_ms=120.0)
                    listen = fault["detail"]["listen_port"]
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", listen)
                    t0 = time.monotonic()
                    writer.write(b"ping")
                    await writer.drain()
                    assert await reader.readexactly(4) == b"ping"
                    rtt = time.monotonic() - t0
                    # request + response each pay >=120ms through the proxy
                    assert rtt >= 0.2, rtt
                    writer.close()

                    healed = await faults.heal(fault["id"])
                    assert healed["state"] == "healed"
                    with pytest.raises(OSError):
                        await asyncio.open_connection("127.0.0.1", listen)
            finally:
                server.close()
                await server.wait_closed()

        run(body(), timeout=30.0)


class TestDelayProxyHalfClose:
    def test_eof_half_closes_forward_leg_only(self, run):
        """A client that shuts down its WRITE side must still receive the
        response (the old teardown hard-closed the opposite direction)."""

        async def body():
            async def handle(reader, writer):
                # read until EOF, then answer — only possible if the
                # proxy half-closed the upstream leg instead of killing
                # the connection
                data = await reader.read()
                writer.write(b"got:" + data)
                await writer.drain()
                writer.close()

            server = await asyncio.start_server(handle, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            proxy = _DelayProxy(0, "127.0.0.1", port, delay_ms=5.0)
            await proxy.start()
            listen = proxy._server.sockets[0].getsockname()[1]
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", listen)
                writer.write(b"hello")
                await writer.drain()
                writer.write_eof()  # half-close client->proxy
                out = await asyncio.wait_for(reader.read(), 5.0)
                assert out == b"got:hello"
            finally:
                await proxy.stop()
                server.close()
                await server.wait_closed()

        run(body(), timeout=30.0)

    def test_one_leg_failure_tears_down_both(self, run):
        """When the upstream dies mid-conversation the client leg must see
        EOF/reset promptly — no half-dead lingering connection."""

        async def body():
            upstream_writer = {}

            async def handle(reader, writer):
                upstream_writer["w"] = writer
                await reader.read(4096)

            server = await asyncio.start_server(handle, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            proxy = _DelayProxy(0, "127.0.0.1", port, delay_ms=1.0)
            await proxy.start()
            listen = proxy._server.sockets[0].getsockname()[1]
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", listen)
                writer.write(b"hi")
                await writer.drain()
                while "w" not in upstream_writer:
                    await asyncio.sleep(0.01)
                # upstream aborts hard
                upstream_writer["w"].transport.abort()
                # the client leg must terminate too (EOF or reset), fast
                with contextlib.suppress(ConnectionError):
                    out = await asyncio.wait_for(reader.read(), 5.0)
                    assert out == b""
                writer.close()
            finally:
                await proxy.stop()
                server.close()
                await server.wait_closed()

        run(body(), timeout=30.0)
