"""Capability probes for environment-dependent tier-1 tests.

Some tier-1 tests exercise code written against a newer JAX surface
than every environment carries — `jax.shard_map` (the top-level export)
and `jax.experimental.pallas.tpu.CompilerParams` (renamed from
`TPUCompilerParams`), plus tests that spawn whole worker processes or
need wall-clock headroom a loaded single-vCPU runner cannot give. On
such environments those tests fail for reasons that have nothing to do
with the code under test, and a red tier-1 run stops meaning anything.

These probes pin each dependence explicitly: the test skips — visibly,
with the capability named in the reason — instead of failing, and on
an environment that HAS the capability the test still runs and still
gates. Probe the capability, never the version string: a backport or a
rename makes version comparisons lie.
"""

import os

import pytest


def _has_shard_map() -> bool:
    import jax

    return hasattr(jax, "shard_map")


def _has_pallas_compiler_params() -> bool:
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # noqa: BLE001 — no pallas at all is also "no"
        return False
    return hasattr(pltpu, "CompilerParams")


requires_shard_map = pytest.mark.skipif(
    not _has_shard_map(),
    reason="this jax build has no top-level jax.shard_map export")

requires_pallas_compiler_params = pytest.mark.skipif(
    not _has_pallas_compiler_params(),
    reason="this jax build has no pallas.tpu.CompilerParams "
           "(pre-rename TPUCompilerParams)")

def _usable_cpus() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux: no affinity API
        return os.cpu_count() or 1


# Multi-process gang tests (deploy gangs, multihost meshes, cross-host
# KVBM) fork 2-3 worker processes that each compile XLA programs and
# then rendezvous over gloo collectives with a fixed connect timeout.
# On a single-core host the ranks compile SERIALLY, the rendezvous
# window expires, and the run dies with "Gloo context initialization
# failed: Connect timeout" or the parent test's own deadline — neither
# of which says anything about the code under test.
requires_multicore = pytest.mark.skipif(
    _usable_cpus() < 2,
    reason="multi-process gang tests need >=2 usable CPUs: concurrent "
           "rank compilation outlives gloo connect timeouts on a "
           "single-core host")
