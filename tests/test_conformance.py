"""Runtime protocol-conformance tests: the ProtocolMonitor replays
live lifecycle events against the SAME specs dynastate lints
(tools/dynastate/protocols/), so these pin both halves — the monitor's
accept/violate semantics on real machines, and the two PR-18 guard
fixes (StreamingTransfer, ColdStartLadder) staying terminal-safe under
an enabled monitor. Reverting either guard makes the hook fire on a
settled lifecycle and the zero-violation assertions here fail."""

import pytest

from dynamo_tpu.engine import coldstart
from dynamo_tpu.engine.coldstart import ColdStartLadder
from dynamo_tpu.llm.kv_transfer import (
    KvLayoutDescriptor,
    PendingTransferTable,
    StreamingTransfer,
)
from dynamo_tpu.runtime import conformance
from dynamo_tpu.runtime.conformance import (
    MAX_DETAILS,
    RULE_POST_TERMINAL,
    RULE_UNHANDLED,
    chaos_assertion,
    get_monitor,
    observe,
    reset_monitor,
)
from dynamo_tpu.runtime.flight_recorder import FlightRecorder
from dynamo_tpu.runtime.resilience import CircuitBreaker


@pytest.fixture
def monitor_on(monkeypatch):
    monkeypatch.setenv("DYNT_CONFORMANCE", "1")
    reset_monitor()
    yield get_monitor()
    reset_monitor()


def _layout():
    return KvLayoutDescriptor(n_layers=2, kv_heads=2, head_dim=4,
                              page_size=16, dtype="float32")


def _transfer(transfer_id="t1"):
    released = []
    table = PendingTransferTable()
    t = StreamingTransfer(transfer_id, [], lambda: released.append(1),
                          _layout(), 128, table=table)
    table.add(t)
    return t, released


class TestMonitorCore:
    def test_loads_all_spec_machines(self, monitor_on):
        snap = monitor_on.snapshot()
        assert set(snap["protocols_loaded"]) >= {
            "kv_stream_transfer", "drain_ladder", "migration_replay",
            "preemption", "coldstart", "striped_weight_pull", "journal",
            "flight_recorder", "breaker"}
        assert snap["enabled"] is True

    def test_valid_sequence_is_clean(self, monitor_on):
        observe("kv_stream_transfer", "t-ok", "append")
        observe("kv_stream_transfer", "t-ok", "append")
        observe("kv_stream_transfer", "t-ok", "finish")
        snap = monitor_on.snapshot()
        assert snap["total_violations"] == 0
        assert snap["instances_tracked"] == 1

    def test_unhandled_event_is_ds101(self, monitor_on):
        observe("kv_stream_transfer", "t-bad", "bogus_event")
        snap = monitor_on.snapshot()
        assert snap["total_violations"] == 1
        assert snap["by_protocol"] == {
            "kv_stream_transfer": {RULE_UNHANDLED: 1}}
        (v,) = snap["violations"]
        assert v == {"protocol": "kv_stream_transfer",
                     "instance": "t-bad", "state": "streaming",
                     "event": "bogus_event", "rule": RULE_UNHANDLED}

    def test_event_after_terminal_is_ds201(self, monitor_on):
        observe("kv_stream_transfer", "t-late", "finish")
        observe("kv_stream_transfer", "t-late", "append")
        snap = monitor_on.snapshot()
        assert snap["by_protocol"] == {
            "kv_stream_transfer": {RULE_POST_TERMINAL: 1}}
        assert snap["violations"][0]["state"] == "finished"

    def test_instances_are_independent(self, monitor_on):
        observe("kv_stream_transfer", "a", "finish")
        observe("kv_stream_transfer", "b", "append")
        assert monitor_on.snapshot()["total_violations"] == 0

    def test_unknown_protocol_ignored(self, monitor_on):
        observe("no_such_protocol", "x", "whatever")
        assert monitor_on.snapshot()["total_violations"] == 0

    def test_disabled_monitor_is_inert(self, monkeypatch):
        monkeypatch.delenv("DYNT_CONFORMANCE", raising=False)
        reset_monitor()
        try:
            observe("kv_stream_transfer", "t", "bogus_event")
            snap = get_monitor().snapshot()
            assert snap["enabled"] is False
            assert snap["total_violations"] == 0
            assert snap["instances_tracked"] == 0
        finally:
            reset_monitor()

    def test_details_capped_but_totals_exact(self, monitor_on):
        for i in range(MAX_DETAILS + 50):
            observe("kv_stream_transfer", f"cap-{i}", "bogus_event")
        snap = monitor_on.snapshot()
        assert snap["total_violations"] == MAX_DETAILS + 50
        assert len(snap["violations"]) == MAX_DETAILS

    def test_chaos_assertion_row(self, monitor_on):
        ok = chaos_assertion(monitor_on.snapshot())
        assert ok == {"name": "protocol_conformance", "ok": True,
                      "detail": {"total_violations": 0,
                                 "by_protocol": {}, "violations": []}}
        for i in range(7):
            observe("kv_stream_transfer", f"x-{i}", "bogus_event")
        bad = chaos_assertion(monitor_on.snapshot())
        assert bad["ok"] is False
        assert bad["detail"]["total_violations"] == 7
        # report rows stay bounded even on a violation storm
        assert len(bad["detail"]["violations"]) == 5


class TestBreakerLifecycle:
    def test_full_trip_cycle_conforms(self, monitor_on):
        b = CircuitBreaker(failure_threshold=1, reset_secs=0.0)
        b.record_failure()                    # closed -> open
        assert b.try_acquire()                # open -> half_open (probe)
        b.record_failure(probe=True)          # half_open -> open
        assert b.try_acquire()                # open -> half_open again
        b.record_success(probe=True)          # half_open -> closed
        b.record_failure()                    # closed -> open
        b.reset()                             # open -> closed
        assert monitor_on.snapshot()["total_violations"] == 0


class TestFlightRecorderLifecycle:
    def test_full_ladder_conforms(self, monitor_on):
        rec = FlightRecorder(capacity=8)
        rec.start("r1", model="m")
        for phase in ("queued", "scheduled", "prefill_start",
                      "first_token"):
            rec.stamp("r1", phase)
        rec.finish("r1")
        assert monitor_on.snapshot()["total_violations"] == 0

    def test_forward_skip_is_legal(self, monitor_on):
        """A shed request never queues; a prefill-only leg jumps straight
        to finished — the spec allows any forward-skipping subset."""
        rec = FlightRecorder(capacity=8)
        rec.start("r2")
        rec.stamp("r2", "first_token")
        rec.finish("r2", status="ok")
        rec.start("r3")
        rec.finish("r3", status="shed")
        assert monitor_on.snapshot()["total_violations"] == 0

    def test_backwards_stamp_is_flagged(self, monitor_on):
        """first-write-wins accepts a never-seen phase even out of order;
        the monitor is what catches the ladder running backwards."""
        rec = FlightRecorder(capacity=8)
        rec.start("r4")
        rec.stamp("r4", "first_token")
        rec.stamp("r4", "queued")
        snap = monitor_on.snapshot()
        assert snap["by_protocol"] == {
            "flight_recorder": {RULE_UNHANDLED: 1}}
        assert snap["violations"][0]["state"] == "first_token"
        assert snap["violations"][0]["event"] == "queued"


class TestStreamingTransferGuards:
    """Gap A (PR-18): finish/append_pages after a terminal event must
    drop instead of mutating the settled transfer. On the pre-fix code
    these calls mutate AND the hooks observe forbidden transitions."""

    def test_finish_after_fail_drops(self, monitor_on):
        t, released = _transfer()
        t.fail()
        assert t.failed and released == [1]
        t.finish(5, [1, 2])
        assert t.done is False
        assert t.first_token is None
        assert t.page_ids == []
        # fail claimed the entry; nothing releases twice
        assert released == [1]
        assert monitor_on.snapshot()["total_violations"] == 0

    def test_append_after_finish_drops(self, monitor_on):
        t, _ = _transfer("t2")
        t.append_pages([1])
        t.finish(7, [1, 2])
        t.append_pages([9])
        assert t.page_ids == [1, 2]
        assert t.first_token == 7
        assert monitor_on.snapshot()["total_violations"] == 0

    def test_fail_after_finish_keeps_transfer_pullable(self, monitor_on):
        t, released = _transfer("t3")
        t.finish(7, [1, 2])
        t.fail()
        assert t.done is True and t.failed is False
        assert released == []
        assert monitor_on.snapshot()["total_violations"] == 0


class TestColdStartLadderGuard:
    """Gap B (PR-18): a late mark after first_token closed the ladder
    (lazy per-shape recompile) must not mutate the published record."""

    def test_late_mark_after_close_drops(self, monitor_on):
        ladder = ColdStartLadder("w0", source="peer")
        ladder.mark("fetch", 0.5)
        total = ladder.first_token()
        assert total is not None and ladder.total == total
        ladder.mark("compile", 1.0)
        assert "compile" not in ladder.phases
        assert ladder.total == total
        assert monitor_on.snapshot()["total_violations"] == 0
        coldstart.reset_observations()

    def test_first_token_idempotent(self, monitor_on):
        ladder = ColdStartLadder("w1")
        first = ladder.first_token()
        assert ladder.first_token() == first
        assert monitor_on.snapshot()["total_violations"] == 0
        coldstart.reset_observations()
