"""Durable journal event plane (the JetStream-mode analog — ref:
lib/llm/src/kv_router/jetstream.rs, router-design.md "JetStream Mode"):
per-publisher append-only logs on shared storage, full-history replay for
restarted subscribers, snapshot-seeded rotation, torn-tail tolerance.

E2E tier: two KV-routed frontends under live traffic; one restarts and
converges to the same radix state as the survivor FROM THE JOURNAL ALONE
(worker resync disabled), then keeps serving."""

import asyncio
import os
import struct
import uuid

import pytest

from dynamo_tpu.runtime.events import (
    JournalEventPublisher,
    JournalEventSubscriberManager,
    _journal_pack,
)


async def _drain(sub, n, timeout=5.0):
    out = []
    deadline = asyncio.get_event_loop().time() + timeout
    while len(out) < n:
        remaining = deadline - asyncio.get_event_loop().time()
        if remaining <= 0:
            break
        try:
            out.append(await asyncio.wait_for(sub.__anext__(), remaining))
        except (asyncio.TimeoutError, StopAsyncIteration):
            break
    return out


class TestJournalTransport:
    def test_publish_subscribe_roundtrip(self, run, tmp_path):
        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns")
            await pub.publish("kv_events", {"a": 1})
            await pub.publish("load_metrics", {"b": 2})
            await pub.publish("kv_events", {"a": 3})
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns",
                                                "kv_events",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 2)
            assert events == [("kv_events", {"a": 1}),
                              ("kv_events", {"a": 3})]
            # live tail after replay
            await pub.publish("kv_events", {"a": 4})
            assert await _drain(sub, 1) == [("kv_events", {"a": 4})]
            await mgr.close()
            await pub.close()
        run(body())

    def test_restarted_subscriber_replays_full_history(self, run,
                                                       tmp_path):
        """The durable property: a brand-new subscriber (a restarted
        router) sees everything ever published."""
        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns")
            for i in range(20):
                await pub.publish("kv_events", {"i": i})
            # first subscriber consumes...
            m1 = JournalEventSubscriberManager(str(tmp_path), "ns", "",
                                               poll_interval=0.02)
            s1 = await m1.start()
            assert len(await _drain(s1, 20)) == 20
            await m1.close()
            # ...then a FRESH subscriber still gets the full history
            m2 = JournalEventSubscriberManager(str(tmp_path), "ns", "",
                                               poll_interval=0.02)
            s2 = await m2.start()
            events = await _drain(s2, 20)
            assert [p["i"] for _t, p in events] == list(range(20))
            await m2.close()
            await pub.close()
        run(body())

    def test_multiple_publishers(self, run, tmp_path):
        async def body():
            p1 = JournalEventPublisher(str(tmp_path), "ns")
            p2 = JournalEventPublisher(str(tmp_path), "ns")
            await p1.publish("t", {"from": 1})
            await p2.publish("t", {"from": 2})
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns", "",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 2)
            assert {p["from"] for _t, p in events} == {1, 2}
            await mgr.close()
            await p1.close()
            await p2.close()
        run(body())

    def test_rotation_seeds_snapshot_and_old_gen_removed(self, run,
                                                         tmp_path):
        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns", max_bytes=400,
                                        grace_seconds=0.0)
            pub.set_snapshot_fn(
                lambda: [("kv_snapshot", {"state": "current"})])
            for i in range(40):  # well past max_bytes -> several rotations
                await pub.publish("kv_events", {"i": i, "pad": "x" * 40})
            assert pub._generation > 0
            files = sorted(os.listdir(tmp_path / "ns"))
            # grace_seconds=0: retired generations unlink at the next
            # rotation, so at most the current + newest-retired remain.
            assert len(files) <= 2
            assert f"{pub.publisher_id}.g{pub._generation}.log" in files
            # fresh subscriber: snapshot frame first, then the tail
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns", "",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 2)
            assert events[0][0] == "kv_snapshot"
            assert events[0][1] == {"state": "current"}
            await mgr.close()
            await pub.close()
        run(body())

    def test_rotation_tail_frames_not_lost(self, run, tmp_path):
        """A subscriber whose last poll position is mid-way through a
        generation that then rotates must still see that generation's
        tail frames (non-snapshot topics like load metrics are not
        reproduced by the rotation snapshot)."""
        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns", max_bytes=500)
            pub.set_snapshot_fn(lambda: [("kv_snapshot", {"s": 1})])
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns", "",
                                                poll_interval=0.02)
            sub = await mgr.start()
            # Publish a first frame and let the subscriber catch up so it
            # holds a position inside generation 0.
            await pub.publish("load_metrics", {"i": 0})
            assert len(await _drain(sub, 1)) == 1
            # Stall polling while the publisher writes tail frames into
            # gen 0 and then rotates past max_bytes.
            mgr._poll = 0.5
            i = 1
            while pub._generation == 0:
                await pub.publish("load_metrics", {"i": i, "pad": "z" * 60})
                i += 1
            mgr._poll = 0.02
            events = await _drain(sub, i, timeout=5.0)
            got = [p["i"] for t, p in events if t == "load_metrics"]
            # Every load_metrics frame from gen 0's tail was delivered.
            assert got == list(range(1, i))
            await mgr.close()
            await pub.close()
        run(body())

    def test_concurrent_publishes_never_tear_frames(self, run, tmp_path):
        """publish() from many tasks concurrently (threadpool-dispatched
        appends) must keep every frame intact across rotations. Checked
        directly against the on-disk files: every surviving generation
        parses cleanly to its last byte and — because the default grace
        window keeps all generations of this short burst on disk — every
        published frame is present exactly once."""
        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns", max_bytes=800)
            pub.set_snapshot_fn(lambda: [])
            await asyncio.gather(*[
                pub.publish("t", {"i": i, "pad": "w" * 50})
                for i in range(60)])
            assert pub._generation > 0  # the burst really rotated
            # Scan before close(): close unlinks retired generations.
            from dynamo_tpu.runtime.events import _journal_read
            got = []
            for name in os.listdir(tmp_path / "ns"):
                buf = (tmp_path / "ns" / name).read_bytes()
                end = 0
                for pos, _t, payload in _journal_read(buf, 0):
                    end = pos
                    got.append(payload["i"])
                assert end == len(buf), f"torn frame in {name}"
            assert sorted(got) == list(range(60))
            await pub.close()
            # close() leaves only the final generation on disk.
            assert len(os.listdir(tmp_path / "ns")) == 1
        run(body())

    def test_live_subscriber_follows_rotation(self, run, tmp_path):
        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns", max_bytes=300)
            pub.set_snapshot_fn(lambda: [("kv_snapshot", {"gen": "snap"})])
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns", "",
                                                poll_interval=0.02)
            sub = await mgr.start()
            seen = []
            for i in range(30):
                await pub.publish("kv_events", {"i": i, "pad": "y" * 30})
                seen.extend(await _drain(sub, 1, timeout=1.0))
            # Every event is delivered exactly once OR superseded by a
            # snapshot frame from a rotation that happened before the
            # subscriber reached it.
            payload_is = [p["i"] for t, p in seen if t == "kv_events"]
            assert payload_is == sorted(set(payload_is))  # no duplicates
            assert any(t == "kv_snapshot" for t, _p in seen) or \
                payload_is == list(range(30))
            await mgr.close()
            await pub.close()
        run(body())

    def test_torn_tail_frame_tolerated(self, run, tmp_path):
        """A crash mid-append leaves a partial frame; the subscriber stops
        at the last complete frame and picks up the rest when a recovered
        publisher completes it."""
        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns")
            await pub.publish("t", {"ok": 1})
            path = pub._path()
            full_frame = _journal_pack("t", {"ok": 2})
            with open(path, "ab") as f:
                f.write(full_frame[: len(full_frame) // 2])  # torn write
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns", "",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 1)
            assert events == [("t", {"ok": 1})]
            assert await _drain(sub, 1, timeout=0.3) == []  # torn frame held
            with open(path, "ab") as f:  # recovery completes the frame
                f.write(full_frame[len(full_frame) // 2:])
            assert await _drain(sub, 1) == [("t", {"ok": 2})]
            await mgr.close()
            await pub.close()
        run(body())


class TestJournalIntegrity:
    """Per-frame CRC32 + skip-to-next-valid-frame resync: corrupt frames
    (the faults service's corrupt_file shapes — flipped bytes, garbage
    appends, zero-fill holes) must not wedge replay; each skip is
    counted (dynamo_journal_bad_frames_total) and followed by ONE
    synthetic journal-resync event so derived state re-dumps instead of
    silently diverging."""

    def test_read_frames_unit_tier(self):
        """Pure-function tier over _journal_read: valid/corrupt/valid,
        torn tail held, garbage tail consumed to EOF exactly once."""
        from dynamo_tpu.runtime.events import _journal_read

        f1 = _journal_pack("t", {"i": 1})
        f2 = _journal_pack("t", {"i": 2})
        f3 = _journal_pack("t", {"i": 3})

        def read(buf):
            bad = [0]
            out = list(_journal_read(buf, 0, lambda k: bad.__setitem__(
                0, bad[0] + k)))
            return out, bad[0]

        # clean
        out, bad = read(f1 + f2)
        assert [(t, p) for _o, t, p in out] == [("t", {"i": 1}),
                                                ("t", {"i": 2})]
        assert bad == 0
        # corrupt middle frame: flip a body byte of f2
        broken = bytearray(f1 + f2 + f3)
        broken[len(f1) + 12] ^= 0xFF
        out, bad = read(bytes(broken))
        assert [p["i"] for _o, _t, p in out if p] == [1, 3]
        assert bad == 1
        # torn tail: held for the next poll, not counted
        out, bad = read(f1 + f2[: len(f2) // 2])
        assert [p["i"] for _o, _t, p in out if p] == [1]
        assert bad == 0
        # garbage tail: consumed via the sentinel, counted once. The
        # consumed span stops IN FRONT of the first byte run that could
        # still be a frame prefix (the last <8 header bytes always
        # qualify) — never all the way to EOF past a potential frame.
        garbage = b"\x07garbage-no-frame-here\xff\xfe"
        out, bad = read(f1 + garbage)
        assert out[-1][1] is None  # sentinel
        assert len(f1) < out[-1][0] <= len(f1 + garbage)
        assert bad == 1
        # corrupt frame followed by a TORN VALID frame: the consumed
        # garbage must stop before the torn frame's start — eating its
        # prefix would make the remainder parse as garbage on the next
        # poll and cascade the loss across every later frame.
        broken2 = bytearray(f1 + f2 + f3[: len(f3) - 5])
        broken2[len(f1) + 12] ^= 0xFF  # corrupt f2's body
        out, bad = read(bytes(broken2))
        assert [p["i"] for _o, _t, p in out if p] == [1]
        assert bad == 1
        consumed = out[-1][0]
        assert consumed <= len(f1 + f2)  # f3's prefix survives
        # next poll from `consumed` with the append finished: f3 parses
        full = bytes(broken2) + f3[len(f3) - 5:]
        out2, bad2 = read(full[consumed:])
        assert [p["i"] for _o, _t, p in out2 if p] == [3]

    def test_flipped_byte_skips_frame_and_signals_resync(self, run,
                                                         tmp_path):
        from dynamo_tpu.runtime.events import JOURNAL_RESYNC_TOPIC

        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns")
            for i in range(5):
                await pub.publish("kv_events", {"i": i})
            path = pub._path()
            buf = bytearray(open(path, "rb").read())
            # Flip one byte inside the SECOND frame's body (frames
            # start after the 8-byte format-magic preamble).
            from dynamo_tpu.runtime.events import _JOURNAL_MAGIC

            first = len(_JOURNAL_MAGIC)
            (length0,) = struct.unpack_from(">I", buf, first)
            second = first + 8 + length0
            buf[second + 12] ^= 0xFF
            with open(path, "wb") as f:
                f.write(buf)
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns",
                                                "kv_events",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 5)
            kv = [p["i"] for t, p in events if t == "kv_events"]
            resync = [p for t, p in events
                      if t == JOURNAL_RESYNC_TOPIC]
            assert kv == [0, 2, 3, 4]  # frame 1 skipped, replay not wedged
            assert len(resync) == 1 and resync[0]["skipped"] == 1
            assert mgr.bad_frames == 1
            # Live tail still flows after the skip.
            await pub.publish("kv_events", {"i": 9})
            more = await _drain(sub, 1)
            assert [p["i"] for _t, p in more] == [9]
            # The skip was counted once, not once per poll.
            assert mgr.bad_frames == 1
            await mgr.close()
            await pub.close()

        run(body())

    def test_garbage_tail_then_fresh_appends_resume(self, run, tmp_path):
        """The generation-boundary fallback: when nothing valid remains
        after the corruption, the reader consumes to EOF so the
        publisher's NEXT appends land on a clean boundary and flow."""
        from dynamo_tpu.runtime.events import JOURNAL_RESYNC_TOPIC

        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns")
            await pub.publish("kv_events", {"i": 0})
            await pub.publish("kv_events", {"i": 1})
            with open(pub._path(), "ab") as f:
                f.write(b'{"torn-frame\x00\xff' + b"\xa5" * 48)
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns", "",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 3)
            assert [p["i"] for t, p in events
                    if t == "kv_events"] == [0, 1]
            assert any(t == JOURNAL_RESYNC_TOPIC for t, _p in events)
            for i in (2, 3):
                await pub.publish("kv_events", {"i": i})
            more = await _drain(sub, 2)
            assert [p["i"] for _t, p in more] == [2, 3]
            await mgr.close()
            await pub.close()

        run(body())

    def test_zero_fill_hole_skipped(self, run, tmp_path):
        """A zero-filled sparse hole (truncate-then-append crash shape)
        parses as (length=0, crc=0) with a CRC-passing empty body — it
        must still count as corruption, not as frames."""

        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns")
            await pub.publish("kv_events", {"i": 0})
            with open(pub._path(), "ab") as f:
                f.write(b"\x00" * 64)
            await pub.publish("kv_events", {"i": 1})
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns",
                                                "kv_events",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 2)
            assert [p["i"] for t, p in events
                    if t == "kv_events"] == [0, 1]
            assert mgr.bad_frames >= 1
            await mgr.close()
            await pub.close()

        run(body())

    def test_corrupt_length_field_does_not_wedge(self, run, tmp_path):
        """A flipped length byte turns a frame into an ever-growing
        'partial'; with valid frames beyond it the reader must skip to
        them instead of waiting for a tail that never completes."""

        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns")
            await pub.publish("kv_events", {"i": 0})
            # Header claims 4000 bytes; only junk follows.
            with open(pub._path(), "ab") as f:
                f.write(struct.pack(">II", 4000, 0xDEAD) + b"\x42" * 37)
            await pub.publish("kv_events", {"i": 1})
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns",
                                                "kv_events",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 2)
            assert [p["i"] for t, p in events
                    if t == "kv_events"] == [0, 1]
            assert mgr.bad_frames >= 1
            await mgr.close()
            await pub.close()

        run(body())

    def test_transient_format_cache_loss_keeps_crc(self, run, tmp_path):
        """A transient read error (ESTALE over NFS) pops the cached
        format verdict while the reader's position stays mid-file. The
        next successful poll must re-derive "crc" from the offset-0
        preamble — inferring "legacy" from the nonzero offset would
        permanently misparse every later frame as corruption."""

        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns")
            for i in range(3):
                await pub.publish("kv_events", {"i": i})
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns",
                                                "kv_events",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 3)
            assert [p["i"] for t, p in events
                    if t == "kv_events"] == [0, 1, 2]
            # Simulate the OSError cleanup path: verdict dropped,
            # position (gen, offset>0) untouched.
            mgr._formats.clear()
            await pub.publish("kv_events", {"i": 3})
            more = await _drain(sub, 1)
            assert [p["i"] for _t, p in more] == [3]
            assert mgr.bad_frames == 0  # no false legacy-parse alarm
            await mgr.close()
            await pub.close()

        run(body())

    def test_bad_frame_accounting_commits_with_position(self, run,
                                                        tmp_path):
        """Corruption accounting is deferred to the scan's position
        commit: a poll whose newest-generation read transiently fails
        re-reads the same corrupt frames next tick, and counting inside
        _read_frames would double-bump dynamo_journal_bad_frames_total
        for one on-disk corruption."""

        async def body():
            pub = JournalEventPublisher(str(tmp_path), "ns")
            for i in range(3):
                await pub.publish("kv_events", {"i": i})
            path = pub._path()
            buf = bytearray(open(path, "rb").read())
            from dynamo_tpu.runtime.events import _JOURNAL_MAGIC

            first = len(_JOURNAL_MAGIC)
            (length0,) = struct.unpack_from(">I", buf, first)
            buf[first + 8 + length0 + 12] ^= 0xFF  # second frame's body
            with open(path, "wb") as f:
                f.write(buf)
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns",
                                                "kv_events")
            name = os.path.basename(path)[: -len(".log")]
            pid, gen_s = name.rsplit(".g", 1)
            out1: list = []
            bad1: list = []
            mgr._read_frames(pid, int(gen_s), 0, out1, bad1)
            out2: list = []
            bad2: list = []
            mgr._read_frames(pid, int(gen_s), 0, out2, bad2)
            assert bad1 and bad2  # both reads saw the corrupt frame
            assert mgr.bad_frames == 0  # neither committed anything
            mgr._commit_bad_frames(bad2)
            assert mgr.bad_frames == 1  # counted once, at commit
            await pub.close()

        run(body())

    def test_resync_event_triggers_indexer_redump(self, run):
        """The standalone indexer reacts to a journal-resync event by
        re-dumping EVERY known worker — lost frames carry no per-worker
        gap to flag them."""
        from dynamo_tpu.indexer import StandaloneIndexer
        from dynamo_tpu.runtime.events import JOURNAL_RESYNC_TOPIC

        async def body():
            idx = StandaloneIndexer(runtime=None)
            idx._worker_subjects = {7: ("ns", "c"), 9: ("ns", "c")}
            called = []
            idx._schedule_resync = called.append

            async def sub():
                yield (JOURNAL_RESYNC_TOPIC,
                       {"publisher": "p", "generation": 0, "skipped": 2})

            await idx._event_loop(sub())
            assert sorted(called) == [7, 9]

        run(body())


# ---------------------------------------------------------------------------
# E2E: two router replicas over the journal; one restarts under traffic
# ---------------------------------------------------------------------------


def _cfg(cluster, journal_root):
    from dynamo_tpu.runtime import RuntimeConfig

    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = cluster
    cfg.request_plane = "tcp"
    cfg.tcp_host = "127.0.0.1"
    cfg.event_plane = "journal"
    cfg.event_journal_path = journal_root
    cfg.system_enabled = False
    cfg.lease_ttl_secs = 2.0
    return cfg


async def _chat(port, content, n=1):
    import aiohttp

    async with aiohttp.ClientSession() as session:
        for _ in range(n):
            async with session.post(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                json={"model": "mock-model",
                      "messages": [{"role": "user", "content": content}],
                      "max_tokens": 4},
            ) as resp:
                assert resp.status == 200
                await resp.json()


def _tree_state(frontend):
    entry = frontend.manager.get("mock-model")
    counts = entry.scheduler.indexer.worker_block_counts()
    return {w.worker_id: n for w, n in counts.items()}


class TestRouterReplicaRestart:
    def test_restarted_replica_converges_from_journal(self, run, tmp_path):
        """Two KV-routed frontends, live traffic through BOTH, kill one,
        restart it: it must converge to the survivor's radix state from
        the durable journal ALONE (worker resync endpoints disabled) and
        keep serving KV-routed traffic (VERDICT r3 ask #7)."""
        from dynamo_tpu.frontend import Frontend
        from dynamo_tpu.mocker import MockerConfig, MockerWorker
        from dynamo_tpu.runtime import DistributedRuntime

        async def body():
            cluster = uuid.uuid4().hex
            journal = str(tmp_path / "journal")
            rts = []

            async def rt():
                r = await DistributedRuntime(_cfg(cluster, journal)).start()
                rts.append(r)
                return r

            workers = []
            for _ in range(2):
                w = MockerWorker(
                    await rt(), model_name="mock-model",
                    config=MockerConfig(speedup_ratio=500.0,
                                        num_blocks=256, block_size=16),
                    load_publish_interval=0.2)
                # JetStream-mode deployment: recovery comes from the
                # durable log, not worker queries.
                w.card.runtime_config["kv_blocks_endpoint"] = False
                await w.start()
                workers.append(w)

            f1 = Frontend(await rt(), host="127.0.0.1", port=0,
                          router_mode="kv")
            await f1.start()
            f2 = Frontend(await rt(), host="127.0.0.1", port=0,
                          router_mode="kv")
            await f2.start()
            for f in (f1, f2):
                for _ in range(100):
                    if f.manager.get("mock-model") is not None:
                        break
                    await asyncio.sleep(0.05)

            # live traffic through BOTH replicas
            await _chat(f1.port, "shared prefix one " * 8, n=3)
            await _chat(f2.port, "shared prefix two " * 8, n=3)
            for _ in range(100):
                if _tree_state(f1) and _tree_state(f1) == _tree_state(f2):
                    break
                await asyncio.sleep(0.05)
            state_before = _tree_state(f1)
            assert state_before and sum(state_before.values()) > 0
            assert _tree_state(f2) == state_before

            # kill replica 2 mid-operation...
            f2_port_rt = rts[-1]
            await f2.close()
            await f2_port_rt.shutdown()
            # ...traffic keeps flowing through replica 1 while 2 is down
            await _chat(f1.port, "prefix while down " * 8, n=2)

            # restart replica 2 fresh
            f2b = Frontend(await rt(), host="127.0.0.1", port=0,
                           router_mode="kv")
            await f2b.start()
            for _ in range(200):
                entry = f2b.manager.get("mock-model")
                if (entry is not None and entry.scheduler is not None
                        and _tree_state(f2b) == _tree_state(f1)
                        and _tree_state(f2b)):
                    break
                await asyncio.sleep(0.05)
            # consistent trees, recovered from the journal alone
            assert _tree_state(f2b) == _tree_state(f1)
            assert sum(_tree_state(f2b).values()) \
                > sum(state_before.values())
            # and the restarted replica still serves KV-routed traffic
            await _chat(f2b.port, "shared prefix one " * 8, n=1)

            await f2b.close()
            await f1.close()
            for w in workers:
                await w.close()
            for r in rts:
                await r.shutdown()

        run(body(), timeout=180)


class TestJournalFormatUpgrade:
    def test_legacy_pre_crc_journal_replays_not_corrupt_skipped(
            self, run, tmp_path):
        """A journal written by the pre-CRC format ([len][body] frames,
        no magic preamble) must replay through the legacy parser on
        upgrade — NOT be discarded as wall-to-wall CRC corruption with
        a false storage-corruption alarm (bad_frames must stay 0)."""
        import msgpack

        def legacy_pack(topic, payload):
            body = msgpack.packb({"t": topic, "p": payload},
                                 use_bin_type=True)
            return struct.pack(">I", len(body)) + body

        async def body():
            ns = os.path.join(str(tmp_path), "ns")
            os.makedirs(ns)
            # Pre-upgrade history from a dead publisher: no magic.
            with open(os.path.join(ns, "oldpub.g0.log"), "wb") as f:
                for i in range(4):
                    f.write(legacy_pack("kv_events", {"i": i}))
            # Post-upgrade publisher in the same dir: CRC format.
            pub = JournalEventPublisher(str(tmp_path), "ns")
            await pub.publish("kv_events", {"i": 100})
            mgr = JournalEventSubscriberManager(str(tmp_path), "ns",
                                                "kv_events",
                                                poll_interval=0.02)
            sub = await mgr.start()
            events = await _drain(sub, 5)
            got = sorted(p["i"] for t, p in events if t == "kv_events")
            assert got == [0, 1, 2, 3, 100]
            assert mgr.bad_frames == 0  # upgrade is not corruption
            await mgr.close()
            await pub.close()

        run(body())
