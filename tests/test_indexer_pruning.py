"""Radix-index TTL expiry + size pruning, both backends (ref:
lib/kv-router/src/indexer/pruning.rs PruneManager; concurrent_radix_tree.rs
for the native tree's internal locking)."""

import threading
import time

import pytest

from dynamo_tpu.kv_router.indexer import (
    NativeRadixTree,
    RadixTree,
    make_radix_tree,
)
from dynamo_tpu.kv_router.protocols import WorkerWithDpRank
from dynamo_tpu.native import get_native

W0 = WorkerWithDpRank(1, 0)
W1 = WorkerWithDpRank(2, 0)

BACKENDS = ["python"]
if get_native() is not None:
    BACKENDS.append("native")


def _tree(backend, **kwargs):
    if backend == "native":
        return NativeRadixTree(get_native(), **kwargs)
    return RadixTree(**kwargs)


def _store(tree, worker, hashes, parent=None):
    if isinstance(tree, NativeRadixTree):
        tree._tree.apply_stored(worker.worker_id, worker.dp_rank, parent,
                                list(hashes))
    else:
        tree._apply_stored(worker, parent, list(hashes))


@pytest.mark.parametrize("backend", BACKENDS)
class TestTtlExpiry:
    def test_blocks_expire_after_ttl(self, backend):
        tree = _tree(backend, ttl_secs=0.05)
        _store(tree, W0, [1, 2, 3])
        assert tree.find_matches([1, 2, 3]).scores == {W0: 3}
        assert tree.maintain() == []  # not yet
        time.sleep(0.08)
        evicted = tree.maintain()
        assert sorted(h for _, _, h in evicted) == [1, 2, 3]
        assert all(wid == W0.worker_id for wid, _, _ in evicted)
        assert tree.find_matches([1, 2, 3]).scores == {}

    def test_restore_refreshes_ttl(self, backend):
        tree = _tree(backend, ttl_secs=0.15)
        _store(tree, W0, [1, 2])
        time.sleep(0.09)
        _store(tree, W0, [1, 2])  # re-store: TTL refreshed
        time.sleep(0.09)  # 0.18 > ttl from FIRST store, < from second
        assert tree.maintain() == []
        assert tree.find_matches([1, 2]).scores == {W0: 2}

    def test_expiry_is_per_worker(self, backend):
        tree = _tree(backend, ttl_secs=0.1)
        _store(tree, W0, [1, 2])
        time.sleep(0.06)
        _store(tree, W1, [1, 2])
        time.sleep(0.06)  # W0's copy expired; W1's is fresh
        evicted = tree.maintain()
        assert {(wid, h) for wid, _, h in evicted} == {(1, 1), (1, 2)}
        assert tree.find_matches([1, 2]).scores == {W1: 2}

    def test_disabled_by_default(self, backend):
        tree = _tree(backend)
        _store(tree, W0, [1, 2])
        time.sleep(0.02)
        assert tree.maintain() == []
        assert tree.find_matches([1, 2]).scores == {W0: 2}


@pytest.mark.parametrize("backend", BACKENDS)
class TestSizePruning:
    def test_prunes_oldest_down_to_target(self, backend):
        tree = _tree(backend, ttl_secs=300.0, max_tree_size=10)
        # 16 single-block chains, oldest first
        for i in range(16):
            _store(tree, W0, [100 + i])
            time.sleep(0.002)  # strictly increasing expiries
        assert tree.total_nodes() == 16
        evicted = tree.maintain()
        # prune down to 0.8 * 10 = 8 nodes, oldest first
        assert tree.total_nodes() == 8
        evicted_hashes = sorted(h for _, _, h in evicted)
        assert evicted_hashes == [100 + i for i in range(8)]
        # newest survive
        assert tree.find_matches([115]).scores == {W0: 1}

    def test_under_budget_untouched(self, backend):
        tree = _tree(backend, ttl_secs=300.0, max_tree_size=10)
        for i in range(5):
            _store(tree, W0, [200 + i])
        assert tree.maintain() == []
        assert tree.total_nodes() == 5

    def test_prune_converges_with_replicated_blocks(self, backend):
        """Hashes held by MULTIPLE workers: node count only drops when the
        last holder is evicted — the sweep must still reach the target in
        ONE maintain() call (node-count-driven loop)."""
        tree = _tree(backend, ttl_secs=300.0, max_tree_size=10)
        for i in range(16):
            _store(tree, W0, [600 + i])
            _store(tree, W1, [600 + i])  # replicate on a second worker
            time.sleep(0.002)
        assert tree.total_nodes() == 16
        tree.maintain()
        assert tree.total_nodes() == 8  # one sweep reaches the target

    def test_size_pruning_works_without_ttl(self, backend):
        """max_tree_size alone must prune (TTL and size budgets are
        independent knobs)."""
        tree = _tree(backend, max_tree_size=10)
        for i in range(16):
            _store(tree, W0, [300 + i])
            time.sleep(0.002)
        evicted = tree.maintain()
        assert tree.total_nodes() == 8
        assert sorted(h for _, _, h in evicted) == [300 + i
                                                    for i in range(8)]

    def test_expiry_applied_before_size_check(self, backend):
        """A sweep whose TTL expiry already brings the tree under budget
        must not additionally prune live blocks."""
        tree = _tree(backend, ttl_secs=0.05, max_tree_size=10)
        for i in range(8):  # these will expire
            _store(tree, W0, [400 + i])
        time.sleep(0.08)
        for i in range(7):  # fresh: under budget after expiry
            _store(tree, W1, [500 + i])
        evicted = tree.maintain()
        # only the 8 expired go; the 7 fresh survive (12 > 10 pre-expiry,
        # 7 <= 10 post-expiry)
        assert sorted(h for _, _, h in evicted) == [400 + i
                                                    for i in range(8)]
        assert tree.total_nodes() == 7


@pytest.mark.skipif(get_native() is None, reason="native core not built")
class TestNativeConcurrency:
    def test_parallel_match_and_mutate(self):
        """The native tree locks internally and releases the GIL: threads
        hammering reads+writes concurrently must neither crash nor corrupt
        counts (the ConcurrentRadixTree contract)."""
        tree = NativeRadixTree(get_native(), ttl_secs=60.0)
        stop = threading.Event()
        errors = []

        def writer(wid):
            try:
                i = 0
                while not stop.is_set():
                    w = WorkerWithDpRank(wid, 0)
                    _store(tree, w, [wid * 10_000 + (i % 50) * 3 + j
                                     for j in range(3)])
                    if i % 7 == 0:
                        tree.remove_worker(w)
                    i += 1
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        def reader():
            try:
                while not stop.is_set():
                    tree.find_matches([1, 2, 3])
                    tree.total_nodes()
                    tree.maintain()
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=writer, args=(w,))
                   for w in range(3)]
        threads += [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
        # full cleanup must leave a consistent empty-ish index
        for w in range(3):
            tree.remove_worker(WorkerWithDpRank(w, 0))
        assert all(c == 0 for c in tree.worker_block_counts().values())


class TestFactoryKnobs:
    def test_env_knobs_flow_through(self, monkeypatch):
        monkeypatch.setenv("DYNT_INDEXER_TTL_SECS", "0.05")
        monkeypatch.setenv("DYNT_INDEXER_MAX_TREE_SIZE", "64")
        tree = make_radix_tree()
        _store(tree, W0, [7])
        time.sleep(0.08)
        assert [(wid, h) for wid, _, h in tree.maintain()] == [(1, 7)]
