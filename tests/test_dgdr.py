"""DGDR flow: request -> profile -> generated graph -> phased reconcile
(ref: deploy/operator DGDRPhase machine + profiling job -> final config).
"""

import asyncio
import uuid

import pytest

from dynamo_tpu.deploy.dgdr import (
    DEPLOYED,
    DEPLOYING,
    DGDR_STATUS_PREFIX,
    FAILED,
    DeploymentRequest,
    DgdrController,
    generate_spec,
    get_status,
    profile_request,
    submit_request,
)
from dynamo_tpu.runtime import DistributedRuntime


class TestProfiling:
    def test_picks_min_chips_meeting_sla(self):
        req = DeploymentRequest(
            name="d", model="qwen3-0.6b", chip="v5e", max_chips=8,
            ttft_ms=2000.0, itl_ms=50.0, isl=1024, osl=256, concurrency=8)
        prof = profile_request(req)
        assert prof.tp >= 1 and prof.replicas >= 1
        assert prof.total_chips <= 8
        assert prof.est_ttft_ms <= 2000.0
        assert prof.est_itl_ms <= 50.0

    def test_tighter_sla_needs_more_chips(self):
        loose = profile_request(DeploymentRequest(
            name="d", model="llama3-8b", chip="v5e", max_chips=16,
            ttft_ms=5000.0, itl_ms=200.0, isl=2048, concurrency=4))
        tight = profile_request(DeploymentRequest(
            name="d", model="llama3-8b", chip="v5e", max_chips=16,
            ttft_ms=300.0, itl_ms=30.0, isl=2048, concurrency=4))
        assert tight.total_chips >= loose.total_chips

    def test_impossible_sla_raises(self):
        with pytest.raises(ValueError, match="meets SLA"):
            profile_request(DeploymentRequest(
                name="d", model="llama3-70b", chip="v5e", max_chips=1,
                ttft_ms=1.0, itl_ms=0.5, isl=8192, concurrency=64))

    def test_generated_spec_shape(self):
        req = DeploymentRequest(name="gen", model="qwen3-0.6b",
                                engine="mocker", concurrency=4)
        prof = profile_request(req)
        spec = generate_spec(req, prof)
        assert set(spec.services) == {"frontend", "decode"}
        assert spec.services["decode"].kind == "mocker"
        assert spec.services["decode"].replicas == prof.replicas


class _FakeController:
    """Records the reconcile surface the DGDR controller drives."""

    def __init__(self, spec):
        self.spec = spec
        self.desired = {n: s.replicas for n, s in spec.services.items()}
        self.started = False
        self.closed = False
        self.scale_calls = []

    def start(self):
        self.started = True

    async def close(self):
        self.closed = True

    def set_replicas(self, service, n):
        self.scale_calls.append((service, n))
        self.desired[service] = n

    def status(self):
        return {"deployment": self.spec.name,
                "services": {n: {"desired": d, "running": d,
                                 "crash_streak": 0}
                             for n, d in self.desired.items()},
                "restarts": 0}


class TestDgdrReconcile:
    def _runtime(self, mem_runtime_config):
        return DistributedRuntime(mem_runtime_config())

    def test_phases_to_deployed_and_rolling_scale(self, run,
                                                  mem_runtime_config):
        async def body():
            rt = await self._runtime(mem_runtime_config).start()
            made = []

            def factory(spec):
                ctl = _FakeController(spec)
                made.append(ctl)
                return ctl

            dgdr = DgdrController(rt, controller_factory=factory)
            await dgdr.start()
            req = DeploymentRequest(name="mine", model="qwen3-0.6b",
                                    engine="mocker", concurrency=64,
                                    max_chips=16, ttft_ms=5000.0,
                                    itl_ms=3.0)
            await submit_request(rt, req)

            async def wait_phase(phase, timeout=15.0):
                deadline = asyncio.get_event_loop().time() + timeout
                while asyncio.get_event_loop().time() < deadline:
                    st = await get_status(rt, "mine")
                    if st and st.get("phase") == phase:
                        return st
                    await asyncio.sleep(0.05)
                raise AssertionError(
                    f"never reached {phase}: {await get_status(rt, 'mine')}")

            st = await wait_phase(DEPLOYED)
            assert st["profile"]["replicas"] >= 1
            assert made and made[0].started

            # Rolling update: drop concurrency -> replicas scale in place
            # (same shape, no controller replacement).
            # conc 64 -> 32 keeps the profiled batch (and thus the
            # service args) identical; only the replica count halves.
            req2 = DeploymentRequest(name="mine", model="qwen3-0.6b",
                                     engine="mocker", concurrency=32,
                                     max_chips=16, ttft_ms=5000.0,
                                     itl_ms=3.0)
            prof2 = profile_request(req2)
            assert prof2.replicas != st["profile"]["replicas"]
            await submit_request(rt, req2)

            async def wait_scale(timeout=15.0):
                deadline = asyncio.get_event_loop().time() + timeout
                while asyncio.get_event_loop().time() < deadline:
                    if made[0].scale_calls:
                        return
                    await asyncio.sleep(0.05)
                raise AssertionError("no rolling scale happened")

            await wait_scale()
            assert len(made) == 1, "shape-preserving update must not " \
                                   "replace the controller"
            assert made[0].desired["decode"] == prof2.replicas

            # Delete -> teardown + status removal
            await rt.discovery.delete("v1/dgdr/mine")
            deadline = asyncio.get_event_loop().time() + 10.0
            while asyncio.get_event_loop().time() < deadline:
                if made[0].closed and await get_status(rt, "mine") is None:
                    break
                await asyncio.sleep(0.05)
            assert made[0].closed
            assert await get_status(rt, "mine") is None

            await dgdr.close()
            await rt.shutdown()

        run(body(), timeout=60.0)

    def test_engine_change_replaces_deployment(self, run,
                                               mem_runtime_config):
        async def body():
            rt = await self._runtime(mem_runtime_config).start()
            made = []

            def factory(spec):
                ctl = _FakeController(spec)
                made.append(ctl)
                return ctl

            dgdr = DgdrController(rt, controller_factory=factory)
            await dgdr.start()
            await submit_request(rt, DeploymentRequest(
                name="swap", model="qwen3-0.6b", engine="mocker",
                concurrency=2, ttft_ms=5000.0, itl_ms=100.0))
            for _ in range(200):
                if made:
                    break
                await asyncio.sleep(0.05)
            assert made
            # engine mocker -> worker changes service args/kind: replace
            await submit_request(rt, DeploymentRequest(
                name="swap", model="qwen3-0.6b", engine="worker",
                concurrency=2, ttft_ms=5000.0, itl_ms=100.0))
            for _ in range(200):
                if len(made) == 2:
                    break
                await asyncio.sleep(0.05)
            assert len(made) == 2 and made[0].closed
            assert made[1].spec.services["decode"].kind == "worker"
            await dgdr.close()
            await rt.shutdown()

        run(body(), timeout=60.0)

    def test_failed_phase_on_impossible_sla(self, run, mem_runtime_config):
        async def body():
            rt = await self._runtime(mem_runtime_config).start()
            dgdr = DgdrController(rt, controller_factory=_FakeController)
            await dgdr.start()
            await submit_request(rt, DeploymentRequest(
                name="doomed", model="llama3-70b", chip="v5e", max_chips=1,
                ttft_ms=1.0, itl_ms=0.5, isl=8192, concurrency=64))
            for _ in range(200):
                st = await get_status(rt, "doomed")
                if st and st.get("phase") == FAILED:
                    break
                await asyncio.sleep(0.05)
            st = await get_status(rt, "doomed")
            assert st["phase"] == FAILED and "SLA" in st["error"]
            await dgdr.close()
            await rt.shutdown()

        run(body(), timeout=60.0)


class TestDgdrRealProcesses:
    def test_deploys_real_mocker_graph(self, run, tmp_path):
        """End-to-end: DGDR document -> profiled -> REAL frontend + mocker
        processes serving /v1/chat/completions."""
        import aiohttp

        from dynamo_tpu.runtime.config import RuntimeConfig

        port = 18700 + (uuid.uuid4().int % 200)

        async def body():
            cfg = RuntimeConfig.from_env()
            cfg.discovery_backend = "file"
            cfg.discovery_path = str(tmp_path / "disc")
            cfg.request_plane = "tcp"
            cfg.tcp_host = "127.0.0.1"
            cfg.event_plane = "mem"
            cfg.system_enabled = False
            rt = await DistributedRuntime(cfg).start()
            dgdr = DgdrController(rt, log_dir=str(tmp_path / "logs"))
            await dgdr.start()
            await submit_request(rt, DeploymentRequest(
                name="real", model="mock-model", engine="mocker",
                concurrency=2, ttft_ms=5000.0, itl_ms=100.0,
                frontend_port=port,
                env={"DYNT_DISCOVERY_BACKEND": "file",
                     "DYNT_DISCOVERY_PATH": str(tmp_path / "disc"),
                     "DYNT_REQUEST_PLANE": "tcp",
                     "DYNT_EVENT_PLANE": "zmq",
                     "JAX_PLATFORMS": "cpu"}))
            async with aiohttp.ClientSession() as session:
                base = f"http://127.0.0.1:{port}"
                up = False
                for _ in range(240):
                    try:
                        async with session.get(base + "/v1/models") as r:
                            body_ = await r.json()
                            if any(m["id"] == "mock-model"
                                   for m in body_.get("data", [])):
                                up = True
                                break
                    except Exception:  # noqa: BLE001
                        pass
                    await asyncio.sleep(0.5)
                assert up, "DGDR-deployed graph never served"
                async with session.post(
                        base + "/v1/chat/completions",
                        json={"model": "mock-model",
                              "messages": [{"role": "user",
                                            "content": "dgdr"}],
                              "max_tokens": 4}) as resp:
                    assert resp.status == 200, await resp.text()
                st = await get_status(rt, "real")
                assert st["phase"] == DEPLOYED
            await dgdr.close()
            await rt.shutdown()

        run(body(), timeout=240.0)


class TestDgdrMeasuredProfiling:
    def test_measured_mode_sweeps_live_deployment(self, run, tmp_path,
                                                  mem_runtime_config):
        """profile_mode=measured (the reference's 'thorough' profiling job,
        folded into the DGDR loop): deploy the rapid plan with the REAL
        process controller (mocker + frontend), sweep the LIVE frontend,
        and publish measured TTFT/ITL into the status."""
        import uuid as _uuid

        from dynamo_tpu.deploy.controller import LocalDeploymentController

        disc = str(tmp_path / "disc")
        port = 8600 + (_uuid.uuid4().int % 200)

        async def body():
            rt = await DistributedRuntime(mem_runtime_config()).start()

            def factory(spec):
                spec.env.update({
                    "DYNT_DISCOVERY_BACKEND": "file",
                    "DYNT_DISCOVERY_PATH": disc,
                    "DYNT_LOG_LEVEL": "WARNING",
                    "JAX_PLATFORMS": "cpu",
                })
                return LocalDeploymentController(
                    spec, log_dir=str(tmp_path / "logs"),
                    reconcile_interval=0.5)

            dgdr = DgdrController(rt, controller_factory=factory)
            await dgdr.start()
            try:
                req = DeploymentRequest(
                    name="measured", model="mock-model", engine="mocker",
                    concurrency=4, max_chips=8, ttft_ms=10000.0,
                    itl_ms=1000.0, isl=64, osl=8,
                    frontend_port=port, profile_mode="measured")
                await submit_request(rt, req)

                deadline = asyncio.get_event_loop().time() + 150
                st = None
                while asyncio.get_event_loop().time() < deadline:
                    st = await get_status(rt, "measured")
                    if st and st.get("phase") == DEPLOYED \
                            and "measured" in st:
                        break
                    await asyncio.sleep(0.5)
                assert st and st.get("phase") == DEPLOYED, st
                assert "measured" in st, st
                m = st["measured"]
                assert m["requests"] >= 1
                assert m["ttft_ms_p50"] > 0
                assert m["tokens_per_sec"] > 0
                # generous SLA -> the rapid replica count stood
                assert st["profile"]["replicas"] >= 1
            finally:
                await rt.discovery.delete("v1/dgdr/measured")
                await asyncio.sleep(0.5)
                await dgdr.close()
                await rt.shutdown()

        run(body(), timeout=240)
