"""Distributed KVBM leader/worker (ref: lib/llm/src/block_manager/
distributed/{leader,worker}.rs): the leader plans offload/onboard while
every rank stores/loads only its LOCAL shard of each KV block.

Tiers:
  1. in-process, tp=2 sharded pool on the 8-device CPU mesh: offload a
     prefilled sequence's sharded KV to the shard arena, clobber the
     pool pages, onboard back — bit-exact against a pre-offload oracle.
  2. leader metadata / arena LRU consistency under eviction.
  3. multi-process e2e: a 2-process x 2-device multihost engine with
     --kvbm-host-blocks serves a prompt, G1 evicts it under pressure,
     the resend onboards from the DISTRIBUTED host tier and the greedy
     completion is unchanged (serving-level bit-exactness).
"""

import asyncio
import os
import subprocess
import sys
import time
import uuid

import numpy as np
import pytest

from dynamo_tpu.block_manager import KvbmConfig
from dynamo_tpu.block_manager.distributed import (
    DistributedKvbm,
    KvbmShardWorker,
)
from dynamo_tpu.engine import ModelRunner, RunnerConfig
from dynamo_tpu.models import get_config
from dynamo_tpu.parallel import MeshConfig, make_mesh
from jax_capabilities import requires_multicore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark_e2e = pytest.mark.skipif(
    os.environ.get("DYNT_SKIP_CHAOS") == "1",
    reason="multi-process tier disabled")


@pytest.fixture(scope="module")
def tp_runner():
    return ModelRunner(
        get_config("tiny-test"),
        RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                     max_pages_per_seq=16, prefill_buckets=(8, 16, 32)),
        make_mesh(MeshConfig(tp=2)),
        seed=0,
    )


class TestShardRoundtrip:
    def test_offload_onboard_bit_exact(self, tp_runner):
        runner = tp_runner
        runner.kvbm_worker = KvbmShardWorker(capacity_blocks=32)
        prompt = np.arange(2, 26, dtype=np.int32)  # 24 tokens, 6 pages
        table = np.zeros(16, np.int32)
        pages = [5, 6, 7, 8, 9, 10]
        table[:6] = pages
        runner.prefill_chunk(prompt, 0, table, 24, (0.0, 1.0, 0, 0))
        oracle = runner.gather_pages(np.asarray(pages, np.int32))

        hashes = [101, 102, 103, 104, 105, 106]
        runner.kvbm_store_shards(np.asarray(pages, np.int32), hashes)
        assert runner.kvbm_worker.drain(30.0)  # D2H + insert are async
        assert len(runner.kvbm_worker) == 6

        # Clobber the original pages so onboard can't cheat.
        runner.scatter_pages(np.asarray(pages, np.int32),
                             np.zeros_like(oracle))
        clobbered = runner.gather_pages(np.asarray(pages, np.int32))
        assert not np.array_equal(clobbered, oracle)

        # Onboard into DIFFERENT pages: shard reassembly must reproduce
        # the bytes exactly.
        new_pages = np.asarray([11, 12, 13, 14, 15, 16], np.int32)
        runner.kvbm_load_shards(hashes, new_pages)
        back = runner.gather_pages(new_pages)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(oracle))

    def test_arena_miss_fails_loudly(self, tp_runner):
        runner = tp_runner
        runner.kvbm_worker = KvbmShardWorker(capacity_blocks=8)
        with pytest.raises(RuntimeError, match="shard arena miss"):
            runner.kvbm_load_shards([999], np.asarray([3], np.int32))

    def test_offload_onboard_bit_exact_int8(self):
        """Quantized pool through the DISTRIBUTED shard path (VERDICT r5
        item 6): packed uint8 blocks shard/reassemble opaquely — the
        worker never learns the pool is two arrays — and the roundtrip
        is bit-exact."""
        import dataclasses

        cfg = dataclasses.replace(get_config("tiny-test"), head_dim=128)
        runner = ModelRunner(
            cfg,
            RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                         max_pages_per_seq=16, prefill_buckets=(8, 16, 32),
                         kv_dtype="int8"),
            make_mesh(MeshConfig(tp=2)),
            seed=0,
        )
        runner.kvbm_worker = KvbmShardWorker(capacity_blocks=32)
        prompt = np.arange(2, 26, dtype=np.int32)
        table = np.zeros(16, np.int32)
        pages = [5, 6, 7, 8, 9, 10]
        table[:6] = pages
        runner.prefill_chunk(prompt, 0, table, 24, (0.0, 1.0, 0, 0))
        oracle = runner.gather_pages(np.asarray(pages, np.int32))
        assert oracle.dtype == np.uint8  # packed quantized blocks

        hashes = [201, 202, 203, 204, 205, 206]
        runner.kvbm_store_shards(np.asarray(pages, np.int32), hashes)
        assert runner.kvbm_worker.drain(30.0)
        runner.scatter_pages(np.asarray(pages, np.int32),
                             np.zeros_like(oracle))
        new_pages = np.asarray([11, 12, 13, 14, 15, 16], np.int32)
        runner.kvbm_load_shards(hashes, new_pages)
        back = runner.gather_pages(new_pages)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(oracle))


class TestLeaderConsistency:
    def test_index_and_arena_evict_identically(self, tp_runner):
        runner = tp_runner
        runner.kvbm_worker = KvbmShardWorker(capacity_blocks=4)
        cfg = KvbmConfig(host_blocks=4, offload_batch=4)
        leader = DistributedKvbm(cfg, runner)
        pages = {h: 20 + i for i, h in enumerate([1, 2, 3, 4, 5, 6])}
        leader.attach_engine(
            lookup_pages=lambda hs: [pages.get(h) for h in hs],
            gather=None, run_in_step=None)
        try:
            leader.notify_stored([1, 2, 3, 4], None)
            assert leader.flush(10.0)
            assert leader.match_prefix([1, 2, 3, 4]) == 4
            # Two more: capacity 4 -> LRU evicts 1 then 2, in BOTH the
            # leader index and the shard arena (same deterministic order).
            leader.notify_stored([5, 6], None)
            assert leader.flush(10.0)
            assert leader.match_prefix([1]) == 0
            assert leader.match_prefix([3, 4, 5, 6]) == 4
            assert len(runner.kvbm_worker) == 4
            arena_hashes = set(runner.kvbm_worker._rows)
            assert arena_hashes == {3, 4, 5, 6}
        finally:
            leader.close()

    def test_onboard_direct_scatters(self, tp_runner):
        runner = tp_runner
        runner.kvbm_worker = KvbmShardWorker(capacity_blocks=16)
        cfg = KvbmConfig(host_blocks=16, offload_batch=4)
        leader = DistributedKvbm(cfg, runner)
        prompt = np.arange(40, 56, dtype=np.int32)  # 4 pages
        table = np.zeros(16, np.int32)
        table[:4] = [30, 31, 32, 33]
        runner.prefill_chunk(prompt, 0, table, 16, (0.0, 1.0, 0, 0))
        oracle = runner.gather_pages(np.asarray([30, 31, 32, 33], np.int32))
        pages = {h: 30 + i for i, h in enumerate([7, 8, 9, 10])}
        leader.attach_engine(
            lookup_pages=lambda hs: [pages.get(h) for h in hs],
            gather=None, run_in_step=None)
        try:
            leader.notify_stored([7, 8, 9, 10], None)
            assert leader.flush(10.0)
            target = np.asarray([40, 41, 42, 43], np.int32)
            assert leader.onboard_direct([7, 8, 9, 10], target, runner)
            back = runner.gather_pages(target)
            np.testing.assert_array_equal(np.asarray(back),
                                          np.asarray(oracle))
            assert leader.stats.onboarded_blocks == 4
            # Unknown hash -> False, no exception
            assert not leader.onboard_direct([777], target[:1], runner)
        finally:
            leader.close()


def _spawn(module, *args, env, log_path):
    f = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", module, *args],
        stdout=f, stderr=subprocess.STDOUT, env=env, cwd=REPO)


@pytestmark_e2e
@requires_multicore
class TestMultihostKvbmE2E:
    def test_offload_onboard_across_hosts(self, run, tmp_path):
        """2-process x 2-device engine with a distributed host tier:
        a prompt's KV is offloaded (sharded across BOTH processes),
        evicted from G1 under pool pressure, then onboarded back —
        and the greedy completion is identical."""
        import aiohttp

        salt = uuid.uuid4().int
        mh_port = 19400 + (salt % 200)
        fe_port = 19650 + (salt % 200)

        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "DYNT_JAX_PLATFORM": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "PYTHONPATH": REPO,
            "DYNT_DISCOVERY_BACKEND": "file",
            "DYNT_DISCOVERY_PATH": str(tmp_path / "disc"),
            "DYNT_REQUEST_PLANE": "tcp",
            "DYNT_EVENT_PLANE": "zmq",
            "DYNT_SYSTEM_ENABLED": "false",
            "DYNT_LOG_LEVEL": "INFO",
        })
        flags = ["--model", "tiny-test", "--page-size", "4",
                 "--num-pages", "72", "--max-batch", "2",
                 "--max-pages-per-seq", "24", "--tp", "2", "--dp", "2",
                 "--kvbm-host-blocks", "96"]
        logs = tmp_path / "logs"
        logs.mkdir()
        procs = []
        try:
            follower = _spawn(
                "dynamo_tpu.worker", *flags,
                "--multihost", f"1/2@127.0.0.1:{mh_port}",
                env=env, log_path=logs / "follower.log")
            driver = _spawn(
                "dynamo_tpu.worker", *flags,
                "--multihost", f"0/2@127.0.0.1:{mh_port}",
                env=env, log_path=logs / "driver.log")
            fe = _spawn("dynamo_tpu.frontend", "--port", str(fe_port),
                        env=env, log_path=logs / "fe.log")
            procs = [follower, driver, fe]

            async def chat(session, base, content):
                async with session.post(
                        base + "/v1/chat/completions", json={
                            "model": "tiny-test",
                            "messages": [
                                {"role": "user", "content": content}],
                            "max_tokens": 4, "temperature": 0.0,
                            "seed": 0}) as r:
                    assert r.status == 200, await r.text()
                    body = await r.json()
                    return body["choices"][0]["message"]["content"]

            async def body():
                from tests.test_multihost import _wait_models

                base = f"http://127.0.0.1:{fe_port}"
                async with aiohttp.ClientSession() as session:
                    assert await _wait_models(session, base, "tiny-test"), (
                        (logs / "driver.log").read_text()[-3000:])
                    # Long-ish prompt (context cap is 64 tokens here);
                    # its blocks offload to the sharded host tier in the
                    # background.
                    target = "abcdefgh" * 3
                    first = await chat(session, base, target)
                    # Pool pressure: unrelated prompts evict target's G1
                    # pages (72-page pool, ~15-20 pages per request).
                    for i in range(5):
                        await chat(session, base, f"un{i}xyzw" * 2)
                    # Resend: prefix must onboard from the DISTRIBUTED
                    # host tier (not recompute), and greedy output must
                    # be bit-identical.
                    again = await chat(session, base, target)
                    assert again == first
                    deadline = time.monotonic() + 20
                    while time.monotonic() < deadline:
                        log_text = (logs / "driver.log").read_text()
                        if "kvbm onboard" in log_text:
                            break
                        await asyncio.sleep(0.5)
                    assert "kvbm onboard" in log_text, log_text[-3000:]

            run(body(), timeout=420.0)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            deadline = time.time() + 10
            for p in procs:
                try:
                    p.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()
