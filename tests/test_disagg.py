"""Disaggregated prefill/decode serving (ref contract: §3.4 PrefillRouter +
KV transfer; disagg-serving.md xPyD). Three tiers:

  1. kv_transfer unit: chunk/assemble roundtrip, layout bridging
  2. real engines: prefill TpuWorker -> kv_pull -> decode TpuWorker; the
     disagg greedy stream must equal the aggregated one token-for-token
  3. mocker E2E: frontend + prefill mocker pool + decode mockers through
     the OpenAI surface (runtime-reconfigurable activation)
"""

import asyncio
import uuid

import numpy as np
import pytest

from dynamo_tpu.engine import RunnerConfig, TpuWorker
from dynamo_tpu.llm.engine import Migration, RouterEngine
from dynamo_tpu.llm.kv_transfer import (
    BlockAssembler,
    KvLayoutDescriptor,
    PendingTransfer,
    PendingTransferTable,
    encode_block_chunks,
)
from dynamo_tpu.llm.prefill_router import PrefillPool, PrefillRouterEngine
from dynamo_tpu.llm.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.push_router import PushRouter


def _layout(ps=4):
    return KvLayoutDescriptor(n_layers=2, kv_heads=2, head_dim=8,
                              page_size=ps, dtype="float32")


class TestKvTransferWire:
    def test_chunk_assemble_roundtrip(self):
        layout = _layout()
        rng = np.random.default_rng(0)
        blocks = rng.normal(size=(5, 2, 2, 4, 2, 8)).astype(np.float32)
        asm = BlockAssembler()
        frames = list(encode_block_chunks(blocks, layout))
        assert frames[0]["total_chunks"] == len(frames)
        for f in reversed(frames):  # order-independent
            asm.add(f)
        assert asm.complete
        out, got_layout = asm.assemble()
        np.testing.assert_array_equal(out, blocks)
        assert got_layout == layout

    def test_chunking_splits_large_bundles(self):
        import dynamo_tpu.llm.kv_transfer as kt

        layout = _layout()
        blocks = np.zeros((8, 2, 2, 4, 2, 8), np.float32)
        old = kt.TRANSFER_CHUNK_BYTES
        kt.TRANSFER_CHUNK_BYTES = layout.page_bytes() * 3
        try:
            frames = list(encode_block_chunks(blocks, layout))
        finally:
            kt.TRANSFER_CHUNK_BYTES = old
        assert len(frames) == 3  # 3 + 3 + 2 pages
        assert sum(f["page_count"] for f in frames) == 8

    def test_incompatible_layouts(self):
        a, b = _layout(), _layout(ps=8)
        assert not a.compatible(b)

    def test_pending_table_expiry_releases(self):
        released = []
        table = PendingTransferTable(ttl_secs=0.0)
        table.add(PendingTransfer(
            transfer_id="t1", page_ids=[1, 2],
            release=lambda: released.append("t1"),
            layout=_layout(), prompt_len=8,
        ))
        assert table.expire_stale() == 1
        assert released == ["t1"]
        assert table.claim("t1") is None

    def test_claim_is_exclusive_with_expiry(self):
        released = []
        table = PendingTransferTable(ttl_secs=0.0)
        table.add(PendingTransfer(
            transfer_id="t2", page_ids=[3],
            release=lambda: released.append("t2"),
            layout=_layout(), prompt_len=4,
        ))
        t = table.claim("t2")
        assert t is not None
        # expiry after a claim must not double-release
        assert table.expire_stale() == 0
        assert released == []
        t.release()
        assert released == ["t2"]


async def _collect(engine, request):
    toks = []
    async for out in engine.generate(request):
        assert out.error is None, out.error
        toks.extend(out.token_ids)
        if out.finish_reason is not None:
            break
    return toks


def _request(tokens, max_tokens=6, temperature=0.0):
    return PreprocessedRequest(
        request_id=uuid.uuid4().hex,
        token_ids=list(tokens),
        sampling=SamplingOptions(max_tokens=max_tokens,
                                 temperature=temperature, seed=7),
        stop=StopConditions(ignore_eos=True),
    )


class TestRealEngineDisagg:
    def test_disagg_stream_matches_aggregated(self, run, mem_runtime_config):
        """Prefill on worker A, KV pulled to worker B, decode on B: greedy
        output must match a pure worker-B run (KV transfer is lossless)."""

        async def body():
            cfg = mem_runtime_config()
            rt = await DistributedRuntime(cfg).start()
            rcfg = RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                                max_pages_per_seq=16,
                                prefill_buckets=(8, 16, 32))
            prefill_w = TpuWorker(rt, model_name="tiny-test",
                                  component="prefill", mode="prefill",
                                  runner_config=rcfg, warmup=False)
            decode_w = TpuWorker(rt, model_name="tiny-test",
                                 component="backend", mode="decode",
                                 runner_config=rcfg, warmup=False)
            await prefill_w.start()
            await decode_w.start()

            decode_ep = rt.namespace("dynamo").component("backend") \
                          .endpoint("generate")
            decode_router = PushRouter(decode_ep.client(), mode="round_robin")
            await decode_router.client.start()
            inner = RouterEngine(decode_router)

            prefill_ep = rt.namespace("dynamo").component("prefill") \
                           .endpoint("generate")
            prefill_router = PushRouter(prefill_ep.client(),
                                        mode="round_robin")
            await prefill_router.client.start()
            pool = PrefillPool(router=prefill_router,
                               instances={prefill_w.instance_id})
            disagg_engine = PrefillRouterEngine(inner, lambda: pool)

            prompt = list(range(30, 47))  # 17 tokens: partial last page
            agg = await _collect(inner, _request(prompt))
            dis = await _collect(disagg_engine, _request(prompt))
            assert agg == dis
            assert len(dis) == 6

            # prefill pool pages were released after the pull
            for _ in range(50):
                if len(prefill_w.transfers) == 0:
                    break
                await asyncio.sleep(0.05)
            assert len(prefill_w.transfers) == 0

            await decode_router.client.close()
            await prefill_router.client.close()
            await prefill_w.close()
            await decode_w.close()
            await rt.shutdown()

        run(body(), timeout=300)

    def test_disagg_first_token_honors_logits_processors(
            self, run, mem_runtime_config):
        """The prefill worker samples the first token with no processors
        applied; the decode side must discard it and regenerate through
        the host path so a forced-response processor controls the WHOLE
        stream (the onboard path's _defer_first_token branch)."""

        async def body():
            cfg = mem_runtime_config()
            rt = await DistributedRuntime(cfg).start()
            rcfg = RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                                max_pages_per_seq=16,
                                prefill_buckets=(8, 16, 32))
            prefill_w = TpuWorker(rt, model_name="tiny-test",
                                  component="prefill", mode="prefill",
                                  runner_config=rcfg, warmup=False)
            decode_w = TpuWorker(rt, model_name="tiny-test",
                                 component="backend", mode="decode",
                                 runner_config=rcfg, warmup=False)
            await prefill_w.start()
            await decode_w.start()
            decode_ep = rt.namespace("dynamo").component("backend") \
                          .endpoint("generate")
            decode_router = PushRouter(decode_ep.client(),
                                       mode="round_robin")
            await decode_router.client.start()
            prefill_ep = rt.namespace("dynamo").component("prefill") \
                           .endpoint("generate")
            prefill_router = PushRouter(prefill_ep.client(),
                                        mode="round_robin")
            await prefill_router.client.start()
            pool = PrefillPool(router=prefill_router,
                               instances={prefill_w.instance_id})
            engine = PrefillRouterEngine(
                RouterEngine(decode_router), lambda: pool)

            forced = [21, 22, 23]
            req = _request(list(range(30, 47)), max_tokens=3)
            req.logits_processors = [
                {"name": "forced_response",
                 "args": {"token_ids": forced, "eos_id": 1}}]
            toks = await _collect(engine, req)
            assert toks == forced

            await decode_router.client.close()
            await prefill_router.client.close()
            await prefill_w.close()
            await decode_w.close()
            await rt.shutdown()

        run(body(), timeout=300)

    def test_disagg_falls_back_when_prefill_pool_empty(self, run,
                                                       mem_runtime_config):
        async def body():
            cfg = mem_runtime_config()
            rt = await DistributedRuntime(cfg).start()
            rcfg = RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                                max_pages_per_seq=16,
                                prefill_buckets=(8, 16, 32))
            decode_w = TpuWorker(rt, model_name="tiny-test",
                                 runner_config=rcfg, warmup=False)
            await decode_w.start()
            decode_ep = rt.namespace("dynamo").component("backend") \
                          .endpoint("generate")
            router = PushRouter(decode_ep.client(), mode="round_robin")
            await router.client.start()
            engine = PrefillRouterEngine(RouterEngine(router), lambda: None)
            toks = await _collect(engine, _request(list(range(12)),
                                                   max_tokens=4))
            assert len(toks) == 4
            await router.client.close()
            await decode_w.close()
            await rt.shutdown()

        run(body(), timeout=300)


class TestStreamingDisagg:
    """Chunked-prefill parity tier (ISSUE 8): with prompts spanning
    several prefill chunks, the streaming handoff (kv_transfer_params
    after the FIRST chunk, pages parked per chunk, first token in the
    pull stream's terminal frame) must produce token streams
    bit-identical to the aggregated path — and actually stream."""

    @staticmethod
    async def _pair(rt, prefill_buckets=(8,)):
        rcfg = RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                            max_pages_per_seq=16,
                            prefill_buckets=prefill_buckets)
        prefill_w = TpuWorker(rt, model_name="tiny-test",
                              component="prefill", mode="prefill",
                              runner_config=rcfg, warmup=False)
        decode_w = TpuWorker(rt, model_name="tiny-test",
                             component="backend", mode="decode",
                             runner_config=rcfg, warmup=False)
        await prefill_w.start()
        await decode_w.start()
        decode_router = PushRouter(
            rt.namespace("dynamo").component("backend")
              .endpoint("generate").client(), mode="round_robin")
        await decode_router.client.start()
        prefill_router = PushRouter(
            rt.namespace("dynamo").component("prefill")
              .endpoint("generate").client(), mode="round_robin")
        await prefill_router.client.start()
        pool = PrefillPool(router=prefill_router,
                           instances={prefill_w.instance_id})
        inner = RouterEngine(decode_router)
        engine = PrefillRouterEngine(inner, lambda: pool)
        closers = (decode_router, prefill_router, prefill_w, decode_w)
        return prefill_w, inner, engine, closers

    @staticmethod
    async def _teardown(rt, closers):
        decode_router, prefill_router, prefill_w, decode_w = closers
        await decode_router.client.close()
        await prefill_router.client.close()
        await prefill_w.close()
        await decode_w.close()
        await rt.shutdown()

    def test_chunked_stream_matches_aggregated(self, run,
                                               mem_runtime_config):
        """30-token prompt at max chunk 8 = 4 chunks: the handoff
        streams (pages parked mid-prefill, params emitted early) and the
        greedy AND sampled streams equal the aggregated ones exactly."""

        async def body():
            rt = await DistributedRuntime(mem_runtime_config()).start()
            prefill_w, inner, engine, closers = await self._pair(rt)
            prompt = list(range(30, 60))  # 30 tokens: partial last page
            for temperature in (0.0, 0.8):
                agg = await _collect(
                    inner, _request(prompt, temperature=temperature))
                dis = await _collect(
                    engine, _request(prompt, temperature=temperature))
                assert agg == dis, (temperature, agg, dis)
            # the handoff genuinely streamed: pages parked before the
    	    # prompt finished prefilling
            assert prefill_w.scheduler.stats.disagg_streamed_pages > 0
            # prefill pool pages were released after the pulls
            for _ in range(50):
                if len(prefill_w.transfers) == 0:
                    break
                await asyncio.sleep(0.05)
            assert len(prefill_w.transfers) == 0
            await self._teardown(rt, closers)

        run(body(), timeout=300)

    def test_serial_handoff_when_pipeline_disabled(self, run,
                                                   mem_runtime_config,
                                                   monkeypatch):
        """DYNT_DISAGG_PIPELINE=0 restores the serial handoff: identical
        output, no streamed pages."""
        monkeypatch.setenv("DYNT_DISAGG_PIPELINE", "0")

        async def body():
            rt = await DistributedRuntime(mem_runtime_config()).start()
            prefill_w, inner, engine, closers = await self._pair(rt)
            prompt = list(range(30, 60))
            agg = await _collect(inner, _request(prompt))
            dis = await _collect(engine, _request(prompt))
            assert agg == dis
            assert prefill_w.scheduler.stats.disagg_streamed_pages == 0
            await self._teardown(rt, closers)

        run(body(), timeout=300)

    def test_mid_stream_release_defers_until_sequence_stops(self, run):
        """A puller dying mid-stream calls transfer.release() while the
        prompt pass is STILL RUNNING. The pages must not return to the
        pool until the sequence stops stepping (a new request allocating
        them would be corrupted by the remaining chunks' KV writes):
        release cancels the sequence and reap frees the pages exactly
        once — the pool never over-frees."""

        async def body():
            rcfg = RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                                max_pages_per_seq=16, prefill_buckets=(8,))
            worker = TpuWorker(None, model_name="tiny-test",
                               component="prefill", mode="prefill",
                               runner_config=rcfg, warmup=False)
            await worker.prepare()
            sched = worker.scheduler

            def usable():
                return sched.pool.free_count() + sched.pool.cached_count()

            before = usable()
            outputs = []

            def emit(out):
                outputs.append(out)
                if out.kv_transfer_params is not None \
                        and out.finish_reason is None:
                    # The "puller": claim on arrival, die immediately.
                    t = worker.transfers.claim(
                        out.kv_transfer_params["transfer_id"])
                    assert t is not None
                    t.release()  # mid-prefill — must NOT free pages yet

            req = _request(list(range(30, 62)), max_tokens=1)
            sched.submit(req, emit, prefill_only=True,
                         on_prefill_done=worker._register_transfer,
                         on_prefill_chunk=worker._stream_transfer_chunk)
            # The cleanup conditions below are vacuously true before the
            # request is admitted — wait for its terminal frame FIRST.
            for _ in range(400):
                if any(o.finish_reason is not None for o in outputs):
                    break
                await asyncio.sleep(0.05)
            for _ in range(200):
                if (usable() >= before and len(worker.transfers) == 0
                        and not worker._stream_transfers
                        and all(s is None for s in sched._slots)):
                    break
                await asyncio.sleep(0.05)
            assert not worker._stream_transfers
            assert len(worker.transfers) == 0
            # released exactly once: the pool is whole, never over-freed
            assert usable() == before, (usable(), before)
            # the prefill leg's stream got a terminal frame (a silent
            # drop would hang the router's background drain)
            assert any(o.finish_reason == "cancelled" for o in outputs), \
                [(o.finish_reason, o.error) for o in outputs]
            await worker.close()

        run(body(), timeout=300)

    def test_stream_abort_on_cancel_releases_pages(self, run):
        """A prefill-only sequence cancelled mid-stream must fail its
        StreamingTransfer (waking any puller) and release the parked
        pages exactly once — the reap-time abort hook. The cancel fires
        from inside the first streamed params emit, so it lands
        deterministically between chunks."""

        async def body():
            rcfg = RunnerConfig(page_size=4, num_pages=64, max_batch=2,
                                max_pages_per_seq=16, prefill_buckets=(8,))
            worker = TpuWorker(None, model_name="tiny-test",
                               component="prefill", mode="prefill",
                               runner_config=rcfg, warmup=False)
            await worker.prepare()
            sched = worker.scheduler
            def usable():
                # released pages may land in the prefix cache (computed
                # KV is cacheable) — usable capacity = free + evictable
                return sched.pool.free_count() + sched.pool.cached_count()

            free_before = usable()
            outputs = []
            handle_box = {}

            def emit(out):
                outputs.append(out)
                if out.kv_transfer_params is not None \
                        and out.finish_reason is None:
                    # First streamed chunk params: cancel mid-stream, on
                    # the scheduler thread (deterministic).
                    handle_box["h"].cancel()

            req = _request(list(range(30, 62)), max_tokens=1)
            handle_box["h"] = sched.submit(
                req, emit, prefill_only=True,
                on_prefill_done=worker._register_transfer,
                on_prefill_chunk=worker._stream_transfer_chunk)
            for _ in range(200):
                if (usable() >= free_before
                        and len(worker.transfers) == 0
                        and not worker._stream_transfers
                        and sched.stats.disagg_streamed_pages > 0):
                    break
                await asyncio.sleep(0.05)
            assert sched.stats.disagg_streamed_pages > 0
            assert len(worker.transfers) == 0
            assert not worker._stream_transfers
            assert usable() >= free_before
            # no finish frame was emitted for the cancelled sequence
            assert not any(o.finish_reason == "stop" for o in outputs)
            await worker.close()

        run(body(), timeout=300)


class TestMockerDisaggE2E:
    def test_frontend_routes_through_prefill_pool(self, run):
        """Frontend + decode mockers + a prefill mocker: requests flow
        prefill-first once the pool appears (xPyD activation), and the
        output stream is unchanged."""
        import aiohttp

        from dynamo_tpu.frontend import Frontend
        from dynamo_tpu.mocker import MockerConfig, MockerWorker
        from dynamo_tpu.runtime import RuntimeConfig

        def _cfg(cluster):
            cfg = RuntimeConfig.from_env()
            cfg.discovery_backend = "mem"
            cfg.discovery_path = cluster
            cfg.request_plane = "tcp"
            cfg.tcp_host = "127.0.0.1"
            cfg.event_plane = "mem"
            cfg.system_enabled = False
            cfg.lease_ttl_secs = 1.0
            return cfg

        async def body():
            cluster = uuid.uuid4().hex
            mcfg = MockerConfig(speedup_ratio=500.0, num_blocks=256)
            rt_d = await DistributedRuntime(_cfg(cluster)).start()
            decode_w = MockerWorker(rt_d, model_name="mock-model",
                                    config=mcfg, load_publish_interval=0.2)
            await decode_w.start()
            frt = await DistributedRuntime(_cfg(cluster)).start()
            frontend = Frontend(frt, host="127.0.0.1", port=0,
                                router_mode="round_robin")
            await frontend.start()
            for _ in range(100):
                if frontend.manager.get("mock-model") is not None:
                    break
                await asyncio.sleep(0.05)

            payload = {
                "model": "mock-model",
                "messages": [{"role": "user", "content": "hello disagg"}],
                "max_tokens": 8,
            }
            base = f"http://127.0.0.1:{frontend.port}"

            async with aiohttp.ClientSession() as session:
                async with session.post(f"{base}/v1/chat/completions",
                                        json=payload) as resp:
                    assert resp.status == 200
                    agg_body = await resp.json()
                agg_text = agg_body["choices"][0]["message"]["content"]

                # Bring up the prefill pool -> PrefillRouter activates.
                rt_p = await DistributedRuntime(_cfg(cluster)).start()
                prefill_w = MockerWorker(rt_p, model_name="mock-model",
                                         component="prefill", mode="prefill",
                                         config=mcfg,
                                         load_publish_interval=0.2)
                await prefill_w.start()
                watcher = frontend.watcher
                for _ in range(100):
                    pool = watcher._prefill_pools.get("mock-model")
                    if pool is not None and pool.active():
                        break
                    await asyncio.sleep(0.05)
                assert watcher._prefill_pools["mock-model"].active()

                async with aiohttp.ClientSession() as s2, s2.post(
                        f"{base}/v1/chat/completions", json=payload) as resp:
                    assert resp.status == 200
                    dis_body = await resp.json()
                dis_text = dis_body["choices"][0]["message"]["content"]
                assert dis_text == agg_text
                # the prefill mocker actually served the prefill leg
                assert prefill_w.engine.steps > 0

                # Drain the pool (lease delete) -> passthrough again.
                await prefill_w.close()
                await rt_p.shutdown()
                for _ in range(100):
                    if "mock-model" not in watcher._prefill_pools:
                        break
                    await asyncio.sleep(0.1)
                assert "mock-model" not in watcher._prefill_pools
                async with aiohttp.ClientSession() as s3, s3.post(
                        f"{base}/v1/chat/completions", json=payload) as resp:
                    assert resp.status == 200

            await frontend.close()
            await frt.shutdown()
            await decode_w.close()
            await rt_d.shutdown()

        run(body(), timeout=300)
