"""Migration operator tests (ref contract: lib/llm/src/migration.rs — retry
a broken stream on another worker, preserving generated tokens; bounded by
migration_limit)."""

import asyncio

import pytest

from dynamo_tpu.llm.engine import Migration, TokenEngine
from dynamo_tpu.llm.protocols import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.request_plane import ConnectionLost


def _request(max_tokens=10):
    return PreprocessedRequest(
        request_id="r1",
        token_ids=[1, 2, 3],
        sampling=SamplingOptions(max_tokens=max_tokens),
        stop=StopConditions(),
    )


class FlakyEngine(TokenEngine):
    """Emits `per_attempt` tokens then drops the connection, until the final
    attempt which completes. Records the requests it saw."""

    def __init__(self, fail_times: int, per_attempt: int = 3) -> None:
        self.fail_times = fail_times
        self.per_attempt = per_attempt
        self.attempts = 0
        self.seen_requests: list[PreprocessedRequest] = []

    async def generate(self, request):
        self.attempts += 1
        self.seen_requests.append(request)
        base = 100 * self.attempts
        for i in range(self.per_attempt):
            yield EngineOutput(token_ids=[base + i])
        if self.attempts <= self.fail_times:
            raise ConnectionLost("worker died")
        yield EngineOutput(token_ids=[999], finish_reason="stop")


class TestMigration:
    def test_stream_resumes_with_accumulated_tokens(self, run):
        async def body():
            inner = FlakyEngine(fail_times=1)
            migration = Migration(inner, migration_limit=3)
            outs = [o async for o in migration.generate(_request())]
            tokens = [t for o in outs for t in o.token_ids]
            # first attempt: 100,101,102 (then died); second: 200,201,202,999
            assert tokens == [100, 101, 102, 200, 201, 202, 999]
            assert outs[-1].finish_reason == "stop"
            # The replayed request must carry the prior output tokens in its
            # prompt and as prior_output_tokens, with max_tokens reduced.
            replay = inner.seen_requests[1]
            assert replay.token_ids == [1, 2, 3, 100, 101, 102]
            assert replay.prior_output_tokens == [100, 101, 102]
            assert replay.sampling.max_tokens == 10 - 3

        run(body())

    def test_migration_limit_yields_error(self, run):
        async def body():
            inner = FlakyEngine(fail_times=10)
            migration = Migration(inner, migration_limit=2)
            outs = [o async for o in migration.generate(_request(max_tokens=100))]
            assert outs[-1].finish_reason == "error"
            assert "migration limit" in outs[-1].error
            assert inner.attempts == 3  # initial + 2 retries

        run(body())

    def test_in_band_migrate_signal_retries(self, run):
        """A worker finishing a stream with finish_reason='migrate' (elastic
        reshard eviction) must be retried like a broken stream — and the
        migrate marker must never reach the client."""

        class ReshardingEngine(TokenEngine):
            def __init__(self):
                self.attempts = 0
                self.seen_requests = []

            async def generate(self, request):
                self.attempts += 1
                self.seen_requests.append(request)
                if self.attempts == 1:
                    yield EngineOutput(token_ids=[7])
                    yield EngineOutput(finish_reason="migrate",
                                       error="elastic reshard")
                    return
                yield EngineOutput(token_ids=[8], finish_reason="stop")

        async def body():
            inner = ReshardingEngine()
            migration = Migration(inner, migration_limit=3)
            outs = [o async for o in migration.generate(_request())]
            tokens = [t for o in outs for t in o.token_ids]
            assert tokens == [7, 8]
            assert all(o.finish_reason != "migrate" for o in outs)
            assert inner.seen_requests[1].token_ids == [1, 2, 3, 7]

        run(body())

    def test_budget_exhausted_during_retries(self, run):
        async def body():
            inner = FlakyEngine(fail_times=10, per_attempt=5)
            migration = Migration(inner, migration_limit=5)
            outs = [o async for o in migration.generate(_request(max_tokens=10))]
            tokens = [t for o in outs for t in o.token_ids]
            # two attempts of 5 tokens each exhaust max_tokens=10 -> length
            assert len(tokens) == 10
            assert outs[-1].finish_reason == "length"

        run(body())


class TestMigrationAccounting:
    def test_replay_prompt_tokens_not_inflated(self, run):
        """A replayed request's prompt embeds the tokens already generated
        (and already billed as completion); the worker reports the raw
        length, and Migration must subtract prior_output_tokens so usage
        accounting stays at the original prompt size."""

        class AccountingFlaky(TokenEngine):
            def __init__(self, fail_times):
                self.fail_times = fail_times
                self.attempts = 0

            async def generate(self, request):
                self.attempts += 1
                yield EngineOutput(token_ids=[100 * self.attempts],
                                   prompt_tokens=len(request.token_ids))
                if self.attempts <= self.fail_times:
                    raise ConnectionLost("worker died")
                yield EngineOutput(token_ids=[999], finish_reason="stop")

        async def body():
            inner = AccountingFlaky(fail_times=1)
            migration = Migration(inner, migration_limit=3)
            outs = [o async for o in migration.generate(_request())]
            reported = [o.prompt_tokens for o in outs
                        if o.prompt_tokens is not None]
            # attempt 1 sees the 3-token prompt; the replay sees 4 raw
            # (3 prompt + 1 prior output) and must report 3
            assert reported == [3, 3]

        run(body())

    def test_migration_limit_honors_registry_knob(self, run, monkeypatch):
        """The ModelWatcher builds Migration(engine, migration_limit=
        env("DYNT_MIGRATION_LIMIT")); the knob must bound the retries."""
        monkeypatch.setenv("DYNT_MIGRATION_LIMIT", "1")
        from dynamo_tpu.runtime.config import env

        async def body():
            inner = FlakyEngine(fail_times=10)
            migration = Migration(
                inner, migration_limit=env("DYNT_MIGRATION_LIMIT"))
            outs = [o async for o in
                    migration.generate(_request(max_tokens=100))]
            assert outs[-1].finish_reason == "error"
            assert inner.attempts == 2  # initial + 1 retry

        run(body())


class TestCooperativeMigration:
    """Cooperative (worker-initiated, in-band finish_reason='migrate')
    migrations carry their own bound — DYNT_PREEMPT_MIGRATION_LIMIT —
    and never consume the failure budget (docs/multi-tenancy.md
    preemption ladder), nor pay backoff jitter."""

    class PreemptingEngine(TokenEngine):
        """Emits `migrates` cooperative migrate frames (one per
        attempt), then completes; optionally also drops the connection
        `fails` times after that."""

        def __init__(self, migrates: int, fails: int = 0):
            self.migrates = migrates
            self.fails = fails
            self.attempts = 0

        async def generate(self, request):
            self.attempts += 1
            yield EngineOutput(token_ids=[self.attempts])
            if self.attempts <= self.migrates:
                yield EngineOutput(finish_reason="migrate",
                                   error="preempted under interactive "
                                         "pressure")
                return
            if self.attempts <= self.migrates + self.fails:
                raise ConnectionLost("worker died")
            yield EngineOutput(token_ids=[999], finish_reason="stop")

    def test_cooperative_bound_is_separate_from_failure_bound(self, run):
        async def body():
            # 3 cooperative migrations exceed migration_limit=1 but fit
            # cooperative_limit=5: the stream must complete.
            inner = self.PreemptingEngine(migrates=3)
            migration = Migration(inner, migration_limit=1,
                                  cooperative_limit=5)
            outs = [o async for o in
                    migration.generate(_request(max_tokens=50))]
            assert outs[-1].finish_reason == "stop"
            assert inner.attempts == 4
            # ...and the failure budget is still fully available after
            # the cooperative replays: one failure + one clean retry.
            inner2 = self.PreemptingEngine(migrates=2, fails=1)
            migration2 = Migration(inner2, migration_limit=1,
                                   cooperative_limit=5)
            outs2 = [o async for o in
                     migration2.generate(_request(max_tokens=50))]
            assert outs2[-1].finish_reason == "stop"
            assert inner2.attempts == 4  # 2 coop + 1 failure + final

        run(body())

    def test_cooperative_limit_bounds_replays(self, run):
        async def body():
            inner = self.PreemptingEngine(migrates=10)
            migration = Migration(inner, migration_limit=3,
                                  cooperative_limit=2)
            outs = [o async for o in
                    migration.generate(_request(max_tokens=50))]
            assert outs[-1].finish_reason == "error"
            assert "migration limit" in outs[-1].error
            assert inner.attempts == 3  # initial + 2 cooperative

        run(body())

    def test_cooperative_replay_skips_backoff(self, run):
        async def body():
            inner = self.PreemptingEngine(migrates=2)
            migration = Migration(inner, migration_limit=3,
                                  cooperative_limit=5)
            calls = []

            class _CountingPolicy:
                def next_delay(self, prev):
                    calls.append(prev)
                    return 99.0

            migration.policy = _CountingPolicy()
            import time

            t0 = time.monotonic()
            outs = [o async for o in
                    migration.generate(_request(max_tokens=50))]
            assert outs[-1].finish_reason == "stop"
            # The jitter policy was never consulted and nothing slept.
            assert calls == []
            assert time.monotonic() - t0 < 1.0

        run(body())

    def test_cooperative_limit_honors_registry_knob(self, run,
                                                    monkeypatch):
        monkeypatch.setenv("DYNT_PREEMPT_MIGRATION_LIMIT", "1")

        async def body():
            inner = self.PreemptingEngine(migrates=10)
            migration = Migration(inner, migration_limit=3)
            outs = [o async for o in
                    migration.generate(_request(max_tokens=50))]
            assert outs[-1].finish_reason == "error"
            assert inner.attempts == 2  # initial + 1 cooperative

        run(body())

    def test_tokens_preserved_across_cooperative_replay(self, run):
        async def body():
            inner = self.PreemptingEngine(migrates=1)
            migration = Migration(inner, migration_limit=0,
                                  cooperative_limit=3)
            outs = [o async for o in
                    migration.generate(_request(max_tokens=50))]
            tokens = [t for o in outs for t in o.token_ids]
            assert tokens == [1, 2, 999]
            assert all(o.finish_reason != "migrate" for o in outs)

        run(body())
