"""Weight service (GMS analog), peer weight streaming (ModelExpress
analog), and the snapshot startup protocol (CRIU analog) — ref surface:
lib/gpu_memory_service, README ModelExpress, deploy/snapshot +
components snapshot.py."""

import asyncio
import multiprocessing
import os
import time
import uuid

import numpy as np
import pytest

import jax

from dynamo_tpu.engine import RunnerConfig, TpuWorker
from dynamo_tpu.models import get_config, init_params
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
from dynamo_tpu.runtime.snapshot import SnapshotController
from dynamo_tpu.weights import WeightClient, serve_in_process
from dynamo_tpu.weights.client import flatten_params, unflatten_like
from dynamo_tpu.weights.streaming import ParamAssembler, encode_param_chunks


def _params():
    return init_params(jax.random.PRNGKey(1), get_config("tiny-test"))


class TestWeightService:
    def test_store_fetch_roundtrip(self, tmp_path):
        sock = str(tmp_path / "w.sock")
        server = serve_in_process(sock)
        try:
            client = WeightClient(sock)
            assert client.ping()
            params = _params()
            client.store("m:1", params)
            models = client.list()
            assert len(models) == 1 and models[0]["complete"]
            flat = client.fetch("m:1")
            rebuilt = unflatten_like(params, flat)
            for (k1, a), (k2, b) in zip(flatten_params(params),
                                        flatten_params(rebuilt)):
                assert k1 == k2
                np.testing.assert_array_equal(a, b)
            client.delete("m:1")
            assert client.fetch("m:1") is None
        finally:
            server.stop()

    def test_worker_crash_survival(self, tmp_path):
        """Weights published by one 'worker' survive its death: a second
        client (the restarted worker) re-attaches them — the GMS value
        proposition."""
        sock = str(tmp_path / "w.sock")
        server = serve_in_process(sock)
        try:
            params = _params()
            # worker #1 publishes, then "crashes" (client object discarded)
            WeightClient(sock).store("m:x", params)
            # worker #2 (fresh restart) re-attaches instead of initializing
            got, from_service = WeightClient(sock).load_or_init(
                "m:x", params, init_fn=lambda: pytest.fail("should not init"))
            assert from_service
            np.testing.assert_array_equal(
                np.asarray(params["embed"]), np.asarray(got["embed"]))
        finally:
            server.stop()

    def test_load_or_init_falls_back_and_publishes(self, tmp_path):
        sock = str(tmp_path / "w.sock")
        server = serve_in_process(sock)
        try:
            client = WeightClient(sock)
            params = _params()
            got, from_service = client.load_or_init(
                "m:y", params, init_fn=lambda: params)
            assert not from_service
            # second call now hits the service
            _, from_service2 = client.load_or_init(
                "m:y", params, init_fn=lambda: pytest.fail("should not init"))
            assert from_service2
        finally:
            server.stop()

    def test_service_down_is_graceful(self, tmp_path):
        client = WeightClient(str(tmp_path / "nope.sock"), timeout=1.0)
        assert not client.ping()
        assert client.fetch("m") is None
        params = _params()
        got, from_service = client.load_or_init("m", params,
                                                init_fn=lambda: params)
        assert not from_service and got is params

    def test_separate_process_server(self, tmp_path):
        """The real deployment shape: the service is its own PROCESS; a
        client in this process stores, another fetches."""
        sock = str(tmp_path / "proc.sock")

        def serve():
            from dynamo_tpu.weights.service import WeightServiceServer

            WeightServiceServer(sock).serve_forever()

        proc = multiprocessing.Process(target=serve, daemon=True)
        proc.start()
        try:
            client = WeightClient(sock)
            for _ in range(100):
                if client.ping():
                    break
                time.sleep(0.05)
            assert client.ping()
            arr = {"a": np.arange(100, dtype=np.float32).reshape(10, 10)}
            client.store("k", arr)
            got = WeightClient(sock).fetch("k")
            np.testing.assert_array_equal(got["a"], arr["a"])
        finally:
            proc.terminate()
            proc.join(timeout=5)


class TestParamStreaming:
    def test_chunk_roundtrip(self):
        flat = flatten_params(_params())
        assembler = ParamAssembler()
        for frame in encode_param_chunks(flat):
            assembler.add(frame)
        assert assembler.complete
        for key, arr in flat:
            np.testing.assert_array_equal(assembler.params[key],
                                          np.asarray(arr))

    def test_multi_chunk_param(self):
        import dynamo_tpu.weights.streaming as streaming

        old = streaming.STREAM_CHUNK_BYTES
        streaming.STREAM_CHUNK_BYTES = 64
        try:
            flat = [("big", np.arange(1000, dtype=np.float32))]
            frames = list(encode_param_chunks(flat))
            assert len(frames) > 1
            assembler = ParamAssembler()
            for frame in reversed(frames):  # out-of-order safe
                assembler.add(frame)
            assert assembler.complete
            np.testing.assert_array_equal(assembler.params["big"], flat[0][1])
        finally:
            streaming.STREAM_CHUNK_BYTES = old

    def test_worker_pulls_from_live_peer(self, run, mem_runtime_config,
                                         monkeypatch):
        """ModelExpress analog E2E: a cold worker pulls weights from a live
        replica and ends up with identical parameters. Striping is forced
        off so this keeps covering the single-peer stream rung (the striped
        rung has its own E2E in test_faststart.py)."""
        monkeypatch.setenv("DYNT_WEIGHT_STRIPE", "0")

        async def body():
            cluster = uuid.uuid4().hex
            rt_a = await DistributedRuntime(
                mem_runtime_config(cluster)).start()
            ns = uuid.uuid4().hex
            cfg = RunnerConfig(page_size=4, num_pages=64, max_batch=4,
                               max_pages_per_seq=16, prefill_buckets=(8, 16))
            worker_a = TpuWorker(rt_a, model_name="tiny-test", namespace=ns,
                                 runner_config=cfg, warmup=False)
            await worker_a.start()
            rt_b = await DistributedRuntime(
                mem_runtime_config(cluster)).start()
            worker_b = TpuWorker(rt_b, model_name="tiny-test", namespace=ns,
                                 runner_config=cfg, warmup=False,
                                 weights_from_peer=True)
            await worker_b.start()
            assert worker_b.weights_source == "peer"
            np.testing.assert_array_equal(
                np.asarray(worker_a.runner.params["embed"]),
                np.asarray(worker_b.runner.params["embed"]))
            await worker_b.close()
            await worker_a.close()
            await rt_b.shutdown()
            await rt_a.shutdown()

        run(body(), timeout=180)


class TestSnapshotController:
    def test_modes_and_markers(self, run, tmp_path):
        with pytest.raises(ValueError):
            SnapshotController(mode="bogus")
        off = SnapshotController(mode="off", directory=str(tmp_path))
        assert not off.enabled

        ctl = SnapshotController(mode="dump", directory=str(tmp_path / "s"))
        assert ctl.enabled
        ctl.engine_ready()
        assert os.path.exists(ctl.ready_path)
        assert open(ctl.ready_path).read() == str(os.getpid())

        async def body():
            waiter = asyncio.create_task(ctl.wait_for_restore(poll=0.01))
            await asyncio.sleep(0.05)
            assert not waiter.done()  # gated until the marker appears
            with open(ctl.restore_path, "w") as f:
                f.write("go")
            await asyncio.wait_for(waiter, 5)

        run(body(), timeout=30)
        # A stale restore marker must not leak into the next run: a fresh
        # ready signal clears it (else wait_for_restore returns instantly
        # and the dump captures open sockets).
        assert os.path.exists(ctl.restore_path)
        ctl.engine_ready()
        assert not os.path.exists(ctl.restore_path)
        ctl.clear()
        assert not os.path.exists(ctl.ready_path)

    def test_snapshot_gated_worker_startup(self, run, mem_runtime_config,
                                           tmp_path):
        """Full protocol: prepare with NO runtime, ready marker, restore,
        then serve with a fresh runtime — and the worker actually serves."""

        async def body():
            ns = uuid.uuid4().hex
            ctl = SnapshotController(mode="dump",
                                     directory=str(tmp_path / "snap"))
            cfg = RunnerConfig(page_size=4, num_pages=64, max_batch=4,
                               max_pages_per_seq=16, prefill_buckets=(8, 16))
            worker = TpuWorker(None, model_name="tiny-test", namespace=ns,
                               runner_config=cfg, warmup=False)
            await worker.prepare()
            ctl.engine_ready()
            # "snapshotter" restores immediately
            with open(ctl.restore_path, "w") as f:
                f.write("go")
            await ctl.wait_for_restore(poll=0.01)
            # Clones of a dumped process must not share identity.
            old_id = worker.instance_id
            worker.rederive_identity()
            assert worker.instance_id != old_id
            assert worker.events.worker_id == worker.instance_id
            rt = await DistributedRuntime(mem_runtime_config()).start()
            worker.runtime = rt
            await worker.serve()

            from dynamo_tpu.llm.protocols import (
                EngineOutput,
                PreprocessedRequest,
                SamplingOptions,
                StopConditions,
            )

            client = (rt.namespace(ns).component("backend")
                      .endpoint("generate").client())
            await client.wait_for_instances(1, timeout=10)
            req = PreprocessedRequest(
                request_id=uuid.uuid4().hex, token_ids=list(range(8)),
                sampling=SamplingOptions(max_tokens=3, temperature=0.0),
                stop=StopConditions(ignore_eos=True),
            ).to_wire()
            outs = [EngineOutput.from_wire(o)
                    async for o in client.direct(req, worker.instance_id)]
            assert sum(len(o.token_ids) for o in outs) == 3
            await worker.close()
            await rt.shutdown()

        run(body(), timeout=180)


class TestWorkerWeightService:
    def test_worker_restart_uses_service(self, run, mem_runtime_config,
                                         tmp_path):
        """Worker #1 initializes + publishes; 'restarted' worker #2 attaches
        from the service and produces identical weights."""
        sock = str(tmp_path / "ws.sock")
        server = serve_in_process(sock)

        async def body():
            ns = uuid.uuid4().hex
            cfg = RunnerConfig(page_size=4, num_pages=64, max_batch=4,
                               max_pages_per_seq=16, prefill_buckets=(8, 16))
            rt1 = await DistributedRuntime(mem_runtime_config()).start()
            w1 = TpuWorker(rt1, model_name="tiny-test", namespace=ns,
                           runner_config=cfg, warmup=False,
                           weight_service=sock)
            await w1.start()
            assert w1.weights_source == "init"
            embed1 = np.asarray(w1.runner.params["embed"])
            await w1.close()
            await rt1.shutdown()  # worker "crashes"

            rt2 = await DistributedRuntime(mem_runtime_config()).start()
            w2 = TpuWorker(rt2, model_name="tiny-test", namespace=ns,
                           runner_config=cfg, warmup=False,
                           weight_service=sock)
            await w2.start()
            assert w2.weights_source == "service"
            np.testing.assert_array_equal(
                embed1, np.asarray(w2.runner.params["embed"]))
            await w2.close()
            await rt2.shutdown()

        try:
            run(body(), timeout=180)
        finally:
            server.stop()
