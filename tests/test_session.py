"""Session tier: cache_control wire surface, PinLedger/SessionStore
bounds, TinyLFU-in-indexer admission, KVBM pin leases, and the
end-to-end cached-turn path (docs/prompt-caching.md)."""

import asyncio
import uuid

import numpy as np
import pytest

from dynamo_tpu.kv_router.indexer import RadixTree
from dynamo_tpu.kv_router.protocols import KvCacheStored, RouterEvent
from dynamo_tpu.llm import ModelDeploymentCard, OpenAIPreprocessor
from dynamo_tpu.session.store import PinLedger, SessionStore, SessionTier
from dynamo_tpu.session.wire import (
    MAX_ANCHORS,
    extract_cache_control,
    parse_ttl,
    resolve_anchor_tokens,
    session_id_of,
    strip_cache_control,
)


def _card(**kwargs):
    return ModelDeploymentCard(name="test-model", context_length=4096,
                               **kwargs)


# -- wire parsing -----------------------------------------------------------


class TestWireParsing:
    def test_parse_ttl_forms(self):
        assert parse_ttl(120) == 120.0
        assert parse_ttl("45") == 45.0
        assert parse_ttl("5m") == 300.0
        assert parse_ttl("2h") == 7200.0
        assert parse_ttl("1.5m") == 90.0
        assert parse_ttl(None) is None
        assert parse_ttl("soon") is None
        assert parse_ttl(0) is None
        assert parse_ttl(True) is None

    def test_message_level_marker(self):
        body = {"messages": [
            {"role": "system", "content": "sys",
             "cache_control": {"type": "ephemeral"}},
            {"role": "user", "content": "hi"},
        ]}
        assert extract_cache_control(body) == [(0, None)]

    def test_content_part_marker(self):
        body = {"messages": [
            {"role": "user", "content": [
                {"type": "text", "text": "big context"},
                {"type": "text", "text": "tail",
                 "cache_control": {"type": "ephemeral", "ttl": "2m"}},
            ]},
            {"role": "user", "content": "follow-up"},
        ]}
        assert extract_cache_control(body) == [(0, 120.0)]

    def test_top_level_marker_anchors_last_message(self):
        body = {"cache_control": {"type": "ephemeral"},
                "messages": [{"role": "user", "content": "a"},
                             {"role": "user", "content": "b"}]}
        assert extract_cache_control(body) == [(1, None)]

    def test_anthropic_system_block_marker(self):
        body = {"system": [{"type": "text", "text": "instructions",
                            "cache_control": {"type": "ephemeral"}}],
                "messages": [{"role": "user", "content": "hi"}]}
        assert extract_cache_control(body) == [(-1, None)]

    def test_anchor_cap_keeps_longest(self):
        body = {"messages": [
            {"role": "user", "content": str(i),
             "cache_control": {"type": "ephemeral"}}
            for i in range(MAX_ANCHORS + 3)
        ]}
        anchors = extract_cache_control(body)
        assert len(anchors) == MAX_ANCHORS
        # Longest prefixes survive the cap.
        assert [i for i, _ in anchors] == list(
            range(3, MAX_ANCHORS + 3))

    def test_non_ephemeral_marker_ignored(self):
        body = {"messages": [{"role": "user", "content": "x",
                              "cache_control": {"type": "permanent"}}]}
        assert extract_cache_control(body) == []

    def test_strip_removes_every_marker(self):
        body = {
            "model": "m", "session_id": "s1",
            "cache_control": {"type": "ephemeral"},
            "system": [{"type": "text", "text": "sys",
                        "cache_control": {"type": "ephemeral"}}],
            "messages": [
                {"role": "user", "cache_control": {"type": "ephemeral"},
                 "content": [{"type": "text", "text": "a",
                              "cache_control": {"type": "ephemeral"}}]},
            ],
        }
        clean = strip_cache_control(body)
        assert "cache_control" not in clean and "session_id" not in clean
        assert "cache_control" not in clean["system"][0]
        assert "cache_control" not in clean["messages"][0]
        assert "cache_control" not in clean["messages"][0]["content"][0]
        # Original untouched (strip copies).
        assert "cache_control" in body["messages"][0]

    def test_strip_of_unmarked_body_is_identity(self):
        body = {"model": "m",
                "messages": [{"role": "user", "content": "hi"}]}
        assert strip_cache_control(body) == body

    def test_session_id_header_wins(self):
        body = {"session_id": "from-body"}
        assert session_id_of(body, {"x-dynt-session-id": "from-header"}) \
            == "from-header"
        assert session_id_of(body, {}) == "from-body"
        assert session_id_of({}, {}) is None
        assert len(session_id_of({"session_id": "x" * 999}, {})) == 256


class TestAnchorResolution:
    def test_anchor_is_prefix_of_full_prompt(self):
        pre = OpenAIPreprocessor(_card())
        messages = [{"role": "system", "content": "you are helpful " * 8},
                    {"role": "user", "content": "question one"},
                    {"role": "user", "content": "question two"}]
        full = pre.preprocess_chat({"model": "m", "messages": messages,
                                    "max_tokens": 8})
        anchors = resolve_anchor_tokens(pre, messages, [(0, None), (1, 60.0)],
                                        full.token_ids)
        assert len(anchors) == 2
        (n0, t0), (n1, t1) = anchors
        assert 0 < n0 < n1 < len(full.token_ids)
        assert t1 == 60.0

    def test_marked_request_tokenizes_identically(self):
        """The unpinned-fallback contract: markers change pinning, never
        the token stream the model sees."""
        pre = OpenAIPreprocessor(_card())
        plain = {"model": "m", "max_tokens": 8,
                 "messages": [{"role": "user", "content": "hello there"},
                              {"role": "user", "content": "again"}]}
        marked = {"model": "m", "max_tokens": 8, "session_id": "s",
                  "messages": [{"role": "user", "content": "hello there",
                                "cache_control": {"type": "ephemeral"}},
                               {"role": "user", "content": "again"}]}
        clean = strip_cache_control(marked)
        assert pre.preprocess_chat(clean).token_ids == \
            pre.preprocess_chat(plain).token_ids


# -- pin ledger -------------------------------------------------------------


class TestPinLedger:
    def test_pin_and_ttl_expiry(self):
        led = PinLedger(max_blocks=100)
        lid = led.pin([1, 2, 3], ttl=10.0, now=0.0)
        assert lid is not None
        assert led.pinned(2)
        assert led.expire(now=5.0) == []
        released = led.expire(now=10.0)
        assert sorted(released) == [1, 2, 3]
        assert not led.pinned(2) and led.lease_count() == 0

    def test_idempotent_repin_refreshes(self):
        led = PinLedger(max_blocks=100)
        led.pin([1, 2], ttl=10.0, lease_id="L", now=0.0)
        led.pin([1, 2], ttl=10.0, lease_id="L", now=8.0)
        assert led.lease_count() == 1 and led.block_count() == 2
        assert led.expire(now=12.0) == []  # refreshed past the old expiry
        assert sorted(led.expire(now=18.0)) == [1, 2]

    def test_shared_prefix_refcounted(self):
        led = PinLedger(max_blocks=100)
        led.pin([1, 2], ttl=100.0, lease_id="A", now=0.0)
        led.pin([1, 2, 3], ttl=100.0, lease_id="B", now=0.0)
        assert led.unpin("A") is True
        # 1,2 still covered by B.
        assert led.pinned(1) and led.pinned(2)
        assert led.unpin("B") is True
        assert led.block_count() == 0

    def test_lease_growth_same_id_swaps_atomically(self):
        led = PinLedger(max_blocks=100)
        led.pin([1, 2], ttl=100.0, lease_id="L", now=0.0)
        led.pin([1, 2, 3, 4], ttl=100.0, lease_id="L", now=1.0)
        assert led.lease_count() == 1
        assert led.pinned(4) and led.pinned(1)
        led.unpin("L")
        assert led.block_count() == 0

    def test_cap_refusal(self):
        led = PinLedger(max_blocks=3)
        assert led.pin([1, 2, 3], ttl=10.0, now=0.0) is not None
        assert led.pin([4], ttl=10.0, now=0.0) is None  # refused
        # Same blocks never count twice.
        assert led.pin([1, 2], ttl=10.0, now=0.0) is not None

    def test_ttl_clamped_to_system_ceiling(self, monkeypatch):
        monkeypatch.setenv("DYNT_PIN_TTL_SECS", "50")
        led = PinLedger(max_blocks=10)
        led.pin([1], ttl=10_000.0, lease_id="L", now=0.0)
        assert led.expire(now=49.0) == []
        assert led.expire(now=50.0) == [1]

    def test_release_hook_fires_once(self):
        released = []
        led = PinLedger(max_blocks=10, on_release=released.extend)
        led.pin([1, 2], ttl=10.0, lease_id="A", now=0.0)
        led.pin([2, 3], ttl=10.0, lease_id="B", now=0.0)
        led.unpin("A")
        assert released == [1]  # 2 still held by B
        led.expire(now=10.0)
        assert sorted(released) == [1, 2, 3]


# -- session store ----------------------------------------------------------


class TestSessionStore:
    def test_affinity_roundtrip_and_ttl(self):
        st = SessionStore(max_sessions=100, shards=4, ttl_secs=60.0)
        st.touch("s1", worker_id=7, now=0.0)
        assert st.get("s1", now=30.0).worker_id == 7
        assert st.get("s1", now=100.0) is None  # idle expiry

    def test_cap_with_tinylfu_admission(self):
        st = SessionStore(max_sessions=4, shards=1, ttl_secs=0.0)
        for i in range(4):
            st.touch(f"hot{i}", now=0.0)
        # Heat the residents.
        for _ in range(3):
            for i in range(4):
                st.touch(f"hot{i}", now=1.0)
        # A cold one-shot session cannot displace a hot one...
        assert st.touch("cold", now=2.0) is None
        assert st.evicted["rejected"] == 1
        # ...but a repeat visitor earns admission (doorkeeper, then
        # frequency parity with the LRU victim).
        entry = None
        for _ in range(8):
            entry = st.touch("persistent", now=3.0)
            if entry is not None:
                break
        assert entry is not None
        assert len(st) == 4

    def test_remove_worker_clears_residency_only(self):
        st = SessionStore(max_sessions=10, shards=2, ttl_secs=0.0)
        st.touch("s1", worker_id=5, prefix_hashes=[1, 2], now=0.0)
        assert st.remove_worker_id(5) == 1
        entry = st.get("s1", now=0.0)
        assert entry.worker_id is None
        assert entry.prefix_hashes == (1, 2)

    def test_bounded_across_shards(self):
        st = SessionStore(max_sessions=64, shards=8, ttl_secs=0.0)
        for i in range(1000):
            st.touch(f"s{i}", now=float(i))
        assert len(st) <= 64


# -- session tier (pin + reconcile) ----------------------------------------


def _tier(**kwargs) -> SessionTier:
    defaults = dict(
        store=SessionStore(max_sessions=1000, shards=2, ttl_secs=600.0),
        ledger=PinLedger(max_blocks=1000), mono_offset=0.0)
    defaults.update(kwargs)
    return SessionTier("test-model", block_size=16, **defaults)


class _Req:
    """Minimal PreprocessedRequest stand-in for register_request."""

    def __init__(self, token_ids, session_id=None):
        self.token_ids = token_ids
        self.session_id = session_id
        self.cache_anchors = []

    def kv_salt(self):
        return None


class TestSessionTier:
    def test_register_floors_to_full_blocks(self):
        tier = _tier()
        req = _Req(list(range(100)), session_id="s1")
        pinned = tier.register_request(req, [(40, None), (90, None)],
                                       now=0.0)
        # 90 tokens -> 5 full blocks of 16.
        assert len(pinned) == 5
        assert tier.ledger.lease_count() == 2  # 40-token + 90-token anchors
        assert tier.store.get("s1", now=0.0).prefix_hashes == tuple(pinned)

    def test_sub_block_anchor_pins_nothing(self):
        tier = _tier()
        assert tier.register_request(_Req(list(range(100))), [(15, None)],
                                     now=0.0) == []
        assert tier.ledger.lease_count() == 0

    def test_idempotent_repin_same_turn(self):
        tier = _tier()
        req = _Req(list(range(64)), session_id="s1")
        tier.register_request(req, [(64, None)], now=0.0)
        tier.register_request(req, [(64, None)], now=1.0)
        assert tier.ledger.lease_count() == 1

    def test_replicas_converge_through_events(self):
        a, b = _tier(origin="a"), _tier(origin="b")
        req = _Req(list(range(64)), session_id="s1")
        a.register_request(req, [(64, "100")], now=0.0)
        a.observe_routed("s1", worker_id=3, now=0.0)
        for payload in a.drain_events():
            assert b.apply_event(payload, now=0.5)
        assert b.ledger.pinned_set() == a.ledger.pinned_set()
        assert b.residency("s1", now=1.0) == 3
        # Self-echoes are filtered.
        b2 = _tier(origin="a")
        req2 = _Req(list(range(32)), session_id="s2")
        b2.register_request(req2, [(32, None)], now=0.0)
        for payload in b2.drain_events():
            assert b2.apply_event(payload) is False

    def test_expired_pin_event_not_applied(self):
        a, b = _tier(origin="a"), _tier(origin="b")
        a.register_request(_Req(list(range(32)), session_id="s"),
                           [(32, "10")], now=0.0)
        events = a.drain_events()
        pin_events = [e for e in events if e["op"] == "pin"]
        assert pin_events
        assert b.apply_event(pin_events[0], now=100.0) is False
        assert b.ledger.lease_count() == 0

    def test_lease_always_dies_at_ttl(self):
        tier = _tier()
        req = _Req(list(range(64)), session_id="s1")
        tier.register_request(req, [(64, "30")], now=0.0)
        assert tier.ledger.lease_count() == 1
        tier.sweep(now=31.0)
        assert tier.ledger.lease_count() == 0
        assert tier.ledger.block_count() == 0


# -- TinyLFU in the radix indexer ------------------------------------------


def _stored(worker_id, event_id, hashes, parent=None):
    return RouterEvent(worker_id=worker_id, event_id=event_id,
                       stored=KvCacheStored(block_hashes=hashes,
                                            parent_hash=parent))


class TestIndexerAdmission:
    def test_node_cap_held_exactly(self):
        tree = RadixTree(max_tree_size=32, admission=True)
        eid = 0
        for i in range(100):
            eid += 1
            tree.apply_event(_stored(1, eid, [1000 + i]))
        assert tree.total_nodes() <= 32

    def test_hot_prefix_survives_cold_flood(self):
        tree = RadixTree(max_tree_size=16, admission=True)
        hot = list(range(1, 9))
        eid = 0
        for h in hot:
            eid += 1
            tree.apply_event(_stored(1, eid, [h]))
        for _ in range(50):  # frequency evidence
            for h in hot:
                tree.find_matches([h])
        for i in range(200):  # one-shot flood
            eid += 1
            tree.apply_event(_stored(1, eid, [5000 + i]))
        assert tree.admission_rejected > 0
        for h in hot:
            assert tree.find_matches([h]).scores, f"hot {h} evicted"

    def test_equal_evidence_rotates_oldest_first(self):
        tree = RadixTree(max_tree_size=4, admission=True)
        eid = 0
        for i in range(4):
            eid += 1
            tree.apply_event(_stored(1, eid, [10 + i]))
        # All cold (doorkeeper only): a fresh candidate with equal
        # evidence displaces the OLDEST entry (>= admits).
        eid += 1
        tree.apply_event(_stored(1, eid, [99]))
        assert tree.total_nodes() <= 4
        assert tree.find_matches([99]).scores
        assert not tree.find_matches([10]).scores  # oldest went

    def test_rejected_chain_truncates_not_corrupts(self):
        tree = RadixTree(max_tree_size=4, admission=True)
        eid = 0
        hot = [1, 2]
        for h in hot:
            eid += 1
            tree.apply_event(_stored(1, eid, [h]))
        for _ in range(40):
            for h in hot:
                tree.find_matches([h])
        eid += 1
        tree.apply_event(_stored(1, eid, [50, 51, 52, 53, 54]))
        # Whatever was admitted, matching is contiguous-from-root.
        scores = tree.find_matches([50, 51, 52, 53, 54])
        depth = max(scores.scores.values(), default=0)
        assert 0 <= depth <= 5
        assert tree.total_nodes() <= 4

    def test_hot_chain_not_wiped_and_no_orphans(self):
        """Review regression: at the cap, extending a hot chain with a
        cold block must neither wipe the chain (every evicted victim
        gets its own frequency check) nor insert the new node under a
        pruned parent (orphans are unmatchable forever)."""
        tree = RadixTree(max_tree_size=4, admission=True)
        tree.apply_event(_stored(1, 1, [1, 2, 3, 4]))
        for _ in range(50):
            tree.find_matches([1, 2, 3, 4])
        tree.apply_event(_stored(1, 2, [5], parent=4))
        # Cold candidate: the hot chain survives intact.
        assert max(tree.find_matches([1, 2, 3, 4]).scores.values()) == 4
        # Nothing unreachable squats in the node map (orphan guard).
        reachable = set()
        stack = [tree._root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                reachable.add(child.hash)
                stack.append(child)
        assert set(tree._nodes) == reachable
        assert tree.total_nodes() <= 4

    def test_cold_chain_eviction_never_orphans(self):
        """All-cold variant: the admission cascade may prune the very
        parent the chain extends — the insert must truncate, leaving
        only root-reachable nodes."""
        tree = RadixTree(max_tree_size=4, admission=True)
        tree.apply_event(_stored(1, 1, [1, 2, 3, 4]))
        tree.apply_event(_stored(1, 2, [5], parent=4))
        reachable = set()
        stack = [tree._root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                reachable.add(child.hash)
                stack.append(child)
        assert set(tree._nodes) == reachable
        assert tree.total_nodes() <= 4

    def test_admission_off_keeps_legacy_prune_path(self):
        tree = RadixTree(max_tree_size=8)  # no admission
        eid = 0
        for i in range(20):
            eid += 1
            tree.apply_event(_stored(1, eid, [100 + i]))
        evicted = tree.maintain()
        assert tree.total_nodes() <= 8
        assert evicted  # maintain pruned oldest down to target

    def test_frequency_decays_with_sample_window(self):
        # After enough traffic the sketch halves: old heat fades, new
        # entries win again (no permanent incumbency).
        tree = RadixTree(max_tree_size=8, admission=True)
        eid = 0
        for i in range(8):
            eid += 1
            tree.apply_event(_stored(1, eid, [i + 1]))
        for _ in range(30):
            for i in range(8):
                tree.find_matches([i + 1])
        # Massive new-key traffic forces sample resets (touches on
        # lookups + admission attempts).
        for i in range(6000):
            eid += 1
            tree.apply_event(_stored(1, eid, [10_000 + i]))
            tree.find_matches([10_000 + i])
        # Eventually newcomers displace the faded incumbents.
        assert any(tree.find_matches([10_000 + i]).scores
                   for i in range(5900, 6000))


# -- KVBM pin leases --------------------------------------------------------


class TestKvbmPins:
    def _manager(self, tmp_path, host_blocks=4, disk_blocks=0):
        from dynamo_tpu.block_manager import (
            BlockLayoutSpec,
            KvBlockManager,
            KvbmConfig,
        )

        layout = BlockLayoutSpec(n_layers=1, total_kv_heads=1, head_dim=8,
                                 page_size=4, dtype="float32")
        cfg = KvbmConfig(host_blocks=host_blocks, disk_blocks=disk_blocks,
                         disk_path=str(tmp_path / "g3.bin"),
                         admission=False)
        return KvBlockManager(cfg, layout), layout

    def _block(self, layout, fill):
        return np.full(layout.block_shape, fill, np.float32)

    def test_pinned_block_survives_eviction_pressure(self, tmp_path):
        mgr, layout = self._manager(tmp_path)
        for h in range(1, 5):
            mgr._offload_sink(h, self._block(layout, h), None)
        mgr.pin_blocks([1], ttl=100.0, now=0.0)
        for h in range(5, 12):  # pressure: would evict LRU (hash 1)
            mgr._offload_sink(h, self._block(layout, h), None)
        assert mgr.host.contains(1)  # pinned: held against eviction
        assert not mgr.host.contains(2)  # unpinned LRU went

    def test_lease_dies_at_ttl(self, tmp_path):
        mgr, layout = self._manager(tmp_path)
        mgr._offload_sink(1, self._block(layout, 1), None)
        mgr.pin_blocks([1], ttl=50.0, now=0.0)
        assert mgr.pinned_blocks() == 1
        mgr.sweep_pins(now=51.0)
        assert mgr.pinned_blocks() == 0
        for h in range(2, 12):
            mgr._offload_sink(h, self._block(layout, h), None)
        assert not mgr.host.contains(1)  # evictable again

    def test_pin_ahead_attaches_on_offload(self, tmp_path):
        mgr, layout = self._manager(tmp_path)
        mgr.pin_blocks([7], ttl=100.0, now=0.0)  # not tiered yet
        mgr._offload_sink(7, self._block(layout, 7), None)
        for h in range(20, 30):
            mgr._offload_sink(h, self._block(layout, h), None)
        assert mgr.host.contains(7)

    def test_repin_refreshes_expiry(self, tmp_path):
        mgr, layout = self._manager(tmp_path)
        mgr._offload_sink(1, self._block(layout, 1), None)
        mgr.pin_blocks([1], ttl=50.0, now=0.0)
        mgr.pin_blocks([1], ttl=50.0, now=40.0)
        mgr.sweep_pins(now=60.0)  # original expiry passed; refreshed holds
        assert mgr.pinned_blocks() == 1
        mgr.sweep_pins(now=91.0)
        assert mgr.pinned_blocks() == 0

    def test_prefetch_promotes_disk_to_host(self, tmp_path):
        mgr, layout = self._manager(tmp_path, host_blocks=8, disk_blocks=8)
        try:
            mgr.disk.insert(42, self._block(layout, 42))
            assert not mgr.host.contains(42)
            mgr.prefetch([42])
            for _ in range(100):
                if mgr.host.contains(42):
                    break
                import time

                time.sleep(0.02)
            assert mgr.host.contains(42)
        finally:
            mgr.close()


# -- end-to-end over HTTP ---------------------------------------------------


def _cfg(cluster):
    from dynamo_tpu.runtime import RuntimeConfig

    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = cluster
    cfg.request_plane = "tcp"
    cfg.tcp_host = "127.0.0.1"
    cfg.event_plane = "mem"
    cfg.system_enabled = False
    cfg.lease_ttl_secs = 1.0
    return cfg


async def _setup(cluster, n_workers=1, router_mode="kv",
                 model="mock-model"):
    from dynamo_tpu.frontend import Frontend
    from dynamo_tpu.mocker import MockerConfig, MockerWorker
    from dynamo_tpu.runtime import DistributedRuntime

    workers = []
    for _ in range(n_workers):
        rt = await DistributedRuntime(_cfg(cluster)).start()
        worker = MockerWorker(
            rt, model_name=model,
            config=MockerConfig(speedup_ratio=500.0, num_blocks=512),
            load_publish_interval=0.1,
        )
        await worker.start()
        workers.append((rt, worker))
    frt = await DistributedRuntime(_cfg(cluster)).start()
    frontend = Frontend(frt, host="127.0.0.1", port=0,
                        router_mode=router_mode)
    await frontend.start()
    for _ in range(100):
        if frontend.manager.get(model) is not None:
            break
        await asyncio.sleep(0.05)
    return frontend, frt, workers


async def _teardown(frontend, frt, workers):
    await frontend.close()
    await frt.shutdown()
    for rt, worker in workers:
        await worker.close()
        await rt.shutdown()


async def _chat(port, body, headers=None):
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.post(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                json=body, headers=headers or {}) as resp:
            return resp.status, await resp.json()


class TestHttpSessionE2E:
    def test_marked_chat_pins_and_unmarked_does_not(self, run):
        async def body():
            frontend, frt, workers = await _setup(uuid.uuid4().hex)
            try:
                entry = frontend.manager.get("mock-model")
                long_text = "context " * 120  # > 1 block of tokens
                status, _ = await _chat(frontend.port, {
                    "model": "mock-model", "max_tokens": 4,
                    "messages": [
                        {"role": "user", "content": long_text,
                         "cache_control": {"type": "ephemeral"}}],
                })
                assert status == 200
                assert entry.session.ledger.lease_count() == 1
                assert entry.session.ledger.block_count() > 0
                before = entry.session.ledger.lease_count()
                status, _ = await _chat(frontend.port, {
                    "model": "mock-model", "max_tokens": 4,
                    "messages": [{"role": "user", "content": long_text}],
                })
                assert status == 200
                # Unmarked request pinned nothing new.
                assert entry.session.ledger.lease_count() == before
            finally:
                await _teardown(frontend, frt, workers)

        run(body())

    def test_idempotent_repin_over_http(self, run):
        async def body():
            frontend, frt, workers = await _setup(uuid.uuid4().hex)
            try:
                entry = frontend.manager.get("mock-model")
                req = {
                    "model": "mock-model", "max_tokens": 4,
                    "session_id": "sess-1",
                    "messages": [
                        {"role": "user", "content": "repeat " * 120,
                         "cache_control": {"type": "ephemeral"}}],
                }
                for _ in range(3):
                    status, _ = await _chat(frontend.port, req)
                    assert status == 200
                assert entry.session.ledger.lease_count() == 1
            finally:
                await _teardown(frontend, frt, workers)

        run(body())

    def test_messages_endpoint_system_marker(self, run):
        async def body():
            import aiohttp

            frontend, frt, workers = await _setup(uuid.uuid4().hex)
            try:
                entry = frontend.manager.get("mock-model")
                async with aiohttp.ClientSession() as session:
                    async with session.post(
                            f"http://127.0.0.1:{frontend.port}/v1/messages",
                            json={
                                "model": "mock-model", "max_tokens": 4,
                                "system": [
                                    {"type": "text",
                                     "text": "instructions " * 120,
                                     "cache_control": {
                                         "type": "ephemeral"}}],
                                "messages": [{"role": "user",
                                              "content": "hi"}],
                            },
                            headers={"x-dynt-session-id": "anth-1"},
                    ) as resp:
                        assert resp.status == 200
                assert entry.session.ledger.lease_count() == 1
                assert entry.session.store.get("anth-1") is not None
            finally:
                await _teardown(frontend, frt, workers)

        run(body())

    def test_session_disabled_falls_back(self, run, monkeypatch):
        monkeypatch.setenv("DYNT_SESSION_ENABLE", "0")

        async def body():
            frontend, frt, workers = await _setup(uuid.uuid4().hex)
            try:
                entry = frontend.manager.get("mock-model")
                assert entry.session is None
                status, _ = await _chat(frontend.port, {
                    "model": "mock-model", "max_tokens": 4,
                    "session_id": "s",
                    "messages": [
                        {"role": "user", "content": "hello",
                         "cache_control": {"type": "ephemeral"}}],
                })
                # Markers are inert, not 400s.
                assert status == 200
            finally:
                await _teardown(frontend, frt, workers)

        run(body())

    def test_cached_turn_routes_to_resident_worker(self, run):
        """Acceptance: turn 2 of a pinned session lands on the worker
        holding turn 1's KV, its TTFT path hits the prefix cache
        (mocker prefill ledger), and the flight recorder carries the
        session event + dynamo_session_* counters move."""

        async def body():
            from dynamo_tpu.runtime import metrics as rt_metrics
            from dynamo_tpu.runtime.flight_recorder import get_recorder

            frontend, frt, workers = await _setup(uuid.uuid4().hex,
                                                  n_workers=2)
            try:
                entry = frontend.manager.get("mock-model")
                hits0 = rt_metrics.SESSION_AFFINITY.labels(
                    outcome="hit")._value.get()
                long_text = "conversation context " * 80
                headers = {"x-dynt-session-id": "agent-42"}
                status, reply = await _chat(frontend.port, {
                    "model": "mock-model", "max_tokens": 4,
                    "messages": [
                        {"role": "user", "content": long_text,
                         "cache_control": {"type": "ephemeral"}}],
                }, headers)
                assert status == 200
                resident = entry.session.store.get("agent-42").worker_id
                assert resident is not None
                by_id = {w.instance_id: w for _, w in workers}
                prefill_before = by_id[resident].engine.prefill_tokens_total
                # Wait for the worker's KV events to land in the radix
                # index (the cached-turn TTFT path needs the overlap).
                await asyncio.sleep(0.3)
                turn2 = {
                    "model": "mock-model", "max_tokens": 4,
                    "messages": [
                        {"role": "user", "content": long_text},
                        {"role": "assistant",
                         "content": reply["choices"][0]["message"]
                         ["content"]},
                        {"role": "user", "content": "short follow-up",
                         "cache_control": {"type": "ephemeral"}}],
                }
                status, _ = await _chat(frontend.port, turn2, headers)
                assert status == 200
                # Residency held: turn 2 landed on the same worker.
                assert entry.session.store.get(
                    "agent-42").worker_id == resident
                hits1 = rt_metrics.SESSION_AFFINITY.labels(
                    outcome="hit")._value.get()
                assert hits1 == hits0 + 1
                # Prefix-cache hit: the resident worker prefilled far
                # fewer tokens than turn 2's full prompt (most of it
                # was turn 1's cached blocks).
                turn2_tokens = len(entry.preprocessor.preprocess_chat(
                    {k: v for k, v in turn2.items()
                     if k != "session_id"}).token_ids)
                prefill_delta = (by_id[resident].engine.prefill_tokens_total
                                 - prefill_before)
                assert 0 < prefill_delta < turn2_tokens * 0.7
                # Flight recorder: both turns carry the session event.
                snap = get_recorder().snapshot()
                session_events = [
                    ev for t in (snap.get("completed", [])
                                 + snap.get("inflight", []))
                    for ev in t.get("events", [])
                    if ev.get("event") == "session"]
                assert session_events
                # Pins recorded for both anchors of the conversation.
                assert entry.session.ledger.block_count() > 0
            finally:
                await _teardown(frontend, frt, workers)

        run(body())


class TestEventDedupeMemoryBound:
    """At-least-once delivery dedupe must stay bounded on a LONG-LIVED
    replica pair: entries die with each event's own absolute expiry and
    each origin's window is capped at DYNT_FED_DEDUPE_MAX — a
    federation streaming events for weeks must not grow the window
    monotonically (docs/federation.md)."""

    def test_long_lived_pair_window_stays_bounded(self, monkeypatch):
        monkeypatch.setenv("DYNT_PIN_TTL_SECS", "5")
        a, b = _tier(origin="a"), _tier(origin="b")
        peak = 0
        for r in range(200):
            t = float(r)
            a.observe_routed(f"s{r}", worker_id=1, now=t)
            for payload in a.drain_events():
                assert b.apply_event(payload, now=t)
            b.sweep(t)
            peak = max(peak, b.dedupe_entries())
        # 200 events applied; only ~one TTL's worth may be remembered.
        assert peak <= 8
        assert b.dedupe_entries() <= 8
        # The origin's emptied window itself is dropped once idle.
        b.sweep(1000.0)
        assert b.dedupe_entries() == 0
        assert b._applied == {}

    def test_redelivery_dropped_and_counted(self):
        a, b = _tier(origin="a"), _tier(origin="b")
        a.observe_routed("dup", worker_id=2, now=100.0)
        events = a.drain_events()
        assert events
        for payload in events:
            assert b.apply_event(dict(payload), now=100.0)
        before = b.duplicates_dropped
        for payload in events:
            assert b.apply_event(dict(payload), now=101.0) is False
        assert b.duplicates_dropped == before + len(events)

    def test_origin_window_capped(self, monkeypatch):
        monkeypatch.setenv("DYNT_FED_DEDUPE_MAX", "8")
        a, b = _tier(origin="a"), _tier(origin="b")
        for i in range(30):
            a.observe_routed(f"c{i}", worker_id=1, now=50.0)
        for payload in a.drain_events():
            b.apply_event(payload, now=50.0)
        assert b.dedupe_entries() <= 8

    def test_snapshot_apply_is_idempotent(self):
        a, b = _tier(origin="a"), _tier(origin="b")
        a.register_request(_Req(list(range(64)), session_id="s1"),
                           [(64, "100")], now=0.0)
        a.observe_routed("s1", worker_id=7, now=0.0)
        a.drain_events()
        snap = a.snapshot_events(now=1.0)
        assert snap
        for payload in snap:
            b.apply_event(payload, now=1.0)
        pinned = b.ledger.pinned_set()
        assert pinned == a.ledger.pinned_set()
        assert b.residency("s1", now=2.0) == 7
        # The resync rung may re-apply the same snapshot: no growth,
        # duplicates land in the window.
        for payload in snap:
            assert b.apply_event(payload, now=2.0) is False
        assert b.ledger.pinned_set() == pinned
