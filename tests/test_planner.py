"""Planner tests: predictors, interpolators, scaling math, budget clamp,
load-based regression, metrics parsing, virtual connector (ref test areas:
tests/planner/ + planner unit behavior in planner_core.py)."""

import asyncio
import math

import numpy as np
import pytest

from dynamo_tpu.planner import (
    ArPredictor,
    CallbackConnector,
    ConstantPredictor,
    DecodeInterpolator,
    FrontendScraper,
    ItlEstimator,
    KalmanPredictor,
    LoadBasedPlanner,
    LoadEventSource,
    PdSplitPlanner,
    PhaseBreakdown,
    PhaseBreakdownSource,
    PlannerConfig,
    PrefillInterpolator,
    SeasonalPredictor,
    SlaPlanner,
    TrafficStats,
    TtftEstimator,
    VirtualConnector,
    apply_chip_budget,
    make_predictor,
    parse_prometheus_text,
    save_decode_profile,
    save_prefill_profile,
)


class TestPredictors:
    def test_constant(self):
        p = ConstantPredictor()
        for v in (0, 0, 5, 8):
            p.add_data_point(v)
        assert p.predict_next() == 8

    def test_leading_idle_skipped(self):
        p = ConstantPredictor()
        p.add_data_point(0)
        p.add_data_point(0)
        assert p.data_buffer == []
        p.add_data_point(3)
        p.add_data_point(0)  # post-traffic zero IS recorded
        assert p.data_buffer == [3.0, 0.0]

    def test_ar_tracks_linear_trend(self):
        p = ArPredictor()
        for t in range(20):
            p.add_data_point(10 + 2 * t)
        pred = p.predict_next()
        assert 45 <= pred <= 55  # next true value is 50

    def test_ar_constant_guard(self):
        p = ArPredictor()
        for _ in range(10):
            p.add_data_point(7.0)
        assert p.predict_next() == 7.0

    def test_kalman_tracks_trend(self):
        p = KalmanPredictor()
        for t in range(30):
            p.add_data_point(100 + 5 * t)
        pred = p.predict_next()
        assert 230 <= pred <= 260  # next true value 250

    def test_seasonal(self):
        p = SeasonalPredictor(period=4)
        pattern = [10, 20, 30, 40]
        for _ in range(3):
            for v in pattern:
                p.add_data_point(v)
        # next position in cycle is pattern[0]
        assert abs(p.predict_next() - 10) < 5

    def test_nan_treated_as_zero(self):
        p = ConstantPredictor()
        p.add_data_point(5)
        p.add_data_point(float("nan"))
        assert p.data_buffer[-1] == 0.0

    def test_registry(self):
        assert isinstance(make_predictor("arima"), ArPredictor)
        with pytest.raises(ValueError):
            make_predictor("nope")


def _prefill_profile(tmp_path):
    isl = np.array([128, 512, 1024, 4096])
    ttft = np.array([20.0, 60.0, 120.0, 500.0])
    thpt = np.array([8000.0, 7000.0, 6000.0, 4000.0])  # tokens/s/chip
    save_prefill_profile(str(tmp_path), isl, ttft, thpt)
    return PrefillInterpolator(str(tmp_path))


def _decode_profile(tmp_path):
    # grid of kv_usage x context; itl grows with kv usage
    kv = np.tile(np.linspace(0.1, 1.0, 10), 3)
    ctx = np.repeat([256, 1024, 4096], 10)
    itl = 5.0 + 40.0 * kv + ctx / 1024.0
    thpt = 2000.0 * kv / (1 + ctx / 4096.0)
    save_decode_profile(str(tmp_path), kv, ctx, itl, thpt,
                        max_kv_tokens=100_000)
    return DecodeInterpolator(str(tmp_path))


class TestInterpolators:
    def test_prefill_interp_clamps_and_interpolates(self, tmp_path):
        interp = _prefill_profile(tmp_path)
        assert interp.interpolate_ttft(128) == pytest.approx(20.0)
        mid = interp.interpolate_ttft(768)
        assert 60.0 < mid < 120.0
        assert interp.interpolate_ttft(99999) == pytest.approx(500.0)
        assert interp.interpolate_thpt_per_chip(128) == pytest.approx(8000.0)

    def test_decode_interp_monotone_itl_in_kv(self, tmp_path):
        interp = _decode_profile(tmp_path)
        low = interp.interpolate_itl(concurrency=10, context_length=1024)
        high = interp.interpolate_itl(concurrency=90, context_length=1024)
        assert high > low

    def test_find_best_thpt_respects_itl(self, tmp_path):
        interp = _decode_profile(tmp_path)
        thpt, itl, kv = interp.find_best_throughput_per_chip(
            itl=25.0, context_length=1024)
        assert itl <= 25.0 + 1e-6
        # tighter SLA -> lower operating kv load -> lower throughput
        thpt2, itl2, kv2 = interp.find_best_throughput_per_chip(
            itl=15.0, context_length=1024)
        assert kv2 <= kv and thpt2 <= thpt + 1e-9

    def test_reference_key_aliases(self, tmp_path):
        raw = {
            "prefill_isl": [100, 200], "prefill_ttft": [10, 20],
            "prefill_thpt_per_gpu": [100.0, 90.0],  # reference key name
        }
        interp = PrefillInterpolator(raw_data={k: np.asarray(v)
                                               for k, v in raw.items()})
        assert interp.interpolate_thpt_per_chip(100) == pytest.approx(100.0)


class TestScalingMath:
    def _planner(self, tmp_path, **cfg_kw):
        cfg = PlannerConfig(adjustment_interval=60.0, ttft_ms=200.0,
                            itl_ms=30.0, no_correction=True, **cfg_kw)
        applied = {}
        conn = CallbackConnector(lambda c, n: applied.__setitem__(c, n))
        pl = SlaPlanner(cfg, conn,
                        prefill_interpolator=_prefill_profile(tmp_path / "p"),
                        decode_interpolator=_decode_profile(tmp_path / "d"))
        return pl, applied

    def test_scale_up_with_load(self, tmp_path):
        pl, _ = self._planner(tmp_path)
        low = pl.plan(TrafficStats(num_req=30, ttft_ms=50, itl_ms=10,
                                   isl=512, osl=128,
                                   request_duration_s=2.0))
        high = pl.plan(TrafficStats(num_req=3000, ttft_ms=50, itl_ms=10,
                                    isl=512, osl=128,
                                    request_duration_s=2.0))
        assert low is not None and high is not None
        assert high[0] >= low[0] and high[1] >= low[1]
        assert high[0] > 1  # real prefill scale-out at 3000 req/min

    def test_no_traffic_skips(self, tmp_path):
        pl, _ = self._planner(tmp_path)
        assert pl.plan(TrafficStats()) is None
        assert pl.plan(TrafficStats(num_req=0, ttft_ms=1, itl_ms=1,
                                    isl=10, osl=10,
                                    request_duration_s=1)) is None

    def test_correction_factor_shrinks_prefill_estimate(self, tmp_path):
        # observed TTFT much better than profile -> correction < 1 ->
        # fewer prefill replicas needed
        pl, _ = self._planner(tmp_path)
        pl.config.no_correction = False
        pl.state.num_d_workers = 1
        stats = TrafficStats(num_req=2000, ttft_ms=30.0, itl_ms=10,
                             isl=512, osl=128, request_duration_s=2.0)
        fast = pl.plan(stats)
        assert pl.state.p_correction < 1.0
        pl2, _ = self._planner(tmp_path)
        base = pl2.plan(stats)  # no correction
        assert fast[0] <= base[0]

    def test_budget_clamp(self):
        cfg = PlannerConfig(max_chip_budget=8, prefill_engine_num_chips=2,
                            decode_engine_num_chips=2, min_endpoint=1)
        p, d = apply_chip_budget(4, 4, cfg)  # wants 16 chips, budget 8
        assert p * 2 + d * 2 <= 8
        assert p >= 1 and d >= 1

    def test_budget_unlimited(self):
        cfg = PlannerConfig(max_chip_budget=0)
        assert apply_chip_budget(7, 9, cfg) == (7, 9)

    def test_budget_below_minimum(self):
        cfg = PlannerConfig(max_chip_budget=1, prefill_engine_num_chips=2,
                            decode_engine_num_chips=2, min_endpoint=1)
        assert apply_chip_budget(3, 3, cfg) == (0, 0)

    def test_budget_aggregated_gives_all_to_decode(self):
        """Regression: num_p=0 (aggregated) must not reserve prefill chips
        or zero out decode when budget < prefill+decode minimum."""
        cfg = PlannerConfig(max_chip_budget=5, prefill_engine_num_chips=1,
                            decode_engine_num_chips=1, min_endpoint=1)
        assert apply_chip_budget(0, 10, cfg) == (0, 5)
        cfg2 = PlannerConfig(max_chip_budget=1, prefill_engine_num_chips=2,
                             decode_engine_num_chips=1, min_endpoint=1)
        assert apply_chip_budget(0, 2, cfg2) == (0, 1)


class TestGoodputLoop:
    """Goodput-fed planning (ROADMAP item 4): SLO-good ratio + the
    flight-recorder phase breakdown steer the plan beyond raw-load math,
    with scale-down hysteresis so transients don't thrash replicas."""

    def _planner(self, tmp_path, **cfg_kw):
        cfg = PlannerConfig(adjustment_interval=60.0, ttft_ms=200.0,
                            itl_ms=30.0, no_correction=True,
                            goodput_target=0.9, **cfg_kw)
        conn = CallbackConnector(lambda c, n: None)
        return SlaPlanner(
            cfg, conn,
            prefill_interpolator=_prefill_profile(tmp_path / "p"),
            decode_interpolator=_decode_profile(tmp_path / "d"))

    def _stats(self, good, total, **kw):
        base = dict(num_req=30, ttft_ms=50, itl_ms=10, isl=512, osl=128,
                    request_duration_s=2.0, slo_good=good, slo_total=total)
        base.update(kw)
        return TrafficStats(**base)

    def test_goodput_violation_scales_bottleneck_pool(self, tmp_path):
        pl = self._planner(tmp_path)
        healthy = pl.plan(self._stats(98, 100))
        pl2 = self._planner(tmp_path)
        # Same raw load, collapsed goodput, decode burn dominant.
        burn = PhaseBreakdown(queue_ms=10, prefill_ms=10, decode_ms=500,
                              samples=8)
        violated = pl2.plan(self._stats(30, 100), breakdown=burn)
        assert violated[1] > healthy[1]

    def test_prefill_burn_scales_prefill_pool(self, tmp_path):
        pl = self._planner(tmp_path)
        healthy = pl.plan(self._stats(98, 100))
        pl2 = self._planner(tmp_path)
        burn = PhaseBreakdown(queue_ms=400, prefill_ms=300, decode_ms=50,
                              samples=8)
        violated = pl2.plan(self._stats(30, 100), breakdown=burn)
        assert violated[0] > healthy[0]

    def test_goodput_ratio_and_shed_fraction(self):
        stats = self._stats(60, 100, shed=25.0)
        assert stats.goodput_ratio() == pytest.approx(0.6)
        assert stats.shed_fraction() == pytest.approx(0.2)
        assert TrafficStats(num_req=1).goodput_ratio() is None
        assert TrafficStats(num_req=1).shed_fraction() is None

    def test_scale_down_needs_hysteresis_streak(self, tmp_path):
        pl = self._planner(tmp_path, hysteresis_intervals=2)
        big = self._stats(98, 100, num_req=3000)
        small = self._stats(98, 100, num_req=30)
        first = pl.plan(big)
        assert first is not None and sum(first) > 2
        # One quiet interval: the shrink is WANTED but suppressed.
        held = pl.plan(small)
        assert held == first
        # A second consecutive quiet interval applies it.
        applied = pl.plan(small)
        assert sum(applied) < sum(first)

    def test_scale_up_applies_immediately(self, tmp_path):
        pl = self._planner(tmp_path, hysteresis_intervals=3)
        small = pl.plan(self._stats(98, 100, num_req=30))
        up = pl.plan(self._stats(98, 100, num_req=3000))
        assert sum(up) > sum(small)

    def test_hysteresis_never_exceeds_chip_budget(self, tmp_path):
        """Regression: a held shrink next to an immediate grow (the
        rebalance case) must not push the applied decision past the
        chip budget — the post-hysteresis re-clamp."""
        pl = self._planner(tmp_path, max_chip_budget=4,
                           hysteresis_intervals=2)
        pl.state.last_decision = (2, 2)
        burn = PhaseBreakdown(queue_ms=400, prefill_ms=300, decode_ms=50,
                              samples=8)
        for _ in range(4):
            out = pl.plan(self._stats(30, 100, num_req=5000),
                          breakdown=burn)
            assert out is not None
            assert out[0] + out[1] <= 4, out

    def test_binding_budget_rebalances_pd_ratio(self, tmp_path):
        pl = self._planner(tmp_path, max_chip_budget=4,
                           hysteresis_intervals=1)
        burn = PhaseBreakdown(queue_ms=400, prefill_ms=300, decode_ms=50,
                              samples=8)
        # Heavy load + bad goodput: the budget clamps the scale-up away,
        # so chips shift toward the prefill bottleneck instead.
        out = pl.plan(self._stats(30, 100, num_req=5000), breakdown=burn)
        assert out is not None
        p, d = out
        assert p + d <= 4
        assert p >= 2  # the ratio moved toward prefill


class TestScraperGoodputSeries:
    def test_absent_good_series_reads_zero_not_nan(self, monkeypatch):
        """Regression: with traffic flowing but ZERO SLO-good requests
        (overloaded restart), the good counter series does not exist —
        that must read as goodput 0, not 'unknown', or the control loop
        is inert in exactly the regime it exists for."""
        scraper = FrontendScraper("http://unused/metrics", "m")
        base = ('dynamo_requests_total{status="ok"} %d\n'
                'dynamo_time_to_first_token_seconds_sum{model="m"} %f\n'
                'dynamo_time_to_first_token_seconds_count{model="m"} %d\n'
                'dynamo_inter_token_latency_seconds_sum{model="m"} %f\n'
                'dynamo_inter_token_latency_seconds_count{model="m"} %d\n'
                'dynamo_input_sequence_tokens_sum{model="m"} %d\n'
                'dynamo_input_sequence_tokens_count{model="m"} %d\n'
                'dynamo_output_sequence_tokens_sum{model="m"} %d\n'
                'dynamo_output_sequence_tokens_count{model="m"} %d\n'
                'dynamo_slo_requests_total{model="m"} %d\n')
        pages = [base % (0, 0.0, 0, 0.0, 0, 0, 0, 0, 0, 0),
                 base % (10, 20.0, 10, 0.5, 10, 5120, 10, 640, 10, 10)]
        monkeypatch.setattr(scraper, "_fetch",
                            lambda: parse_prometheus_text(pages.pop(0)))
        assert scraper.scrape() is None  # baseline
        stats = scraper.scrape()
        assert stats.slo_total == 10
        assert stats.slo_good == 0.0
        assert stats.shed == 0.0
        assert stats.goodput_ratio() == 0.0

    def test_nan_goodput_does_not_poison_load_based_gate(self):
        cfg = PlannerConfig(goodput_target=0.9)
        conn = CallbackConnector(lambda c, n: None)
        pl = LoadBasedPlanner(cfg, conn, LoadEventSource())
        pl.observe_goodput(float("nan"), 10)
        assert pl._goodput_ratio is None
        assert pl.plan_decode(2) == 2


class TestPhaseBreakdown:
    def test_burn_classification(self):
        src = PhaseBreakdownSource("http://unused/debug/requests")
        snap = {"completed": [
            {"request_id": "a", "phases": {
                "received": 100.0, "prefill_start": 100.4,
                "first_token": 100.5, "finished": 100.9}},
            {"request_id": "b", "phases": {
                "received": 200.0, "first_token": 200.2,
                "finished": 200.4}},
        ]}
        out = src.ingest(snap)
        assert out.samples == 2
        assert out.queue_ms == pytest.approx((400 + 200) / 2, rel=0.01)
        assert out.prefill_ms == pytest.approx(50, rel=0.01)
        assert out.decode_ms == pytest.approx((400 + 200) / 2, rel=0.01)

    def test_ingest_dedups_across_intervals(self):
        src = PhaseBreakdownSource("http://unused/debug/requests")
        snap = {"completed": [{"request_id": "a", "phases": {
            "received": 1.0, "first_token": 1.5, "finished": 2.0}}]}
        assert src.ingest(snap).samples == 1
        assert src.ingest(snap).samples == 0  # already seen

    def test_bottleneck_verdict(self):
        assert PhaseBreakdown(queue_ms=300, prefill_ms=100,
                              decode_ms=200).bottleneck() == "prefill"
        assert PhaseBreakdown(queue_ms=10, prefill_ms=10,
                              decode_ms=200).bottleneck() == "decode"


class TestPdSplitPlanner:
    def test_converges_to_argmax(self):
        pl = PdSplitPlanner(switch_margin=0.05)
        pl.observe(1, 3, 10.0)
        pl.observe(2, 2, 16.0)
        pl.observe(3, 1, 13.0)
        assert pl.best() == (2, 2)
        assert pl.decisions  # the switch was recorded

    def test_hysteresis_keeps_incumbent_within_margin(self):
        pl = PdSplitPlanner(switch_margin=0.10)
        pl.observe(2, 2, 10.0)  # incumbent
        pl.observe(1, 3, 10.5)  # 5% better: inside the switch margin
        assert pl.best() == (2, 2)
        pl.observe(1, 3, 14.0)  # EMA pulls it decisively ahead
        assert pl.best() == (1, 3)

    def test_ema_smooths_noise(self):
        pl = PdSplitPlanner(switch_margin=0.05, ema_alpha=0.5)
        pl.observe(2, 2, 10.0)
        pl.observe(1, 3, 2.0)   # one terrible sample
        pl.observe(1, 3, 30.0)  # one great sample -> EMA 16
        assert pl.scores[(1, 3)] == pytest.approx(16.0)


class TestLoadBasedGoodput:
    def test_violated_goodput_forces_growth_and_vetoes_shrink(self):
        cfg = PlannerConfig(goodput_target=0.9)
        conn = CallbackConnector(lambda c, n: None)
        pl = LoadBasedPlanner(cfg, conn, LoadEventSource())
        # No estimator data at all: goodput alone drives the verdict.
        pl.observe_goodput(50, 100)
        assert pl.plan_decode(2) == 3
        pl.observe_goodput(99, 100)
        assert pl.plan_decode(2) == 2

    def test_no_goodput_signal_leaves_decision_alone(self):
        cfg = PlannerConfig()
        conn = CallbackConnector(lambda c, n: None)
        pl = LoadBasedPlanner(cfg, conn, LoadEventSource())
        assert pl.plan_decode(2) == 2


class TestLoadBased:
    def test_regressions_learn_linear_model(self):
        est = TtftEstimator()
        for tokens in range(100, 2100, 100):
            est.observe_step(tokens, 1.0 + 0.01 * tokens)  # 10us/token
        est.observe_isl(1000)
        # 3000 queued + 1000 isl at 2048/chunk -> 2 chunks
        ttft = est.estimate_next_ttft_ms(3000, 2048)
        expect = (1.0 + 0.01 * 2048) + (1.0 + 0.01 * (4000 - 2048))
        assert ttft == pytest.approx(expect, rel=0.05)

    def test_itl_estimator(self):
        est = ItlEstimator()
        for bs in range(1, 20):
            est.observe_step(bs, 5.0 + 0.5 * bs)
        assert est.estimate_itl_ms(10) == pytest.approx(10.0, rel=0.05)

    def test_scale_up_down_decisions(self):
        cfg = PlannerConfig(itl_ms=20.0, min_endpoint=1,
                            scale_down_sensitivity=0.5)
        src = LoadEventSource()
        pl = LoadBasedPlanner(cfg, CallbackConnector(lambda c, n: None), src)
        # feed steps: heavy load -> wall time above SLA at observed batch
        for i in range(20):
            src.on_event({"worker_id": 1, "dp_rank": 0,
                          "step_wall_ms": 30.0 + i * 0.01,
                          "decode_tokens_in_step": 8,
                          "active_requests": 8})
            pl.ingest()
        assert pl.plan_decode(current_replicas=2) == 3  # all violate
        # light load -> well under SLA * sensitivity
        src.latest.clear()
        pl2 = LoadBasedPlanner(cfg, CallbackConnector(lambda c, n: None), src)
        for i in range(20):
            src.on_event({"worker_id": 1, "dp_rank": 0,
                          "step_wall_ms": 2.0 + i * 0.01,
                          "decode_tokens_in_step": 4,
                          "active_requests": 4})
            pl2.ingest()
        assert pl2.plan_decode(current_replicas=2) == 1


class TestMetricsParsing:
    def test_parse_prometheus_text(self):
        text = """# HELP x y
dynamo_requests_total{namespace="n",status="ok"} 42
dynamo_time_to_first_token_seconds_sum{model="m"} 1.5
dynamo_time_to_first_token_seconds_count{model="m"} 10
"""
        snap = parse_prometheus_text(text)
        assert snap[("dynamo_requests_total",
                     (("namespace", "n"), ("status", "ok")))] == 42
        assert snap[("dynamo_time_to_first_token_seconds_sum",
                     (("model", "m"),))] == 1.5

    def test_scraper_deltas(self, monkeypatch):
        pages = [
            # baseline
            'dynamo_requests_total{status="ok"} 10\n'
            'dynamo_time_to_first_token_seconds_sum{model="m"} 1.0\n'
            'dynamo_time_to_first_token_seconds_count{model="m"} 10\n'
            'dynamo_inter_token_latency_seconds_sum{model="m"} 0.5\n'
            'dynamo_inter_token_latency_seconds_count{model="m"} 50\n'
            'dynamo_input_sequence_tokens_sum{model="m"} 1000\n'
            'dynamo_input_sequence_tokens_count{model="m"} 10\n'
            'dynamo_output_sequence_tokens_sum{model="m"} 500\n'
            'dynamo_output_sequence_tokens_count{model="m"} 10\n'
            'dynamo_request_duration_seconds_sum{namespace="n"} 5\n'
            'dynamo_request_duration_seconds_count{namespace="n"} 10\n',
            # after one interval: +5 req, ttft avg 100ms, itl avg 10ms
            'dynamo_requests_total{status="ok"} 15\n'
            'dynamo_time_to_first_token_seconds_sum{model="m"} 1.5\n'
            'dynamo_time_to_first_token_seconds_count{model="m"} 15\n'
            'dynamo_inter_token_latency_seconds_sum{model="m"} 1.0\n'
            'dynamo_inter_token_latency_seconds_count{model="m"} 100\n'
            'dynamo_input_sequence_tokens_sum{model="m"} 2000\n'
            'dynamo_input_sequence_tokens_count{model="m"} 15\n'
            'dynamo_output_sequence_tokens_sum{model="m"} 1000\n'
            'dynamo_output_sequence_tokens_count{model="m"} 15\n'
            'dynamo_request_duration_seconds_sum{namespace="n"} 10\n'
            'dynamo_request_duration_seconds_count{namespace="n"} 15\n',
        ]
        scraper = FrontendScraper("http://unused/metrics", "m")
        it = iter(pages)
        monkeypatch.setattr(scraper, "_fetch",
                            lambda: parse_prometheus_text(next(it)))
        assert scraper.scrape() is None  # baseline
        stats = scraper.scrape()
        assert stats.num_req == 5
        assert stats.ttft_ms == pytest.approx(100.0)
        assert stats.itl_ms == pytest.approx(10.0)
        assert stats.isl == pytest.approx(200.0)
        assert stats.osl == pytest.approx(100.0)
        assert stats.is_valid()


class TestVirtualConnector:
    def test_decision_roundtrip(self, run, mem_runtime_config):
        from dynamo_tpu.planner import TargetReplica
        from dynamo_tpu.runtime import DistributedRuntime

        async def go():
            rt = await DistributedRuntime(mem_runtime_config()).start()
            try:
                conn = VirtualConnector(rt)
                await conn.set_component_replicas(
                    [TargetReplica("backend", 3),
                     TargetReplica("prefill", 2)])
                decision = await conn.read_decision()
                assert decision["targets"] == {"backend": 3, "prefill": 2}
                assert decision["decision_id"] == 1
            finally:
                await rt.shutdown()

        run(go())


class TestMeasuredTimingReplicas:
    """Planner replica math over the MEASURED v5e timing model (mocker
    timing preset -> derived decode profile): the SLA math is validated
    against real step-time physics, not synthetic curves (VERDICT r3
    item 9)."""

    def test_decode_replicas_match_hand_math(self):
        import math

        from dynamo_tpu.mocker.engine import derive_decode_profile

        raw = {k: np.asarray(v)
               for k, v in derive_decode_profile(
                   "tpu-v5e-qwen3-0.6b").items()}
        interp = DecodeInterpolator(raw_data=raw)
        cfg = PlannerConfig(adjustment_interval=60.0, ttft_ms=500.0,
                            itl_ms=5.0, no_correction=True)
        pl = SlaPlanner(cfg, CallbackConnector(lambda c, n: None),
                        decode_interpolator=interp)
        num_req, isl, osl = 3000.0, 512.0, 128.0
        n = pl.compute_num_decode(num_req, isl, osl)
        per_chip, itl, _kv = interp.find_best_throughput_per_chip(
            itl=cfg.itl_ms, context_length=isl + osl / 2)
        expect = max(cfg.min_endpoint,
                     math.ceil(num_req * osl / 60.0 / per_chip))
        assert n == expect
        assert itl <= cfg.itl_ms + 1e-6
        # The measured model bounds per-chip decode throughput around
        # the real chip's capability (bs=32 tops out ~6k tok/s): the
        # planner must not assume fantasy throughput.
        assert 500.0 < per_chip < 8000.0

    def test_tighter_itl_needs_more_replicas(self):
        from dynamo_tpu.mocker.engine import derive_decode_profile

        raw = {k: np.asarray(v)
               for k, v in derive_decode_profile(
                   "tpu-v5e-qwen3-0.6b").items()}

        def replicas(itl_ms):
            cfg = PlannerConfig(adjustment_interval=60.0, ttft_ms=500.0,
                                itl_ms=itl_ms, no_correction=True)
            pl = SlaPlanner(cfg, CallbackConnector(lambda c, n: None),
                            decode_interpolator=DecodeInterpolator(
                                raw_data=raw))
            return pl.compute_num_decode(6000.0, 512.0, 128.0)

        # itl 2.2ms only admits tiny batches on the measured model;
        # relaxed ITL lets bigger batches serve the same load with
        # fewer chips.
        assert replicas(2.2) > replicas(6.0)


class TestPreSweptProfiles:
    """Shipped pre-swept v5e profiles (VERDICT r4 item 10; ref:
    planner/utils/pre_swept_results/): the planner boots with no
    profiling step using in-repo calibrated NPZ data."""

    def test_shipped_profiles_resolve_and_load(self):
        from dynamo_tpu.planner.interpolation import (
            DecodeInterpolator,
            PrefillInterpolator,
            pre_swept_dir,
        )

        for model in ("qwen3-0.6b", "mistral-7b"):
            path = pre_swept_dir(model, "v5e")
            assert path is not None, model
            pre = PrefillInterpolator(path)
            dec = DecodeInterpolator(path)
            # sane, monotone-ish physics: longer ISL never speeds TTFT
            assert pre.interpolate_ttft(512) <= pre.interpolate_ttft(4096)
            assert pre.interpolate_thpt_per_chip(1024) > 0
            itl = dec.interpolate_itl(0.5, 1024)
            thpt = dec.interpolate_thpt_per_chip(0.5, 1024)
            assert itl > 0 and thpt > 0

    def test_calibration_matches_measured_anchor(self):
        """The decode grid passes (near) the measured real-chip anchor
        point — the calibration contract of scripts/gen_pre_swept.py."""
        import numpy as np

        from dynamo_tpu.planner.interpolation import (
            DecodeInterpolator,
            pre_swept_dir,
        )

        path = pre_swept_dir("mistral-7b", "v5e")
        raw = np.load(path + "/decode_raw_data.npz")
        # anchor: bs=8 ctx=256 measured 247.2 tok/s/chip (BASELINE r5).
        # Check the RAW grid rows bracketing the anchor's kv_usage
        # (8*256/max_kv ~ 0.28) at ctx=256 — the calibrated curve must
        # pass near the measured point.
        row = {float(x): float(t) for x, y, t in
               zip(raw["x_kv_usage"], raw["y_context_length"],
                   raw["z_thpt_per_chip"]) if y == 256}
        lo, hi = row[0.2], row[0.35]
        assert lo <= 247.2 <= hi or abs(lo - 247.2) / 247.2 < 0.5, row
        # and the regridded interpolator loads + answers positively
        dec = DecodeInterpolator(path)
        assert dec.interpolate_thpt_per_chip(0.35, 256) > 0

    def test_unknown_model_returns_none(self):
        from dynamo_tpu.planner.interpolation import pre_swept_dir

        assert pre_swept_dir("no-such-model", "v5e") is None


class TestLoadBasedPlannerLoop:
    def test_run_loop_scales_decode_from_events(self, run):
        """The planner CLI's --mode load driver: LoadMetrics events feed
        the estimators and the loop applies the decode target through
        the connector (makes step_wall_ms / *_tokens_in_step /
        active_requests reachable — dynaflow DF302)."""
        applied = []
        cfg = PlannerConfig(adjustment_interval=0.01, itl_ms=20.0,
                            min_endpoint=1, scale_down_sensitivity=0.5)
        src = LoadEventSource()
        pl = LoadBasedPlanner(
            cfg,
            CallbackConnector(lambda c, n: applied.append((c, n)),
                              observe=lambda c: 2),
            src)

        async def body():
            pl.start()
            # a live worker keeps publishing fresh snapshots (replayed
            # stale ones are identity-deduped by ingest)
            for i in range(300):
                src.on_event({"worker_id": 1, "dp_rank": 0,
                              "step_wall_ms": 30.0 + i * 0.01,
                              "decode_tokens_in_step": 8,
                              "active_requests": 8})
                await asyncio.sleep(0.005)
                if applied:
                    break
            await pl.stop()
            assert applied and applied[-1] == (cfg.decode_component, 3)

        run(body())

    def test_dead_worker_snapshot_expires(self):
        """A worker that dies while busy must not pin its last high-load
        snapshot forever (it would block scale-down indefinitely)."""
        src = LoadEventSource(metrics_ttl=0.0)
        src.on_event({"worker_id": 1, "dp_rank": 0, "active_requests": 9})
        assert src.snapshots() == []
        assert src.worker_count() == 0

    def test_stale_snapshot_not_reingested(self):
        cfg = PlannerConfig(itl_ms=20.0)
        src = LoadEventSource()
        pl = LoadBasedPlanner(cfg, CallbackConnector(lambda c, n: None),
                              src)
        src.on_event({"worker_id": 1, "dp_rank": 0, "step_wall_ms": 30.0,
                      "decode_tokens_in_step": 8})
        pl.ingest()
        count = pl.itl_est.reg.num_observations
        pl.ingest()  # same snapshot object: must not observe again
        assert pl.itl_est.reg.num_observations == count
