"""Standalone etcd v3 gateway stub for chaos/fault-injection tests:
`python tests/etcd_stub_server.py PORT` serves tests.test_etcd_discovery.
StubEtcd on a FIXED port until killed — so a test can SIGKILL it
mid-serving and restart an EMPTY one on the same port (the etcd-HA
outage scenario, ref: tests/fault_tolerance/etcd_ha/)."""

import asyncio
import sys


async def main() -> None:
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from tests.test_etcd_discovery import StubEtcd

    stub = StubEtcd()
    await stub.start(port=int(sys.argv[1]))
    print(f"stub etcd up on {stub.port}", flush=True)
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
