"""KServe v2 gRPC frontend E2E against the mocker (ref contract:
lib/llm/src/grpc/service/kserve.rs — GRPCInferenceService next to the
OpenAI HTTP surface)."""

import asyncio
import uuid

import pytest

grpc = pytest.importorskip("grpc")

from dynamo_tpu.llm.kserve import inference_pb2 as pb
from dynamo_tpu.llm.kserve import KServeGrpcService

from tests.test_frontend_e2e import _setup, _teardown

_S = "/inference.GRPCInferenceService/"


def _infer_request(model, text, max_tokens=6, chat=False, rid="r1"):
    req = pb.ModelInferRequest(
        model_name=model, id=rid,
        inputs=[pb.ModelInferRequest.InferInputTensor(
            name="text_input", datatype="BYTES", shape=[1],
            contents=pb.InferTensorContents(bytes_contents=[text.encode()]))],
    )
    req.parameters["max_tokens"].int64_param = max_tokens
    if chat:
        req.parameters["chat"].bool_param = True
    return req


async def _grpc_setup(cluster):
    frontend, frt, workers = await _setup(cluster)
    service = KServeGrpcService(frontend.manager, host="127.0.0.1", port=0)
    await service.start()
    channel = grpc.aio.insecure_channel(f"127.0.0.1:{service.port}")
    return frontend, frt, workers, service, channel


class TestKServeGrpc:
    def test_liveness_metadata_infer(self, run):
        async def body():
            frontend, frt, workers, service, channel = await _grpc_setup(
                uuid.uuid4().hex)
            live = await channel.unary_unary(
                _S + "ServerLive",
                request_serializer=pb.ServerLiveRequest.SerializeToString,
                response_deserializer=pb.ServerLiveResponse.FromString,
            )(pb.ServerLiveRequest())
            assert live.live
            ready = await channel.unary_unary(
                _S + "ModelReady",
                request_serializer=pb.ModelReadyRequest.SerializeToString,
                response_deserializer=pb.ModelReadyResponse.FromString,
            )(pb.ModelReadyRequest(name="mock-model"))
            assert ready.ready
            meta = await channel.unary_unary(
                _S + "ModelMetadata",
                request_serializer=pb.ModelMetadataRequest.SerializeToString,
                response_deserializer=pb.ModelMetadataResponse.FromString,
            )(pb.ModelMetadataRequest(name="mock-model"))
            assert meta.inputs[0].name == "text_input"
            resp = await channel.unary_unary(
                _S + "ModelInfer",
                request_serializer=pb.ModelInferRequest.SerializeToString,
                response_deserializer=pb.ModelInferResponse.FromString,
            )(_infer_request("mock-model", "hello world"))
            text = resp.outputs[0].contents.bytes_contents[0].decode()
            assert len(text) > 0
            await channel.close()
            await service.close()
            await _teardown(frontend, frt, workers)

        run(body(), timeout=90)

    def test_stream_infer_and_unknown_model(self, run):
        async def body():
            frontend, frt, workers, service, channel = await _grpc_setup(
                uuid.uuid4().hex)
            stream = channel.stream_stream(
                _S + "ModelStreamInfer",
                request_serializer=pb.ModelInferRequest.SerializeToString,
                response_deserializer=pb.ModelStreamInferResponse.FromString,
            )
            call = stream()
            await call.write(_infer_request("mock-model", "hi", chat=True))
            await call.done_writing()
            deltas, final_seen = [], False
            async for item in call:
                assert not item.error_message
                out = item.infer_response.outputs[0]
                text = out.contents.bytes_contents[0].decode()
                params = item.infer_response.parameters
                if ("triton_final_response" in params
                        and params["triton_final_response"].bool_param):
                    final_seen = True
                elif text:
                    deltas.append(text)
            assert deltas and final_seen
            # Unknown model -> NOT_FOUND
            try:
                await channel.unary_unary(
                    _S + "ModelInfer",
                    request_serializer=pb.ModelInferRequest.SerializeToString,
                    response_deserializer=pb.ModelInferResponse.FromString,
                )(_infer_request("nope", "hello"))
                raise AssertionError("expected NOT_FOUND")
            except grpc.aio.AioRpcError as exc:
                assert exc.code() == grpc.StatusCode.NOT_FOUND
            await channel.close()
            await service.close()
            await _teardown(frontend, frt, workers)

        run(body(), timeout=90)
