"""Streaming parser tests: reasoning split, tool-call formats, chunk-
boundary jailing, and DeltaGenerator integration (ref: lib/parsers tests +
chat_completions jail behavior)."""

import json

import pytest

from dynamo_tpu.parsers import (
    HermesToolParser,
    Llama3JsonToolParser,
    MistralToolParser,
    PythonicToolParser,
    StreamingReasoningParser,
    make_reasoning_parser,
    make_tool_parser,
)


def _drip(parser, text, n=3):
    """Feed text in n-char chunks; collect reasoning/content or
    content/calls depending on parser type."""
    out = []
    for i in range(0, len(text), n):
        out.append(parser.push(text[i : i + n]))
    out.append(parser.finalize())
    return out


class TestReasoningParser:
    def test_basic_split(self):
        p = StreamingReasoningParser()
        events = _drip(p, "<think>step one</think>the answer")
        reasoning = "".join(e.reasoning for e in events)
        content = "".join(e.content for e in events)
        assert reasoning == "step one"
        assert content == "the answer"

    def test_partial_tag_never_leaks(self):
        """Tags split across chunk boundaries must not appear in output."""
        p = StreamingReasoningParser()
        for n in (1, 2, 3, 5):
            p = StreamingReasoningParser()
            events = _drip(p, "pre<think>mid</think>post", n=n)
            content = "".join(e.content for e in events)
            reasoning = "".join(e.reasoning for e in events)
            assert "<think>" not in content and "</think>" not in content
            assert content == "prepost" and reasoning == "mid"

    def test_unterminated_think_counts_as_reasoning(self):
        p = StreamingReasoningParser()
        events = _drip(p, "<think>ran out of budget")
        assert "".join(e.reasoning for e in events) == "ran out of budget"
        assert "".join(e.content for e in events) == ""

    def test_starts_in_reasoning(self):
        p = make_reasoning_parser("deepseek-r1")
        events = _drip(p, "implicit thought</think>visible")
        assert "".join(e.reasoning for e in events) == "implicit thought"
        assert "".join(e.content for e in events) == "visible"

    def test_unknown_name_raises(self):
        with pytest.raises(ValueError):
            make_reasoning_parser("nope")


class TestHermesParser:
    CALL = '<tool_call>{"name": "get_weather", "arguments": {"city": "SF"}}</tool_call>'

    def test_single_call_with_surrounding_text(self):
        for n in (1, 4, 7, 100):
            p = HermesToolParser()
            events = _drip(p, f"Let me check. {self.CALL} done", n=n)
            calls = [c for e in events for c in e.calls]
            content = "".join(e.content for e in events)
            assert len(calls) == 1
            assert calls[0].name == "get_weather"
            assert json.loads(calls[0].arguments) == {"city": "SF"}
            assert "<tool_call>" not in content
            assert "Let me check." in content and "done" in content

    def test_multiple_calls(self):
        p = HermesToolParser()
        events = _drip(p, self.CALL + self.CALL)
        calls = [c for e in events for c in e.calls]
        assert [c.name for c in calls] == ["get_weather", "get_weather"]

    def test_malformed_json_falls_back_to_content(self):
        p = HermesToolParser()
        events = _drip(p, "<tool_call>not json</tool_call>")
        content = "".join(e.content for e in events)
        assert "not json" in content
        assert not [c for e in events for c in e.calls]


class TestMistralParser:
    def test_array_of_calls(self):
        text = ('thinking [TOOL_CALLS] [{"name": "a", "arguments": {"x": 1}},'
                ' {"name": "b", "arguments": {}}]')
        p = MistralToolParser()
        events = _drip(p, text, n=5)
        calls = [c for e in events for c in e.calls]
        assert [c.name for c in calls] == ["a", "b"]
        assert "".join(e.content for e in events).strip() == "thinking"


class TestLlama3JsonParser:
    def test_whole_message_call(self):
        text = '{"name": "lookup", "parameters": {"q": "tpu"}}'
        p = Llama3JsonToolParser()
        events = _drip(p, text, n=6)
        calls = [c for e in events for c in e.calls]
        assert len(calls) == 1 and calls[0].name == "lookup"
        assert json.loads(calls[0].arguments) == {"q": "tpu"}

    def test_plain_text_passes_through(self):
        p = Llama3JsonToolParser()
        events = _drip(p, "just a normal answer", n=4)
        assert "".join(e.content for e in events) == "just a normal answer"
        assert not [c for e in events for c in e.calls]


class TestPythonicParser:
    def test_call_list(self):
        text = '[get_weather(city="SF"), sum_all(1, 2)]'
        p = PythonicToolParser()
        events = _drip(p, text, n=5)
        calls = [c for e in events for c in e.calls]
        assert [c.name for c in calls] == ["get_weather", "sum_all"]
        assert json.loads(calls[0].arguments) == {"city": "SF"}
        assert json.loads(calls[1].arguments) == {"__positional__": [1, 2]}

    def test_non_call_list_is_content(self):
        p = PythonicToolParser()
        events = _drip(p, "[1, 2, 3] is a list")
        assert not [c for e in events for c in e.calls]
        assert "[1, 2, 3] is a list" == "".join(e.content for e in events)

    def test_registry(self):
        assert isinstance(make_tool_parser("qwen"), HermesToolParser)
        with pytest.raises(ValueError):
            make_tool_parser("bogus")


class TestDeltaGeneratorIntegration:
    def _gen(self, **kw):
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.llm.preprocessor import DeltaGenerator, OpenAIPreprocessor
        from dynamo_tpu.llm.protocols import (
            PreprocessedRequest, SamplingOptions, StopConditions)

        card = ModelDeploymentCard(name="m", tokenizer={"kind": "byte"})
        pre = OpenAIPreprocessor(card)
        req = PreprocessedRequest(
            request_id="r", token_ids=[1, 2], sampling=SamplingOptions(),
            stop=StopConditions(), model="m")
        return DeltaGenerator(pre, req, kind="chat", **kw), pre

    def _feed_text(self, gen, pre, text):
        """Push text as byte tokens through the engine-output path."""
        from dynamo_tpu.llm.protocols import EngineOutput

        tokens = pre.tokenizer.encode(text)
        chunks = []
        for i, t in enumerate(tokens):
            final = i == len(tokens) - 1
            chunks += gen.on_output(EngineOutput(
                token_ids=[t], finish_reason="stop" if final else None))
        return chunks

    def test_reasoning_and_tools_in_stream(self):
        gen, pre = self._gen(tool_parser="hermes", reasoning_parser="think")
        text = ('<think>need weather</think>'
                '<tool_call>{"name": "w", "arguments": {}}</tool_call>')
        chunks = self._feed_text(gen, pre, text)
        reasoning = "".join(
            c["choices"][0]["delta"].get("reasoning_content", "")
            for c in chunks)
        tool_deltas = [c for c in chunks
                       if c["choices"][0]["delta"].get("tool_calls")]
        assert reasoning == "need weather"
        assert len(tool_deltas) == 1
        assert gen.finish_reason == "tool_calls"
        final = gen.final_response()
        msg = final["choices"][0]["message"]
        assert msg["tool_calls"][0]["function"]["name"] == "w"
        assert msg["reasoning_content"] == "need weather"
        assert final["choices"][0]["finish_reason"] == "tool_calls"

    def test_plain_stream_unchanged(self):
        gen, pre = self._gen()
        chunks = self._feed_text(gen, pre, "hello world")
        text = "".join(c["choices"][0]["delta"].get("content", "")
                       for c in chunks)
        assert text == "hello world"
        assert gen.finish_reason == "stop"
