"""Streaming parser tests: reasoning split, tool-call formats, chunk-
boundary jailing, and DeltaGenerator integration (ref: lib/parsers tests +
chat_completions jail behavior)."""

import json

import pytest

from dynamo_tpu.parsers import (
    HermesToolParser,
    Llama3JsonToolParser,
    MistralToolParser,
    PythonicToolParser,
    StreamingReasoningParser,
    make_reasoning_parser,
    make_tool_parser,
)


def _drip(parser, text, n=3):
    """Feed text in n-char chunks; collect reasoning/content or
    content/calls depending on parser type."""
    out = []
    for i in range(0, len(text), n):
        out.append(parser.push(text[i : i + n]))
    out.append(parser.finalize())
    return out


class TestReasoningParser:
    def test_basic_split(self):
        p = StreamingReasoningParser()
        events = _drip(p, "<think>step one</think>the answer")
        reasoning = "".join(e.reasoning for e in events)
        content = "".join(e.content for e in events)
        assert reasoning == "step one"
        assert content == "the answer"

    def test_partial_tag_never_leaks(self):
        """Tags split across chunk boundaries must not appear in output."""
        p = StreamingReasoningParser()
        for n in (1, 2, 3, 5):
            p = StreamingReasoningParser()
            events = _drip(p, "pre<think>mid</think>post", n=n)
            content = "".join(e.content for e in events)
            reasoning = "".join(e.reasoning for e in events)
            assert "<think>" not in content and "</think>" not in content
            assert content == "prepost" and reasoning == "mid"

    def test_unterminated_think_counts_as_reasoning(self):
        p = StreamingReasoningParser()
        events = _drip(p, "<think>ran out of budget")
        assert "".join(e.reasoning for e in events) == "ran out of budget"
        assert "".join(e.content for e in events) == ""

    def test_starts_in_reasoning(self):
        p = make_reasoning_parser("deepseek-r1")
        events = _drip(p, "implicit thought</think>visible")
        assert "".join(e.reasoning for e in events) == "implicit thought"
        assert "".join(e.content for e in events) == "visible"

    def test_unknown_name_raises(self):
        with pytest.raises(ValueError):
            make_reasoning_parser("nope")


class TestHermesParser:
    CALL = '<tool_call>{"name": "get_weather", "arguments": {"city": "SF"}}</tool_call>'

    def test_single_call_with_surrounding_text(self):
        for n in (1, 4, 7, 100):
            p = HermesToolParser()
            events = _drip(p, f"Let me check. {self.CALL} done", n=n)
            calls = [c for e in events for c in e.calls]
            content = "".join(e.content for e in events)
            assert len(calls) == 1
            assert calls[0].name == "get_weather"
            assert json.loads(calls[0].arguments) == {"city": "SF"}
            assert "<tool_call>" not in content
            assert "Let me check." in content and "done" in content

    def test_multiple_calls(self):
        p = HermesToolParser()
        events = _drip(p, self.CALL + self.CALL)
        calls = [c for e in events for c in e.calls]
        assert [c.name for c in calls] == ["get_weather", "get_weather"]

    def test_malformed_json_falls_back_to_content(self):
        p = HermesToolParser()
        events = _drip(p, "<tool_call>not json</tool_call>")
        content = "".join(e.content for e in events)
        assert "not json" in content
        assert not [c for e in events for c in e.calls]


class TestMistralParser:
    def test_array_of_calls(self):
        text = ('thinking [TOOL_CALLS] [{"name": "a", "arguments": {"x": 1}},'
                ' {"name": "b", "arguments": {}}]')
        p = MistralToolParser()
        events = _drip(p, text, n=5)
        calls = [c for e in events for c in e.calls]
        assert [c.name for c in calls] == ["a", "b"]
        assert "".join(e.content for e in events).strip() == "thinking"


class TestLlama3JsonParser:
    def test_whole_message_call(self):
        text = '{"name": "lookup", "parameters": {"q": "tpu"}}'
        p = Llama3JsonToolParser()
        events = _drip(p, text, n=6)
        calls = [c for e in events for c in e.calls]
        assert len(calls) == 1 and calls[0].name == "lookup"
        assert json.loads(calls[0].arguments) == {"q": "tpu"}

    def test_plain_text_passes_through(self):
        p = Llama3JsonToolParser()
        events = _drip(p, "just a normal answer", n=4)
        assert "".join(e.content for e in events) == "just a normal answer"
        assert not [c for e in events for c in e.calls]


class TestPythonicParser:
    def test_call_list(self):
        text = '[get_weather(city="SF"), sum_all(1, 2)]'
        p = PythonicToolParser()
        events = _drip(p, text, n=5)
        calls = [c for e in events for c in e.calls]
        assert [c.name for c in calls] == ["get_weather", "sum_all"]
        assert json.loads(calls[0].arguments) == {"city": "SF"}
        assert json.loads(calls[1].arguments) == {"__positional__": [1, 2]}

    def test_non_call_list_is_content(self):
        p = PythonicToolParser()
        events = _drip(p, "[1, 2, 3] is a list")
        assert not [c for e in events for c in e.calls]
        assert "[1, 2, 3] is a list" == "".join(e.content for e in events)

    def test_registry(self):
        assert isinstance(make_tool_parser("qwen"), HermesToolParser)
        with pytest.raises(ValueError):
            make_tool_parser("bogus")


class TestDeltaGeneratorIntegration:
    def _gen(self, **kw):
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.llm.preprocessor import DeltaGenerator, OpenAIPreprocessor
        from dynamo_tpu.llm.protocols import (
            PreprocessedRequest, SamplingOptions, StopConditions)

        card = ModelDeploymentCard(name="m", tokenizer={"kind": "byte"})
        pre = OpenAIPreprocessor(card)
        req = PreprocessedRequest(
            request_id="r", token_ids=[1, 2], sampling=SamplingOptions(),
            stop=StopConditions(), model="m")
        return DeltaGenerator(pre, req, kind="chat", **kw), pre

    def _feed_text(self, gen, pre, text):
        """Push text as byte tokens through the engine-output path."""
        from dynamo_tpu.llm.protocols import EngineOutput

        tokens = pre.tokenizer.encode(text)
        chunks = []
        for i, t in enumerate(tokens):
            final = i == len(tokens) - 1
            chunks += gen.on_output(EngineOutput(
                token_ids=[t], finish_reason="stop" if final else None))
        return chunks

    def test_reasoning_and_tools_in_stream(self):
        gen, pre = self._gen(tool_parser="hermes", reasoning_parser="think")
        text = ('<think>need weather</think>'
                '<tool_call>{"name": "w", "arguments": {}}</tool_call>')
        chunks = self._feed_text(gen, pre, text)
        reasoning = "".join(
            c["choices"][0]["delta"].get("reasoning_content", "")
            for c in chunks)
        tool_deltas = [c for c in chunks
                       if c["choices"][0]["delta"].get("tool_calls")]
        assert reasoning == "need weather"
        assert len(tool_deltas) == 1
        assert gen.finish_reason == "tool_calls"
        final = gen.final_response()
        msg = final["choices"][0]["message"]
        assert msg["tool_calls"][0]["function"]["name"] == "w"
        assert msg["reasoning_content"] == "need weather"
        assert final["choices"][0]["finish_reason"] == "tool_calls"

    def test_plain_stream_unchanged(self):
        gen, pre = self._gen()
        chunks = self._feed_text(gen, pre, "hello world")
        text = "".join(c["choices"][0]["delta"].get("content", "")
                       for c in chunks)
        assert text == "hello world"
        assert gen.finish_reason == "stop"


class TestXmlToolParser:
    def _drip(self, parser, text, n=7):
        ev_all = []
        for i in range(0, len(text), n):
            ev_all.append(parser.push(text[i:i + n]))
        ev_all.append(parser.finalize())
        content = "".join(e.content for e in ev_all)
        calls = [c for e in ev_all for c in e.calls]
        return content, calls

    def test_function_parameters(self):
        from dynamo_tpu.parsers.tool_calls import XmlToolParser

        text = ("let me check. <tool_call>\n<function=get_weather>\n"
                "<parameter=city>\nParis\n</parameter>\n"
                "<parameter=days>\n3\n</parameter>\n"
                "</function>\n</tool_call> done.")
        content, calls = self._drip(XmlToolParser(), text)
        assert "let me check." in content and "done." in content
        assert len(calls) == 1
        assert calls[0].name == "get_weather"
        args = json.loads(calls[0].arguments)
        assert args == {"city": "Paris", "days": 3}

    def test_malformed_block_passes_through(self):
        from dynamo_tpu.parsers.tool_calls import XmlToolParser

        text = "<tool_call>not a function block</tool_call>"
        content, calls = self._drip(XmlToolParser(), text)
        assert calls == []
        assert "not a function block" in content


class TestDsmlToolParser:
    def test_calls(self):
        from dynamo_tpu.parsers.tool_calls import DsmlToolParser

        text = ("ok <｜tool▁calls▁begin｜><｜tool▁call▁begin｜>function"
                "<｜tool▁sep｜>lookup\n```json\n{\"q\": \"x\"}\n```"
                "<｜tool▁call▁end｜><｜tool▁calls▁end｜>")
        parser = DsmlToolParser()
        events = [parser.push(text), parser.finalize()]
        calls = [c for e in events for c in e.calls]
        assert len(calls) == 1 and calls[0].name == "lookup"
        assert json.loads(calls[0].arguments) == {"q": "x"}
        assert "ok " in "".join(e.content for e in events)


class TestHarmonyParser:
    def test_tool_call_and_final_channel(self):
        from dynamo_tpu.parsers.tool_calls import HarmonyToolParser

        text = ("<|channel|>analysis<|message|>thinking...<|end|>"
                "<|channel|>commentary to=functions.get_time "
                "<|constrain|>json<|message|>{\"tz\": \"UTC\"}<|call|>"
                "<|channel|>final<|message|>It is noon.<|return|>")
        parser = HarmonyToolParser()
        events = []
        for i in range(0, len(text), 9):
            events.append(parser.push(text[i:i + 9]))
        events.append(parser.finalize())
        calls = [c for e in events for c in e.calls]
        content = "".join(e.content for e in events)
        assert len(calls) == 1 and calls[0].name == "get_time"
        assert json.loads(calls[0].arguments) == {"tz": "UTC"}
        assert content == "It is noon."

    def test_plain_text_passthrough(self):
        from dynamo_tpu.parsers.tool_calls import HarmonyToolParser

        parser = HarmonyToolParser()
        events = [parser.push("just plain text"), parser.finalize()]
        assert "".join(e.content for e in events) == "just plain text"

    def test_harmony_reasoning_parser(self):
        from dynamo_tpu.parsers import make_reasoning_parser

        parser = make_reasoning_parser("harmony")
        text = ("<|channel|>analysis<|message|>deep thought<|end|>"
                "<|channel|>final<|message|>answer")
        reasoning, content = "", ""
        for i in range(0, len(text), 8):
            ev = parser.push(text[i:i + 8])
            reasoning += ev.reasoning
            content += ev.content
        ev = parser.finalize()
        reasoning += ev.reasoning
        content += ev.content
        assert reasoning == "deep thought"
        assert "<|channel|>final<|message|>answer" in content


class TestHarmonyStreaming:
    def test_final_channel_streams_incrementally(self):
        """Visible text must stream as it arrives — jailing it until
        finalize would make streamed TTFT equal full generation time."""
        from dynamo_tpu.parsers.tool_calls import HarmonyToolParser

        parser = HarmonyToolParser()
        parser.push("<|channel|>final<|message|>")
        ev = parser.push("Hello, ")
        assert ev.content == "Hello, "  # streamed immediately
        ev = parser.push("world")
        assert ev.content == "world"
        ev = parser.push("<|return|>")
        assert ev.content == ""
        assert parser.finalize().content == ""

    def test_multiple_analysis_spans_all_surface_as_reasoning(self):
        from dynamo_tpu.parsers import make_reasoning_parser

        parser = make_reasoning_parser("harmony")
        text = ("<|channel|>analysis<|message|>first<|end|>"
                "<|channel|>commentary to=functions.f "
                "<|message|>{}<|call|>"
                "<|channel|>analysis<|message|>second<|end|>"
                "<|channel|>final<|message|>done<|return|>")
        reasoning = ""
        rest = ""
        for i in range(0, len(text), 11):
            ev = parser.push(text[i:i + 11])
            reasoning += ev.reasoning
            rest += ev.content
        ev = parser.finalize()
        reasoning += ev.reasoning
        rest += ev.content
        assert reasoning == "firstsecond"
        # non-analysis structure passes through for the tool parser
        assert "functions.f" in rest and "done" in rest

    def test_unterminated_final_body(self):
        from dynamo_tpu.parsers.tool_calls import HarmonyToolParser

        parser = HarmonyToolParser()
        ev1 = parser.push("<|channel|>final<|message|>cut off mid")
        ev2 = parser.finalize()
        assert ev1.content + ev2.content == "cut off mid"


class TestDsmlMalformedSibling:
    def test_broken_call_reemitted_not_dropped(self):
        from dynamo_tpu.parsers.tool_calls import DsmlToolParser

        text = ("<｜tool▁calls▁begin｜>"
                "<｜tool▁call▁begin｜>function<｜tool▁sep｜>good\n"
                "```json\n{\"a\": 1}\n```<｜tool▁call▁end｜>"
                "<｜tool▁call▁begin｜>function<｜tool▁sep｜>broken\n"
                "```json\n{\"b\": trunc")
        parser = DsmlToolParser()
        events = [parser.push(text), parser.finalize()]
        calls = [c for e in events for c in e.calls]
        content = "".join(e.content for e in events)
        assert [c.name for c in calls] == ["good"]
        assert "broken" in content  # visible, not vanished
